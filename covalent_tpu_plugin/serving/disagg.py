"""Disaggregated prefill/decode serving: a KV transfer plane over replicas.

Prefill is compute-bound (one big batched pass over the prompt), decode
is memory-bound (one tiny step per token, thousands of times); a replica
doing both lets long-prompt admissions head-of-line block every
interactive stream sharing its engine loop (the Gemma-on-TPU serving
study in PAPERS.md grounds the split's throughput/latency methodology).
A :class:`DisaggregatedSet` separates the phases across the replica set
it already is:

* **Prefill tier** — the first ``prefill_replicas`` members open on
  prefill-ranked pools (``PoolSpec.role == "prefill"``) and never
  receive router traffic.  A long-prompt request runs
  ``engine.prefill_only`` there: the admission prefill's exact
  computation, packaged as a serializable **KV bundle** (cache lane +
  cursor + first token + rng/sampling state).
* **KV transfer through the CAS** — the bundle is content-addressed
  (sha256) end to end: the worker announces its digest, the dispatcher
  re-hashes the received bytes before trusting them, and the decode
  worker verifies again before unpickling.  Transfer rides a raw binary
  frame body on the agent channel when the decode channel negotiated
  frames (the gang-local fast path), or a CAS put — digest-named,
  single-flighted, deduped across identical prompts — referenced by
  path across pools.
* **Decode tier** — the router (sticky > prefix-affinity > least-loaded,
  per-tenant DRR order, unchanged) places the request on a decode
  replica whose engine scatters the imported lane straight into a slot
  (``admit_from_kv``) and goes directly to token generation.  Greedy
  streams are bit-identical to the non-disaggregated path (oracle-
  asserted in ``tests/test_continuous.py``).
* **Degrade, never error** — a dead/slow prefill tier, a digest
  mismatch, a torn transfer, or an engine refusing the bundle all fall
  back to a full prefill on the decode replica; the caller's stream is
  byte-identical either way, only slower.  Short prompts
  (< ``min_prompt_tokens``) skip the KV road entirely.
* **Quantized lanes compose** — request ``params`` (the per-request
  ``quality`` knob included) ride the prefill round trip verbatim, so a
  ``quality="kv_quant"`` request prefills on the prefill tier's matching
  lane group and ships an **int8 KV bundle** (~2-4x fewer bytes).  The
  bundle carries a quantization fingerprint next to the sampling
  fingerprint; a decode replica with no matching lane group refuses it
  and the request degrades to a full prefill there — same
  byte-identical-stream contract as every other degrade road.

``COVALENT_TPU_SERVE_DISAGG=0`` routes everything direct (kill switch);
``COVALENT_TPU_SERVE_DISAGG_MIN_PROMPT`` / ``_KV_TIMEOUT_S`` /
``_PREFILL`` tune the classification threshold, the prefill round-trip
budget, and the default prefill-tier width.
"""

from __future__ import annotations

import asyncio
import collections
import hashlib
import os
import time
import uuid
from typing import Any

from ..cache import prune_cas_dir
from ..obs import events as obs_events
from ..obs.trace import context_of
from ..utils.log import app_log
from .metrics import (
    SERVE_DISAGG_REQUESTS_TOTAL,
    SERVE_KV_TRANSFER_BYTES_TOTAL,
    SERVE_KV_TRANSFER_SECONDS,
    SERVE_KV_TRANSFERS_TOTAL,
)
from .replicas import ReplicaSet
from .supervisor import (
    ServeError,
    ServeRequest,
    SessionSupervisor,
    _env_number,
)

__all__ = [
    "DisaggregatedSet",
    "open_disaggregated_set",
]


def _disagg_enabled() -> bool:
    return os.environ.get(
        "COVALENT_TPU_SERVE_DISAGG", ""
    ).strip().lower() not in ("0", "off", "false", "no")


def _prefix_key(prompt: list) -> str:
    """Router affinity key: digest of the prompt's reusable prefix (all
    but the last token — exactly the prefix a repeated prompt hits in
    the engine's tree)."""
    if len(prompt) < 2:
        return ""
    return hashlib.sha256(
        (",".join(str(int(t)) for t in prompt[:-1])).encode()
    ).hexdigest()


class DisaggregatedSet(ReplicaSet):
    """A :class:`~.replicas.ReplicaSet` split into prefill and decode
    tiers, connected by CAS-addressed KV bundles.

    Build through :func:`open_disaggregated_set`.  The request surface
    is the replica set's unchanged; classification (prompt length vs
    ``min_prompt_tokens``), the prefill round trip, digest verification,
    and the degrade-to-full-prefill policy all run inside
    :meth:`_prepare_request` before the router sees the request.
    """

    def __init__(
        self,
        targets: list[Any],
        factory: Any,
        *,
        decode_replicas: int | None = None,
        prefill_replicas: int | None = None,
        min_prompt_tokens: int | None = None,
        kv_timeout_s: float | None = None,
        **set_options: Any,
    ) -> None:
        self.prefill_replicas = int(
            prefill_replicas
            if prefill_replicas is not None
            else _env_number("COVALENT_TPU_SERVE_DISAGG_PREFILL", 1, int)
        )
        if self.prefill_replicas < 1:
            raise ValueError(
                f"prefill_replicas must be >= 1, got {self.prefill_replicas}"
            )
        decode = int(
            decode_replicas
            if decode_replicas is not None
            else max(1, len(targets) - self.prefill_replicas)
        )
        if decode < 1:
            raise ValueError(f"decode_replicas must be >= 1, got {decode}")
        self.decode_replicas = decode
        self.min_prompt_tokens = int(
            min_prompt_tokens
            if min_prompt_tokens is not None
            else _env_number(
                "COVALENT_TPU_SERVE_DISAGG_MIN_PROMPT", 64, int
            )
        )
        self.kv_timeout_s = float(
            kv_timeout_s
            if kv_timeout_s is not None
            else _env_number("COVALENT_TPU_SERVE_DISAGG_KV_TIMEOUT_S", 30.0)
        )
        self.enabled = _disagg_enabled()
        #: replica id -> "prefill" | "decode".
        self._role_of: dict[str, str] = {}
        self._opening_role = ""
        #: prefill-role opens currently in flight (role is assigned by
        #: tier DEFICIT, not by replica index: a failed initial open
        #: must not permanently lose the prefill tier — the next open,
        #: scale-up included, re-fills it).
        self._prefill_opening = 0
        #: prefill work currently in flight per prefill replica id.
        self._prefill_load: collections.Counter = collections.Counter()
        #: bench-readable transfer accounting (the metrics' raw feed).
        self.kv_bytes_total = 0
        self.kv_transfer_s: collections.deque = collections.deque(
            maxlen=4096
        )
        self.requests_by_path: collections.Counter = collections.Counter()
        super().__init__(
            targets, factory,
            replicas=decode + self.prefill_replicas,
            **set_options,
        )

    # -- placement (role-aware) --------------------------------------------

    def _rank_targets(self) -> list[tuple[Any, Any]]:
        """Base affinity/warmth/spread ranking, re-sorted so targets
        whose pool declared the tier's role come first and opposite-role
        pools last (role-less pools stay neutral)."""
        ranked = super()._rank_targets()
        role = self._opening_role
        if not role:
            return ranked

        def mismatch(entry: tuple[Any, Any]) -> int:
            executor, pool = entry
            target_role = ""
            if pool is not None:
                target_role = str(
                    getattr(getattr(pool, "spec", None), "role", "") or ""
                )
            if not target_role:
                target_role = str(getattr(executor, "serve_role", "") or "")
            if not target_role:
                return 1
            return 0 if target_role == role else 2

        return sorted(ranked, key=mismatch)  # stable within classes

    async def _open_replica(self) -> SessionSupervisor:
        have = self._prefill_opening + sum(
            1 for rid, sup in self._replicas.items()
            if self._role_of.get(rid) == "prefill" and sup.alive
        )
        role = "prefill" if have < self.prefill_replicas else "decode"
        self._opening_role = role
        if role == "prefill":
            self._prefill_opening += 1
        try:
            supervisor = await super()._open_replica()
        finally:
            self._opening_role = ""
            if role == "prefill":
                self._prefill_opening -= 1
        if supervisor.replica_of is not None:
            self._role_of[supervisor.replica_of[1]] = role
        return supervisor

    def _views(self):
        """Router world view: decode replicas only — the prefill tier
        never receives routed decode work."""
        views = super()._views()
        return {
            rid: view for rid, view in views.items()
            if self._role_of.get(rid, "decode") == "decode"
        }

    def _decode_alive(self) -> bool:
        return any(
            sup.alive
            for rid, sup in self._replicas.items()
            if self._role_of.get(rid, "decode") == "decode"
        )

    # -- classification + prefill tier -------------------------------------

    async def request(
        self,
        prompt,
        params: dict | None = None,
        deadline_s: float | None = None,
        tenant: str = "",
        sticky: str = "",
    ) -> ServeRequest:
        if not self._closed and not self._decode_alive():
            raise ServeError(
                f"disaggregated set {self.name} has no live decode replicas"
            )
        return await super().request(
            prompt, params, deadline_s=deadline_s, tenant=tenant,
            sticky=sticky,
        )

    async def _prepare_request(self, request: ServeRequest) -> None:
        """Classify, prefill on the prefill tier, attach the KV bundle.

        Runs BEFORE the router pump, so a disaggregated request reaches
        the decode tier with its prefill already done (and its
        prefix-affinity key set).  Every failure mode lands in the same
        place: ``request.kv`` stays None and the decode replica runs the
        full prefill — never a user-visible error.
        """
        request.prefix_key = _prefix_key(request.prompt)
        if (
            not self.enabled
            or len(request.prompt) < self.min_prompt_tokens
        ):
            self.requests_by_path["direct"] += 1
            SERVE_DISAGG_REQUESTS_TOTAL.labels(path="direct").inc()
            return
        kv = await self._prefill_kv_for(request)
        # Checkpoint even on a failed round trip: the time was spent
        # either way, and the waterfall must attribute it to the prefill
        # hop rather than silently folding it into the route segment.
        request.t_prefill_done = time.monotonic()
        path = "disagg" if kv is not None else "fallback"
        self.requests_by_path[path] += 1
        SERVE_DISAGG_REQUESTS_TOTAL.labels(path=path).inc()
        request.kv = kv

    def _prefill_supervisor(self) -> tuple[str, SessionSupervisor] | None:
        candidates = [
            (rid, sup)
            for rid, sup in self._replicas.items()
            if self._role_of.get(rid) == "prefill" and sup.routable
        ]
        if not candidates:
            return None
        return min(
            candidates, key=lambda entry: self._prefill_load[entry[0]]
        )

    async def _prefill_kv_for(
        self, request: ServeRequest
    ) -> tuple[bytes, str] | None:
        """One prefill-tier round trip: returns ``(bundle, digest)`` or
        None after any failure (counted, evented, degraded)."""
        picked = self._prefill_supervisor()
        if picked is None:
            SERVE_KV_TRANSFERS_TOTAL.labels(outcome="fallback").inc()
            return None
        replica_id, supervisor = picked
        self._prefill_load[replica_id] += 1
        t0 = time.perf_counter()
        try:
            # Outer bound on the WHOLE round trip: prefill_kv's own
            # timeout only covers the serve_kv wait, while a replica
            # caught mid-reconnect blocks in _await_ready — a caller's
            # request must degrade on the KV budget, not wait out a
            # reconnect cycle.
            event = await asyncio.wait_for(
                supervisor.prefill_kv(
                    request.prompt, request.params,
                    rid=f"{request.rid}-kv{uuid.uuid4().hex[:6]}",
                    timeout_s=self.kv_timeout_s,
                    trace=context_of(request.span, rid=request.rid),
                ),
                self.kv_timeout_s + 5.0,
            )
        except Exception as err:  # noqa: BLE001 - degrade, never error
            SERVE_KV_TRANSFERS_TOTAL.labels(outcome="error").inc()
            obs_events.emit(
                "serve.kv_prefill_failed",
                set=self.name,
                replica=replica_id,
                rid=request.rid,
                error=repr(err),
            )
            app_log.debug(
                "disagg %s: prefill for %s failed on %s (%s); degrading "
                "to full prefill", self.name, request.rid, replica_id, err,
            )
            return None
        finally:
            self._prefill_load[replica_id] -= 1
        data = event.get("data_bytes")
        if not isinstance(data, (bytes, bytearray)) or not data:
            SERVE_KV_TRANSFERS_TOTAL.labels(outcome="error").inc()
            return None
        data = bytes(data)
        digest = hashlib.sha256(data).hexdigest()
        announced = str(event.get("digest") or "")
        if announced and digest != announced:
            # The wire (or the worker) handed us bytes that do not match
            # what the prefill engine hashed: a torn transfer.  The
            # decode replica re-prefills from the prompt — correctness
            # never rides an unverified bundle.
            SERVE_KV_TRANSFERS_TOTAL.labels(
                outcome="digest_mismatch"
            ).inc()
            obs_events.emit(
                "serve.kv_digest_mismatch",
                set=self.name,
                replica=replica_id,
                rid=request.rid,
                announced=announced[:12],
                received=digest[:12],
            )
            return None
        elapsed = time.perf_counter() - t0
        SERVE_KV_TRANSFERS_TOTAL.labels(outcome="ok").inc()
        SERVE_KV_TRANSFER_BYTES_TOTAL.inc(len(data))
        SERVE_KV_TRANSFER_SECONDS.observe(elapsed)
        self.kv_bytes_total += len(data)
        self.kv_transfer_s.append(elapsed)
        # Off the request path: the mirror is an audit/staging artifact
        # (the frames road never reads it back), so a multi-MB disk
        # write must not tax this request's TTFT.
        mirror = asyncio.ensure_future(asyncio.to_thread(
            self._mirror_to_cas, supervisor, data, digest
        ))
        mirror.add_done_callback(
            lambda t: None if t.cancelled() else t.exception()
        )
        return data, digest

    @staticmethod
    def _mirror_to_cas(
        supervisor: SessionSupervisor, data: bytes, digest: str
    ) -> None:
        """Content-addressed local CAS copy of every verified bundle (the
        artifact the cross-pool staging road ships from), byte-bounded by
        the executor's ``cas_max_bytes`` LRU prune."""
        try:
            root = os.path.join(supervisor.executor.cache_dir, "cas")
            os.makedirs(root, exist_ok=True)
            path = os.path.join(root, f"{digest}.kv")
            if not os.path.exists(path):
                tmp = f"{path}.tmp.{os.getpid()}.{os.urandom(4).hex()}"
                with open(tmp, "wb") as f:
                    f.write(data)
                os.replace(tmp, path)
            budget = int(
                getattr(supervisor.executor, "cas_max_bytes", 0) or 0
            )
            if budget > 0:
                prune_cas_dir(root, budget)
        except OSError as err:
            app_log.debug("KV CAS mirror write failed: %s", err)

    # -- health / scaling (decode-tier aware) -------------------------------

    def _on_replica_failed(
        self, supervisor: SessionSupervisor, failure: BaseException
    ) -> bool:
        handled = super()._on_replica_failed(supervisor, failure)
        if not self._decode_alive():
            # The base class drains the router queue only when EVERY
            # replica is gone; a live prefill tier with a dead decode
            # tier would otherwise leave queued requests hanging on a
            # pump that can never place them.
            for item in self.router.drain():
                request = item.task_metadata.get("request")
                if request is not None and not request.done:
                    request._fail(ServeError(
                        f"disaggregated set {self.name} has no live "
                        f"decode replicas: {failure}"
                    ))
        return handled

    async def scale_to(self, replicas: int) -> int:
        """Scale the DECODE tier to ``replicas`` members (the prefill
        tier stays at its configured width); returns the live decode
        count."""
        if self._closed:
            raise ServeError(f"replica set {self.name} is closed")
        replicas = int(replicas)
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        live = {
            rid: sup for rid, sup in self._replicas.items()
            if sup.alive and self._role_of.get(rid, "decode") == "decode"
        }
        if replicas > len(live):
            grow = replicas - len(live)
            results = await asyncio.gather(
                *(self._open_replica() for _ in range(grow)),
                return_exceptions=True,
            )
            for failure in results:
                if isinstance(failure, BaseException):
                    app_log.warning(
                        "disagg set %s scale-up open failed: %r",
                        self.name, failure,
                    )
            self._schedule_pump()
        elif replicas < len(live):
            victims = sorted(
                live, key=lambda rid: live[rid].in_flight
            )[: len(live) - replicas]
            for rid in victims:
                await self._retire_replica(rid)
        self.replicas_wanted = self.prefill_replicas + replicas
        self._publish_replica_states()
        decode_live = len([
            rid for rid, sup in self._replicas.items()
            if sup.alive and self._role_of.get(rid, "decode") == "decode"
        ])
        obs_events.emit(
            "serve.replica_set_scaled",
            set=self.name,
            replicas=decode_live,
        )
        return decode_live

    # -- views --------------------------------------------------------------

    def status(self) -> dict[str, Any]:
        view = super().status()
        transfers = sorted(self.kv_transfer_s)
        view["roles"] = dict(self._role_of)
        view["min_prompt_tokens"] = self.min_prompt_tokens
        view["disagg_enabled"] = self.enabled
        view["requests_by_path"] = dict(self.requests_by_path)
        view["kv_bytes_total"] = self.kv_bytes_total
        view["kv_transfer_p50_ms"] = round(
            (transfers[len(transfers) // 2] if transfers else 0.0) * 1e3,
            4,
        )
        return view


async def open_disaggregated_set(
    targets: Any,
    factory: Any,
    *,
    decode_replicas: int | None = None,
    prefill_replicas: int | None = None,
    min_prompt_tokens: int | None = None,
    kv_timeout_s: float | None = None,
    name: str = "",
    sticky_ttl_s: float | None = None,
    router_queue_max: int | None = None,
    tenant_weights: dict[str, float] | None = None,
    **session_options: Any,
) -> DisaggregatedSet:
    """Open a prefill tier + a decode tier of one engine factory behind
    the replica-set router, connected by CAS-addressed KV bundles.

    ``targets`` is the same pool/executor list ``open_replica_set``
    takes; placement prefers pools whose spec declares the matching
    ``role`` (``"prefill"`` / ``"decode"``), then falls back to the
    affinity/warmth ranking.  ``decode_replicas`` defaults to
    ``len(targets) - prefill_replicas``; prompts shorter than
    ``min_prompt_tokens`` bypass the prefill tier entirely.
    """
    if not isinstance(targets, (list, tuple)):
        targets = [targets]
    disagg = DisaggregatedSet(
        list(targets),
        factory,
        decode_replicas=decode_replicas,
        prefill_replicas=prefill_replicas,
        min_prompt_tokens=min_prompt_tokens,
        kv_timeout_s=kv_timeout_s,
        name=name,
        sticky_ttl_s=sticky_ttl_s,
        router_queue_max=router_queue_max,
        tenant_weights=tenant_weights,
        **session_options,
    )
    await disagg._open()
    return disagg
