"""Persistent serving tier: resident model servers with streaming RPCs.

Load + compile once inside a warm gang, then serve request-level RPCs
over the held-open agent channel for the session's whole lifetime — the
dispatch plane's answer to interactive traffic (ROADMAP item 2).

* :func:`open_session` — ship a model factory by CAS digest, open the
  session, get a :class:`ServeHandle` back.
* :class:`ServeHandle` — multiplex concurrent callers onto the session;
  tokens stream back incrementally; channel death reconnects and
  replays with exactly-once token delivery.
* ``models/serve.ContinuousEngine`` — the in-worker continuous-batching
  engine the worker harness drives (``slots``/``admit``/``step``).
"""

from .handle import (
    ServeError,
    ServeHandle,
    ServeRequest,
    ServeRequestRejected,
    open_session,
)
from .metrics import (
    SERVE_QUEUE_DEPTH,
    SERVE_RECONNECTS_TOTAL,
    SERVE_REQUEST_SECONDS,
    SERVE_REQUESTS_TOTAL,
    SERVE_SESSIONS,
    SERVE_TOKENS_PER_S,
    SERVE_TOKENS_TOTAL,
    SERVE_TTFT_SECONDS,
    SERVE_WORKER_SLOTS,
)

__all__ = [
    "ServeError",
    "ServeHandle",
    "ServeRequest",
    "ServeRequestRejected",
    "open_session",
    "SERVE_QUEUE_DEPTH",
    "SERVE_RECONNECTS_TOTAL",
    "SERVE_REQUEST_SECONDS",
    "SERVE_REQUESTS_TOTAL",
    "SERVE_SESSIONS",
    "SERVE_TOKENS_PER_S",
    "SERVE_TOKENS_TOTAL",
    "SERVE_TTFT_SECONDS",
    "SERVE_WORKER_SLOTS",
]
