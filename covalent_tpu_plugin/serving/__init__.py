"""Persistent serving tier: resident model servers with streaming RPCs.

Load + compile once inside a warm gang, then serve request-level RPCs
over the held-open agent channel for the session's whole lifetime — the
dispatch plane's answer to interactive traffic (ROADMAP item 2).

* :func:`open_session` — ship a model factory by CAS digest, open ONE
  session, get a :class:`ServeHandle` back.
* :func:`open_replica_set` — open N sessions of the same factory across
  fleet pools behind a session-aware router (:class:`ReplicaSet`):
  least-loaded placement with per-tenant DRR fairness, sticky session
  ids, per-replica health with drain-on-death onto survivors.
* :func:`open_disaggregated_set` — split the set into a prefill tier
  and a decode tier connected by CAS-addressed KV bundles
  (:class:`DisaggregatedSet`): long prompts prefill on dedicated
  replicas, ship their KV through the CAS with digest verification,
  and decode replicas admit straight from KV — degrading to a full
  prefill on any failure, never a user-visible error.
* :class:`~.supervisor.SessionSupervisor` — one supervised session:
  reconnect after channel death, exactly-once ``idx``-spliced stream
  replay; both fronts share it, so neither duplicates replay machinery.
* ``models/serve.ContinuousEngine`` — the in-worker continuous-batching
  engine the worker harness drives (``slots``/``admit``/``step``), with
  shared-prefix prefill reuse for common system prompts.
"""

from .disagg import DisaggregatedSet, open_disaggregated_set
from .handle import (
    ServeError,
    ServeHandle,
    ServeRequest,
    ServeRequestRejected,
    open_session,
)
from .metrics import (
    SERVE_DISAGG_REQUESTS_TOTAL,
    SERVE_KV_TRANSFER_BYTES_TOTAL,
    SERVE_KV_TRANSFER_SECONDS,
    SERVE_KV_TRANSFERS_TOTAL,
    SERVE_PREFILL_POSITIONS,
    SERVE_PREFIX_HITS,
    SERVE_PREFIX_MISSES,
    SERVE_QUEUE_DEPTH,
    SERVE_RECONNECTS_TOTAL,
    SERVE_REPLICA_IN_FLIGHT,
    SERVE_REPLICA_REQUESTS_TOTAL,
    SERVE_REPLICAS,
    SERVE_REQUEST_SECONDS,
    SERVE_REQUESTS_TOTAL,
    SERVE_ROUTER_DECISION_SECONDS,
    SERVE_ROUTER_DECISIONS_TOTAL,
    SERVE_SESSIONS,
    SERVE_TOKENS_PER_S,
    SERVE_TOKENS_TOTAL,
    SERVE_TTFT_SECONDS,
    SERVE_WORKER_SLOTS,
)
from .registry import (
    AdapterRegistry,
    adapter_content_digest,
    pack_adapter,
    unpack_adapter,
)
from .replicas import (
    ReplicaRouter,
    ReplicaSet,
    ReplicaView,
    open_replica_set,
)
from .supervisor import SessionSupervisor

__all__ = [
    "AdapterRegistry",
    "DisaggregatedSet",
    "adapter_content_digest",
    "pack_adapter",
    "unpack_adapter",
    "ServeError",
    "ServeHandle",
    "ServeRequest",
    "ServeRequestRejected",
    "SessionSupervisor",
    "ReplicaRouter",
    "ReplicaSet",
    "ReplicaView",
    "open_session",
    "open_replica_set",
    "open_disaggregated_set",
    "SERVE_DISAGG_REQUESTS_TOTAL",
    "SERVE_KV_TRANSFER_BYTES_TOTAL",
    "SERVE_KV_TRANSFER_SECONDS",
    "SERVE_KV_TRANSFERS_TOTAL",
    "SERVE_PREFILL_POSITIONS",
    "SERVE_PREFIX_HITS",
    "SERVE_PREFIX_MISSES",
    "SERVE_QUEUE_DEPTH",
    "SERVE_RECONNECTS_TOTAL",
    "SERVE_REPLICA_IN_FLIGHT",
    "SERVE_REPLICA_REQUESTS_TOTAL",
    "SERVE_REPLICAS",
    "SERVE_REQUEST_SECONDS",
    "SERVE_REQUESTS_TOTAL",
    "SERVE_ROUTER_DECISION_SECONDS",
    "SERVE_ROUTER_DECISIONS_TOTAL",
    "SERVE_SESSIONS",
    "SERVE_TOKENS_PER_S",
    "SERVE_TOKENS_TOTAL",
    "SERVE_TTFT_SECONDS",
    "SERVE_WORKER_SLOTS",
]
