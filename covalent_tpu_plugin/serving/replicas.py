"""Horizontally scaled serving: N replica sessions behind one handle.

One resident session's throughput ceiling is one engine's slot count; a
:class:`ReplicaSet` raises it by opening N sessions of the SAME engine
factory across fleet pools and fronting them with a session-aware
router.  Each replica is one :class:`~.supervisor.SessionSupervisor` —
the exact reconnect/exactly-once-replay machinery a single
:class:`~.handle.ServeHandle` runs — so horizontal scale adds no new
failure semantics, only placement:

* **Least-loaded placement, DRR tie-break.**  Every request passes
  through a per-tenant :class:`~..fleet.queue.FairWorkQueue` (the fleet
  scheduler's deficit-round-robin, reused verbatim): under contention
  the DRR decides *whose* request dispatches next, and the least-loaded
  open replica receives it (rotation breaks exact load ties).  With
  free capacity the queue is pass-through — submit, pop, place — so the
  uncontended path stays a dict lookup and a compare, not a scheduler.
* **Sticky session ids.**  ``request(..., sticky="user-42")`` pins a
  multi-turn caller to one replica (engine-side prefix caches are
  per-replica), refreshed on use and expired after ``sticky_ttl_s``.  A
  pin survives its replica's reconnect (the supervisor keeps the
  replica's identity across generations); only a replica death past its
  retry budget re-pins.
* **Per-replica health + drain-on-death.**  New requests only route to
  ``open`` replicas; a reconnecting replica's backlog waits for it
  (sticky) or flows to survivors (unpinned).  A replica that dies past
  its retry budget hands its in-flight requests back
  (``detach_requests``) and the router re-routes them onto survivors —
  the requests' own token high-water marks make the cross-replica
  replay exactly-once, the same ``idx`` splice a same-replica reconnect
  uses.
* **Warm-up affinity.**  Replica placement prefers pools already
  holding the factory's CAS digest (zero re-staging), then warm gangs,
  then free capacity — the serving analog of the scheduler's fn-digest
  affinity.

``open_replica_set(targets, factory, replicas=1)`` with one target
degenerates to today's single-session behavior (one supervisor, pass-
through router); ``open_session`` remains the unchanged one-session API.
"""

from __future__ import annotations

import asyncio
import collections
import os
import time
import uuid
from typing import Any, Callable

import cloudpickle

from ..cache import bytes_digest
from ..fleet import journal as journal_mod
from ..fleet.health import DEGRADED, HEALTH, PROBING, QUARANTINED
from ..fleet.queue import DEFAULT_TENANT, FairWorkQueue, QueueFullError, WorkItem
from ..obs import events as obs_events
from ..obs.trace import Span, record_span
from ..utils.log import app_log
from .metrics import (
    SERVE_HEDGES_TOTAL,
    SERVE_REPLICAS,
    SERVE_ROUTER_DECISION_SECONDS,
    SERVE_ROUTER_DECISIONS_TOTAL,
    SERVE_ROUTER_QUEUE_DEPTH,
)
from .supervisor import (
    ServeError,
    ServeRequest,
    ServeRequestRejected,
    SessionSupervisor,
)

__all__ = [
    "ReplicaView",
    "ReplicaRouter",
    "ReplicaSet",
    "open_replica_set",
]

#: Router states a replica-set member can be in (the SERVE_REPLICAS
#: gauge's closed label set).
_REPLICA_STATES = ("open", "reconnecting", "failed", "closed")


class ReplicaView:
    """One replica's routing-relevant shape: id, health, load, capacity.

    Deliberately tiny and data-only so the router is unit-testable with
    fake fleets and a fake clock — no supervisor, no I/O.
    """

    __slots__ = (
        "rid", "open", "alive", "load", "capacity", "health",
        "degraded", "quarantined",
    )

    def __init__(
        self, rid: str, *, open: bool, load: int, capacity: int,
        alive: bool | None = None, health: float = 1.0,
        degraded: bool = False, quarantined: bool = False,
    ) -> None:
        self.rid = rid
        self.open = bool(open)
        #: open OR recovering: a sticky pin to this replica still holds.
        self.alive = bool(open if alive is None else alive)
        self.load = int(load)
        self.capacity = max(1, int(capacity))
        #: continuous health score in [0, 1] (fleet.health).
        self.health = float(health)
        #: gray-degraded: routable as LAST RESORT only — a healthy
        #: replica with headroom always wins over it.
        self.degraded = bool(degraded)
        #: quarantined: receives NO new traffic; sticky pins drain off it
        #: (re-pin on next use) and only a canary probe readmits it.
        self.quarantined = bool(quarantined)


class ReplicaRouter:
    """Session-aware request router over a set of replica views.

    Synchronous and clock-injectable: :meth:`submit` admits one request
    item (bounded — a full queue sheds, the same capacity verdict the
    worker-side admission queue renders), :meth:`pump` drains the DRR
    queue onto whatever open replicas have headroom and returns the
    ``(item, replica_id, outcome)`` assignments.  The caller (the
    replica set) performs the actual submissions and re-pumps on every
    completion or health transition.

    Sticky semantics: a pinned item only ever places on its pinned
    replica while that replica is *alive* (open or reconnecting) —
    waiting out a reconnect rather than abandoning the replica's warm
    state — and re-pins to a fresh least-loaded choice once the replica
    is gone.  Pins expire ``sticky_ttl_s`` after their last use.
    """

    def __init__(
        self,
        *,
        weights: dict[str, float] | None = None,
        sticky_ttl_s: float = 300.0,
        queue_max: int = 0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self._clock = clock
        self.sticky_ttl_s = float(sticky_ttl_s)
        self._queue = FairWorkQueue(
            max_depth=queue_max, policy="reject",
            weights=weights, clock=clock,
            # The router's backlog moves its OWN gauge, never the fleet
            # scheduler's (two queues on one series would fight).
            depth_gauge=SERVE_ROUTER_QUEUE_DEPTH,
        )
        #: sticky key -> [replica_id, last_used] (TTL-expired lazily).
        self._sticky: dict[str, list] = {}
        #: prefix key -> replica id that last served a request sharing
        #: that prompt prefix (bounded FIFO): requests carrying the same
        #: key steer to the replica whose engine-side prefix tree is
        #: already warm for it.  A *preference*, never a pin — sticky
        #: sids rank above it, and it only engages when the remembered
        #: replica is open with headroom, so DRR fairness (which decides
        #: WHOSE request pops) is untouched.
        self._prefix_sites: "collections.OrderedDict[str, str]" = (
            collections.OrderedDict()
        )
        self._prefix_sites_max = 1024
        #: adapter name -> replica ids whose engine holds that adapter
        #: resident.  Unlike prefix affinity this is a CONSTRAINT when
        #: known: a replica without the adapter refuses the request
        #: outright, so placement restricts to residents (and defers
        #: when no resident has headroom) rather than merely preferring
        #: them.  An adapter the router has no sites for places
        #: unconstrained — the attach-to-all default, or a caller
        #: naming an unknown adapter (the worker's clean refusal is the
        #: right answer there, not a router stall).
        self._adapter_sites: dict[str, set[str]] = {}
        #: rotation cursor for exact load ties, so equal replicas share.
        self._rr = 0

    # -- introspection ------------------------------------------------------

    @property
    def queued(self) -> int:
        return len(self._queue)

    def backlog(self) -> dict[str, int]:
        return self._queue.backlog()

    def sticky_count(self) -> int:
        self._expire_sticky()
        return len(self._sticky)

    def sticky_target(self, key: str) -> str | None:
        """The live pin for ``key`` (refreshes nothing; expires lazily)."""
        entry = self._sticky.get(key)
        if entry is None:
            return None
        if self._clock() - entry[1] > self.sticky_ttl_s:
            del self._sticky[key]
            return None
        return entry[0]

    def _expire_sticky(self) -> None:
        now = self._clock()
        for key in [
            k for k, (_, used) in self._sticky.items()
            if now - used > self.sticky_ttl_s
        ]:
            del self._sticky[key]

    def pin(self, key: str, replica_id: str) -> None:
        self._sticky[key] = [replica_id, self._clock()]

    def set_queue_max(self, depth: int) -> None:
        """Resize the admission bound (the set does this once replica
        capacity is known; 0 = unbounded)."""
        self._queue.max_depth = max(0, int(depth))

    def forget_replica(self, replica_id: str) -> None:
        """Drop every pin to a retired replica (its pins re-place)."""
        for key in [
            k for k, (rid, _) in self._sticky.items() if rid == replica_id
        ]:
            del self._sticky[key]
        for key in [
            k for k, rid in self._prefix_sites.items()
            if rid == replica_id
        ]:
            del self._prefix_sites[key]
        for name in list(self._adapter_sites):
            self._adapter_sites[name].discard(replica_id)
            if not self._adapter_sites[name]:
                del self._adapter_sites[name]

    def record_prefix_site(self, prefix_key: str, replica_id: str) -> None:
        """Remember which replica last warmed ``prefix_key`` (bounded)."""
        if not prefix_key:
            return
        self._prefix_sites[prefix_key] = replica_id
        self._prefix_sites.move_to_end(prefix_key)
        while len(self._prefix_sites) > self._prefix_sites_max:
            self._prefix_sites.popitem(last=False)

    def prefix_site(self, prefix_key: str) -> str | None:
        return self._prefix_sites.get(prefix_key)

    def record_adapter_site(self, adapter: str, replica_id: str) -> None:
        """Mark ``replica_id``'s engine as holding ``adapter`` resident."""
        if adapter:
            self._adapter_sites.setdefault(adapter, set()).add(replica_id)

    def drop_adapter_site(
        self, adapter: str, replica_id: str | None = None
    ) -> None:
        """Forget residency — one replica's, or (default) everywhere."""
        if replica_id is None:
            self._adapter_sites.pop(adapter, None)
            return
        sites = self._adapter_sites.get(adapter)
        if sites is not None:
            sites.discard(replica_id)
            if not sites:
                del self._adapter_sites[adapter]

    def adapter_sites(self, adapter: str) -> set[str]:
        return set(self._adapter_sites.get(adapter) or ())

    # -- admission + placement ----------------------------------------------

    def submit(self, item: WorkItem) -> None:
        """Admit one request item; raises :class:`QueueFullError` at the
        bound (the caller sheds it as ``serve_admission_shed``)."""
        self._queue.put(item)

    def remove(self, predicate) -> list[WorkItem]:
        return self._queue.remove(predicate)

    def drain(self) -> list[WorkItem]:
        return self._queue.drain()

    def pump(
        self, views: dict[str, ReplicaView]
    ) -> list[tuple[WorkItem, str, str]]:
        """Assign queued items to replicas with headroom, DRR-fairly.

        Pops at most the current depth (one DRR visit per queued item per
        pump): an item whose target has no headroom — or whose sticky
        replica is mid-reconnect — requeues with its original enqueue
        stamp, so fairness age and ``queued`` accounting survive the
        deferral.  Returns ``(item, replica_id, outcome)`` per placement,
        ``outcome`` in ``{"sticky", "prefix_affinity", "least_loaded"}``.
        """
        # Quarantined replicas get NO new traffic: they are excluded from
        # headroom entirely (the canary probe path is their only road
        # back), so every placement rule below — sticky, prefix, least-
        # loaded — routes around them by construction.
        headroom = {
            rid: view.capacity - view.load
            for rid, view in views.items()
            if view.open and not view.quarantined
        }
        assigned: list[tuple[WorkItem, str, str]] = []
        if not headroom:
            return assigned
        deferred: list[WorkItem] = []
        for _ in range(len(self._queue)):
            if not any(free > 0 for free in headroom.values()):
                # Out of lanes: STOP popping.  Draining the rest just to
                # requeue it would reset the DRR lanes' deficit state
                # every pump and hand the head tenant the whole trickle.
                break
            item = self._queue.pop()
            if item is None:
                break
            sticky = str(item.task_metadata.get("sticky") or "")
            prefix_key = str(item.task_metadata.get("prefix_key") or "")
            adapter = str(item.task_metadata.get("adapter") or "")
            # Residency constraint: when the router KNOWS where this
            # request's adapter lives, only those replicas are eligible
            # — anywhere else refuses it outright (unknown_adapter).
            sites = self._adapter_sites.get(adapter) if adapter else None
            constrained = bool(sites)

            def _eligible(rid: str) -> bool:
                return not constrained or rid in sites

            target = None
            outcome = "least_loaded"
            if sticky:
                pinned = self.sticky_target(sticky)
                if pinned is not None:
                    view = views.get(pinned)
                    if (
                        view is not None and view.alive
                        and not view.quarantined
                        # A pin at a replica WITHOUT the adapter falls
                        # through to a fresh (resident) placement and
                        # re-pins there: waiting on the pinned replica
                        # would wait for a refusal.
                        and _eligible(pinned)
                    ):
                        if headroom.get(pinned, 0) > 0:
                            target, outcome = pinned, "sticky"
                        else:
                            # Pinned replica full or reconnecting: wait
                            # for IT (warm per-replica state is the whole
                            # point of the pin) instead of re-placing.
                            deferred.append(item)
                            continue
                    # else: the pin points at a dead OR quarantined
                    # replica — fall through to a fresh placement and
                    # re-pin below (the sticky drain: a browned-out
                    # replica's pinned sessions move off it rather than
                    # waiting out a reconnect that never comes).
            if target is None and prefix_key:
                # Prefix affinity ranks BELOW sticky and above
                # least-loaded, and unlike a pin it never defers: a warm
                # prefix tree is worth steering toward, not waiting on.
                site = self.prefix_site(prefix_key)
                if (
                    site is not None and headroom.get(site, 0) > 0
                    and _eligible(site)
                ):
                    view = views.get(site)
                    if view is not None and view.open:
                        target, outcome = site, "prefix_affinity"
            if target is None:
                pool = (
                    {
                        rid: free for rid, free in headroom.items()
                        if rid in sites
                    }
                    if constrained else headroom
                )
                target = self._least_loaded(views, pool)
                if target is None:
                    # Constrained and no resident lane free: wait for
                    # one (the adapter IS attached somewhere) rather
                    # than burning the request on a certain refusal.
                    deferred.append(item)
                    continue
                if constrained:
                    outcome = "adapter_affinity"
                if sticky:
                    self.pin(sticky, target)
            if outcome == "sticky":
                # Refresh the pin's TTL on use: a multi-turn caller stays
                # put as long as its turns keep landing.
                self.pin(sticky, target)
            if prefix_key:
                self.record_prefix_site(prefix_key, target)
            headroom[target] -= 1
            assigned.append((item, target, outcome))
        for item in deferred:
            # enqueued_at survives a requeue (FairWorkQueue keeps the
            # first stamp), so deferral never resets fairness age.
            self._queue.put(item)
        return assigned

    def _least_loaded(
        self, views: dict[str, ReplicaView], headroom: dict[str, int]
    ) -> str | None:
        """The open replica with the most free lanes (ties rotate).

        Health-aware: gray-degraded replicas are LAST-RESORT — they only
        receive work when no healthy replica has headroom.  Routing a
        request to a 10x-slower replica because it happens to be least
        loaded is exactly the tail-latency trap this avoids.
        """
        candidates = [
            rid for rid, free in headroom.items() if free > 0
        ]
        if not candidates:
            return None
        healthy = [rid for rid in candidates if not views[rid].degraded]
        pool = healthy or candidates
        # Effective load folds in this pump's own assignments (headroom
        # already decremented), so one burst spreads instead of piling
        # onto the momentarily-least-loaded replica.
        best = min(
            views[rid].capacity - headroom[rid] for rid in pool
        )
        tied = [
            rid for rid in pool
            if views[rid].capacity - headroom[rid] == best
        ]
        self._rr += 1
        return tied[self._rr % len(tied)]


class ReplicaSet:
    """N supervised serving sessions of one engine factory, one front.

    Build through :func:`open_replica_set`.  The request surface mirrors
    :class:`~.handle.ServeHandle.request` plus ``sticky=`` (the
    multi-turn session id); streams, results, deadlines, rejection
    classification, and exactly-once delivery are all the supervisor's —
    identical to the single-session tier.
    """

    def __init__(
        self,
        targets: list[Any],
        factory: Any,
        *,
        replicas: int | None = None,
        name: str = "",
        sticky_ttl_s: float | None = None,
        router_queue_max: int | None = None,
        tenant_weights: dict[str, float] | None = None,
        prefer_stable: bool = False,
        **session_options: Any,
    ) -> None:
        if not targets:
            raise ValueError("a replica set needs at least one target")
        self.name = name or f"rset-{uuid.uuid4().hex[:8]}"
        self.factory = factory
        self._targets = [self._split_target(t) for t in targets]
        self.replicas_wanted = int(
            replicas if replicas is not None else len(self._targets)
        )
        if self.replicas_wanted < 1:
            raise ValueError(
                f"replicas must be >= 1, got {self.replicas_wanted}"
            )
        #: SLO-critical placement: rank non-preemptible (stable) pool
        #: targets ahead of spot ones, so serving replicas pin to
        #: capacity that will not be reclaimed under them.  The autoscale
        #: controller sets this on the sets it manages as SLO-critical.
        self.prefer_stable = bool(prefer_stable)
        self._session_options = dict(session_options)
        self._router_queue_max = router_queue_max
        self.router = ReplicaRouter(
            weights=tenant_weights,
            sticky_ttl_s=(
                300.0 if sticky_ttl_s is None else float(sticky_ttl_s)
            ),
            queue_max=0,  # resized once replica capacity is known
        )
        #: replica id -> supervisor (dead replicas leave; closed leave).
        self._replicas: dict[str, SessionSupervisor] = {}
        #: replica id -> (executor, pool) it was placed on.
        self._placements: dict[str, tuple[Any, Any]] = {}
        self._payload: bytes | None = None
        self._digest = ""
        self._next_rid = 0
        self._next_replica = 0
        self._closed = False
        #: scale-to-zero: True between a drain-to-zero (scale_to(0)) and
        #: the re-warm the next request (or explicit scale-up) triggers.
        self._suspended = False
        #: replica count a demand-triggered resume re-opens (the
        #: controller grows it further from trends once traffic flows).
        self._resume_to = 1
        #: serializes scale transitions against each other AND against a
        #: request arriving mid-teardown — such a request waits for the
        #: drain to finish, then re-warms; it is never dropped.
        self._scale_lock = asyncio.Lock()
        self._pump_tasks: set[asyncio.Task] = set()
        #: recent router decision walls (the <1ms bench assertion reads
        #: the same numbers the histogram observes).
        self.decision_s: collections.deque = collections.deque(maxlen=4096)
        # -- tail-latency hedging ------------------------------------------
        # A deterministic (temperature=0), non-sticky request whose TTFT
        # exceeds the set's adaptive percentile is speculatively re-issued
        # on the next-healthiest replica; first token stream wins, the
        # loser is cancelled through the exactly-once idx splice so the
        # byte stream is identical either way.  Budgeted: hedges stay
        # under COVALENT_TPU_HEDGE_BUDGET_PCT of issued requests.
        self._hedge_enabled = os.environ.get(
            "COVALENT_TPU_HEDGE", "on"
        ).strip().lower() not in ("off", "0", "false", "disabled")
        self._hedge_percentile = float(
            os.environ.get("COVALENT_TPU_HEDGE_PERCENTILE", "95") or 95
        )
        self._hedge_min_s = float(
            os.environ.get("COVALENT_TPU_HEDGE_MIN_S", "0.05") or 0.05
        )
        self._hedge_budget_pct = float(
            os.environ.get("COVALENT_TPU_HEDGE_BUDGET_PCT", "5") or 5
        )
        #: recent time-to-first-token samples (both arms feed it).
        self._ttft_ring: collections.deque = collections.deque(maxlen=512)
        self._hedge_issued = 0
        self._hedge_wins = 0
        self._requests_issued = 0

    @staticmethod
    def _split_target(target: Any) -> tuple[Any, Any]:
        """(executor, pool-or-None) from a Pool or a bare executor."""
        if hasattr(target, "spec") and hasattr(target, "executor"):
            return target.executor, target
        return target, None

    # -- views --------------------------------------------------------------

    @property
    def state(self) -> str:
        if self._closed:
            return "closed"
        states = {sup.state for sup in self._replicas.values()}
        if "open" in states:
            return "open"
        if "reconnecting" in states:
            return "reconnecting"
        if self._suspended:
            return "suspended"
        return "failed"

    @property
    def suspended(self) -> bool:
        """Scaled to zero: no live replicas, re-warms on first demand."""
        return self._suspended and not any(
            s.alive for s in self._replicas.values()
        )

    @property
    def live_replicas(self) -> int:
        """Replicas that are open or recovering (the autoscale view)."""
        return len([s for s in self._replicas.values() if s.alive])

    @property
    def decode_slots(self) -> int:
        """Aggregate engine slots across live replicas — the honest
        concurrency capacity (the router's per-replica view adds the
        admission queue on top; a utilization target must not)."""
        return sum(
            max(1, sup.slots)
            for sup in self._replicas.values()
            if sup.alive
        )

    @property
    def queued(self) -> int:
        """Requests waiting in the router's DRR queue."""
        return self.router.queued

    @property
    def supervisors(self) -> dict[str, SessionSupervisor]:
        return dict(self._replicas)

    @property
    def in_flight(self) -> int:
        return sum(sup.in_flight for sup in self._replicas.values())

    @property
    def served(self) -> int:
        return sum(sup.served for sup in self._replicas.values())

    @property
    def reconnects(self) -> int:
        return sum(sup.reconnects for sup in self._replicas.values())

    def _views(self) -> dict[str, ReplicaView]:
        views: dict[str, ReplicaView] = {}
        for rid, sup in self._replicas.items():
            # A replica's routable capacity mirrors the worker's own
            # bound (engine slots + admission queue): the router sheds
            # before the worker would, so worker-side sheds only happen
            # to callers bypassing the set.
            capacity = max(1, sup.slots) + max(0, sup.queue_max)
            st = HEALTH.state(sup.sid)
            views[rid] = ReplicaView(
                rid,
                open=sup.routable,
                alive=sup.alive,
                load=sup.in_flight,
                capacity=capacity,
                health=HEALTH.score(sup.sid),
                # PROBING counts as degraded too: the canary is in
                # flight, not passed — the replica routes last-resort
                # (able to take the probe plus overflow) until the
                # verdict readmits it to PROBATION.
                degraded=(st in (DEGRADED, PROBING)),
                quarantined=(st == QUARANTINED),
            )
            # Quarantined replicas only come back via a canary probe:
            # allow_probe is single-flight with exponential dwell, so at
            # most one cheap ping is in flight per quarantined replica.
            if st == QUARANTINED and sup.alive and HEALTH.allow_probe(sup.sid):
                self._spawn_canary(sup)
        return views

    def _spawn_canary(self, sup: SessionSupervisor) -> None:
        """Probe a quarantined replica with a cheap ping; report verdict."""

        async def _probe() -> None:
            ok = await sup.canary()
            HEALTH.record_probe(sup.sid, ok)

        try:
            task = asyncio.ensure_future(_probe())
        except RuntimeError:
            # No running loop (sync status path) — release the probe slot
            # WITHOUT a verdict so the next pump retries: no probe ran,
            # so nothing may readmit OR lengthen the quarantine dwell.
            HEALTH.release_probe(sup.sid)
            return
        self._pump_tasks.add(task)
        task.add_done_callback(
            lambda t: (
                self._pump_tasks.discard(t),
                t.cancelled() or t.exception(),
            )
        )

    def status(self) -> dict[str, Any]:
        """The set's contribution to operator views (bench + smoke)."""
        decisions = sorted(self.decision_s)
        p50 = decisions[len(decisions) // 2] if decisions else 0.0
        return {
            "name": self.name,
            "state": self.state,
            **({"suspended": True} if self.suspended else {}),
            "replicas": {
                rid: sup.status() for rid, sup in self._replicas.items()
            },
            "in_flight": self.in_flight,
            "served": self.served,
            "reconnects": self.reconnects,
            "queued": self.router.queued,
            "sticky": self.router.sticky_count(),
            **(
                {"adapters": self.adapter_residency()}
                if any(s.adapters for s in self._replicas.values())
                else {}
            ),
            "router_decision_p50_ms": round(p50 * 1e3, 4),
            "hedge": {
                "enabled": self._hedge_enabled,
                "issued": self._hedge_issued,
                "wins": self._hedge_wins,
                "threshold_s": round(self._hedge_threshold_s(), 4),
            },
        }

    def _publish_replica_states(self) -> None:
        counts = {state: 0 for state in _REPLICA_STATES}
        for sup in self._replicas.values():
            counts[sup.state] = counts.get(sup.state, 0) + 1
        for state in _REPLICA_STATES:
            SERVE_REPLICAS.labels(set=self.name, state=state).set(
                counts[state]
            )

    # -- open / placement ---------------------------------------------------

    async def _open(self) -> "ReplicaSet":
        with Span("serve.replica_set_open", {"set": self.name}):
            self._payload = await asyncio.to_thread(
                cloudpickle.dumps, self.factory
            )
            self._digest = bytes_digest(self._payload)
            opened = await asyncio.gather(
                *(self._open_replica() for _ in range(self.replicas_wanted)),
                return_exceptions=True,
            )
        failures = [r for r in opened if isinstance(r, BaseException)]
        if len(failures) == len(opened):
            raise ServeError(
                f"replica set {self.name}: every replica open failed"
            ) from failures[0]
        for failure in failures:
            app_log.warning(
                "replica set %s: a replica failed to open (%r); "
                "continuing degraded", self.name, failure,
            )
        if self._router_queue_max is None:
            # Default admission bound: the whole set's worker-side
            # capacity again as router backlog — past that, shedding is
            # the honest verdict (same rationale as the worker queue).
            total = sum(
                view.capacity for view in self._views().values()
            )
            self.router.set_queue_max(max(1, total))
        else:
            self.router.set_queue_max(self._router_queue_max)
        self._publish_replica_states()
        journal_mod.record(
            "replica_set", name=self.name, replicas=self.replicas_wanted
        )
        obs_events.emit(
            "serve.replica_set_opened",
            set=self.name,
            replicas=len(self._replicas),
            wanted=self.replicas_wanted,
        )
        return self

    def _rank_targets(self) -> list[tuple[Any, Any]]:
        """Placement order for the next replica.

        Spread first (fewest replicas of THIS set already on the
        target); under ``prefer_stable`` non-preemptible pools beat spot
        ones next (SLO-critical serving pins to capacity that will not
        be reclaimed — ahead even of staging affinity: re-staging a
        factory is cheap, losing a replica mid-burn is not); then the
        serving analog of fn-digest affinity: a target whose gang
        already holds the factory's CAS digest re-opens with zero
        staging, then warm gangs over cold, then free pool slots.
        """
        assigned: dict[int, int] = {}
        for executor, _pool in self._placements.values():
            assigned[id(executor)] = assigned.get(id(executor), 0) + 1

        def rank(entry: tuple[Any, Any]):
            executor, pool = entry
            # Pool targets go through the Pool's own probe (it guards
            # cold/stub executors); bare executors are probed directly.
            holds = getattr(
                pool if pool is not None else executor,
                "holds_serve_digest", None,
            )
            affinity = False
            if holds is not None:
                try:
                    affinity = bool(holds(self._digest))
                except Exception:  # noqa: BLE001 - ranking is best-effort
                    affinity = False
            # getattr: unit tests build bare sets via __new__.
            spot = bool(
                getattr(self, "prefer_stable", False)
                and pool is not None
                and getattr(pool, "preemptible", False)
            )
            warm = bool(getattr(executor, "is_warm", False))
            free = pool.free_slots if pool is not None else 0
            return (
                assigned.get(id(executor), 0),
                spot,
                not affinity,
                not warm,
                -free,
            )

        return sorted(self._targets, key=rank)

    async def _open_replica(self) -> SessionSupervisor:
        index = self._next_replica
        self._next_replica += 1
        replica_id = f"r{index}"
        executor, pool = self._rank_targets()[0]
        self._placements[replica_id] = (executor, pool)
        supervisor = SessionSupervisor(
            executor,
            sid=f"{self.name}:{replica_id}",
            pool=pool,
            replica_of=(self.name, replica_id),
            on_change=self._on_replica_change,
            on_failed=self._on_replica_failed,
            **self._session_options,
        )
        self._replicas[replica_id] = supervisor
        try:
            assert self._payload is not None
            await supervisor.open(self._payload, self._digest)
        except BaseException:
            self._replicas.pop(replica_id, None)
            self._placements.pop(replica_id, None)
            raise
        journal_mod.record(
            "replica", set=self.name, sid=supervisor.sid, replica=index
        )
        self._publish_replica_states()
        return supervisor

    # -- requests -----------------------------------------------------------

    async def request(
        self,
        prompt,
        params: dict | None = None,
        deadline_s: float | None = None,
        tenant: str = "",
        sticky: str = "",
    ) -> ServeRequest:
        """Submit one request through the router; returns its stream.

        ``sticky`` names the caller's multi-turn session: its requests
        pin to one replica until ``sticky_ttl_s`` of silence (or the
        replica's death).  A request the router cannot place immediately
        waits in the per-tenant DRR queue and dispatches as lanes free —
        its stream just starts later.  A full router queue sheds with
        :class:`ServeRequestRejected` (``serve_admission_shed``).

        A set scaled to zero (``scale_to(0)``) re-warms here: the first
        request after the idle teardown waits out any still-draining
        suspension (mid-teardown requests are never dropped), opens a
        fresh replica, and streams normally — cold-start latency, no
        error.

        ``params`` ride to the engine verbatim; beyond the sampling
        knobs this includes the per-request ``quality`` selector
        (``"exact"`` or a decode-mode name — see
        ``models.serve.ContinuousEngine``): engines with lane groups
        route the request to the matching quantized lane, and ANY
        refusal (unknown name, unbuilt group) falls back to the
        bit-exact fp lane rather than rejecting.
        """
        if self._closed:
            raise ServeError(f"replica set {self.name} is closed")
        if not any(s.alive for s in self._replicas.values()):
            if self._suspended:
                await self._ensure_live()
            else:
                raise ServeError(
                    f"replica set {self.name} has no live replicas"
                )
        self._next_rid += 1
        rid = f"{self.name}-r{self._next_rid}"
        request = ServeRequest(
            rid,
            [int(t) for t in prompt],
            params,
            (
                self._default_deadline_s()
                if deadline_s is None
                else deadline_s
            ),
            tenant,
        )
        request.sticky = sticky
        await self._prepare_request(request)
        item = WorkItem(
            fn=None, args=(), kwargs={},
            task_metadata={
                "request": request, "sticky": sticky,
                "prefix_key": request.prefix_key,
                "adapter": str((params or {}).get("adapter") or ""),
            },
            tenant=tenant or DEFAULT_TENANT,
        )
        t0 = time.perf_counter()
        try:
            self.router.submit(item)
        except QueueFullError as err:
            SERVE_ROUTER_DECISIONS_TOTAL.labels(outcome="shed").inc()
            rejection = ServeRequestRejected(
                rid, "serve_admission_shed", str(err)
            )
            request._fail(rejection)
            raise rejection from None
        if self.suspended:
            # A scale_to(0) drained the set between the alive-check at
            # the top and this submit — the ``_prepare_request`` hook is
            # a real suspension point (a disaggregated prefill round
            # trip) — and the drain's own queued-demand check ran before
            # this item existed.  Re-warm NOW rather than leaving the
            # item in a queue nothing pumps; a failed re-warm unqueues
            # and fails it loudly.
            try:
                await self._ensure_live()
            except BaseException:
                self.router.remove(
                    lambda it: it.task_metadata.get("request") is request
                )
                if not request.done:
                    request._fail(ServeError(
                        f"replica set {self.name}: re-warm failed"
                    ))
                raise
        assignments = self.router.pump(self._views())
        elapsed = time.perf_counter() - t0
        self.decision_s.append(elapsed)
        SERVE_ROUTER_DECISION_SECONDS.observe(elapsed)
        placed = {id(i) for i, _, _ in assignments}
        # The router hop is its own waterfall row (distinct from the
        # tiling ``route`` segment, which also absorbs DRR queue time):
        # a request that waited out a full queue shows a long segment
        # but a short hop, and the difference IS the diagnosis.
        record_span(
            "serve.router_hop",
            trace_id=request.span.trace_id,
            parent_id=request.span.span_id,
            start_ts=time.time() - elapsed,
            duration_s=elapsed,
            attributes={
                "rid": rid,
                "outcome": (
                    "placed" if id(item) in placed else "queued"
                ),
            },
        )
        if id(item) not in placed:
            SERVE_ROUTER_DECISIONS_TOTAL.labels(outcome="queued").inc()
        await self._dispatch_assignments(assignments)
        self._requests_issued += 1
        if self._hedge_eligible(request):
            task = asyncio.ensure_future(self._hedge_watch(request))
            self._pump_tasks.add(task)
            task.add_done_callback(
                lambda t: (
                    self._pump_tasks.discard(t),
                    t.cancelled() or t.exception(),
                )
            )
        return request

    # -- multi-adapter registry ---------------------------------------------

    def adapter_residency(self) -> dict[str, list[str]]:
        """adapter name -> replica ids whose engine holds it resident."""
        residency: dict[str, list[str]] = {}
        for rid, sup in self._replicas.items():
            for name in sup.adapters:
                residency.setdefault(name, []).append(rid)
        return {name: sorted(rids) for name, rids in residency.items()}

    async def attach_adapter(
        self,
        name: str,
        payload: Any = None,
        *,
        path: str = "",
        digest: str = "",
        rank: int | None = None,
        alpha: float = 16.0,
        replicas: int = 0,
        timeout_s: float | None = None,
    ) -> dict[str, dict]:
        """Attach a named adapter across the set, spread by load.

        ``replicas=0`` (default) attaches everywhere — any replica can
        then serve the adapter and routing stays unconstrained.
        ``replicas=N`` attaches to only the N LEAST-LOADED open replicas
        (capacity consolidation: a long-tail adapter does not need every
        engine's bank slots), and the router learns the residency sites
        so requests naming the adapter place onto — and wait for — the
        replicas that actually hold it.  Returns replica id -> worker
        ack; a replica that refuses (bank full) is skipped with its
        error in the map, not fatal, as long as at least one attach
        lands.
        """
        open_replicas = [
            (rid, sup) for rid, sup in self._replicas.items()
            if sup.routable
        ]
        if not open_replicas:
            raise ServeError(
                f"replica set {self.name} has no open replica to attach "
                f"adapter {name!r} to"
            )
        open_replicas.sort(key=lambda pair: pair[1].in_flight)
        count = int(replicas) if replicas else len(open_replicas)
        chosen = open_replicas[:max(1, count)]
        spread = bool(replicas) and len(chosen) < len(open_replicas)
        acks: dict[str, dict] = {}
        landed = 0
        for rid, sup in chosen:
            try:
                acks[rid] = await sup.attach_adapter(
                    name, payload, path=path, digest=digest, rank=rank,
                    alpha=alpha, timeout_s=timeout_s,
                )
                landed += 1
                if spread:
                    self.router.record_adapter_site(str(name), rid)
            except BaseException as err:
                if isinstance(err, asyncio.CancelledError):
                    raise
                acks[rid] = {"error": repr(err)}
                app_log.warning(
                    "adapter %r attach on replica %s failed: %r",
                    name, rid, err,
                )
        if not landed:
            raise ServeError(
                f"adapter {name!r} attached to no replica of {self.name}: "
                f"{acks}"
            )
        if not spread:
            # Resident everywhere that matters: lift any stale routing
            # constraint from a previous partial attachment.
            self.router.drop_adapter_site(str(name))
        return acks

    async def detach_adapter(
        self, name: str, timeout_s: float = 30.0
    ) -> dict[str, dict]:
        """Detach a named adapter from every replica holding it."""
        acks: dict[str, dict] = {}
        for rid, sup in list(self._replicas.items()):
            if name not in sup.adapters:
                continue
            try:
                acks[rid] = await sup.detach_adapter(
                    name, timeout_s=timeout_s
                )
            except BaseException as err:
                if isinstance(err, asyncio.CancelledError):
                    raise
                acks[rid] = {"error": repr(err)}
        self.router.drop_adapter_site(str(name))
        return acks

    async def _prepare_request(self, request: ServeRequest) -> None:
        """Pre-dispatch hook: a disaggregated set runs the prefill tier
        here (attaching the KV bundle and prefix key) before the router
        ever sees the request.  The base set does nothing."""

    def _default_deadline_s(self) -> float:
        for sup in self._replicas.values():
            return sup.default_deadline_s
        return 0.0

    async def _dispatch_assignments(
        self, assignments: list[tuple[WorkItem, str, str]]
    ) -> None:
        for item, replica_id, outcome in assignments:
            SERVE_ROUTER_DECISIONS_TOTAL.labels(outcome=outcome).inc()
            request = item.task_metadata["request"]
            supervisor = self._replicas.get(replica_id)
            if supervisor is None or not supervisor.alive:
                self._reroute(request, item.task_metadata.get("sticky", ""))
                continue
            try:
                await supervisor.submit(
                    request, fail_on_error=False, wait_ready=False,
                )
            except Exception as err:  # noqa: BLE001 - re-route, not fail
                if request.done:
                    continue
                app_log.debug(
                    "replica %s submit failed (%s); re-routing %s",
                    replica_id, err, request.rid,
                )
                self._reroute(
                    request, item.task_metadata.get("sticky", "")
                )

    def _reroute(self, request: ServeRequest, sticky: str = "") -> None:
        """Queue a request again after its replica died under it.

        The sticky key defaults to the one the request was submitted
        with, so a drain-on-death re-route keeps (or re-establishes) the
        caller's pin on whatever survivor takes the stream.
        """
        sticky = sticky or request.sticky
        if request.done:
            return
        live = [s for s in self._replicas.values() if s.alive]
        if not live or self._closed:
            request._fail(ServeError(
                f"replica set {self.name}: no live replica to re-route "
                f"{request.rid} onto"
            ))
            return
        SERVE_ROUTER_DECISIONS_TOTAL.labels(outcome="failover").inc()
        item = WorkItem(
            fn=None, args=(), kwargs={},
            task_metadata={
                "request": request, "sticky": sticky,
                "prefix_key": request.prefix_key,
                "adapter": str(
                    (request.params or {}).get("adapter") or ""
                ),
            },
            tenant=request.tenant or DEFAULT_TENANT,
        )
        try:
            self.router.submit(item)
        except QueueFullError as err:
            request._fail(ServeRequestRejected(
                request.rid, "serve_admission_shed", str(err)
            ))
            return
        self._schedule_pump()

    # -- tail-latency hedging -----------------------------------------------

    def _hedge_eligible(self, request: ServeRequest) -> bool:
        """Only deterministic, un-pinned requests may hedge: a sampled
        (temperature>0) stream would diverge between arms, and a sticky
        request's KV/session locality belongs to its pinned replica."""
        if not self._hedge_enabled or request.sticky:
            return False
        params = request.params or {}
        if params.get("temperature"):
            return False
        return len([s for s in self._replicas.values() if s.alive]) > 1

    def _hedge_threshold_s(self) -> float:
        """Adaptive trigger: the set's recent TTFT percentile, floored at
        COVALENT_TPU_HEDGE_MIN_S.  With too few samples the threshold is
        deliberately conservative (1s) — warm-up latency is not a gray
        failure."""
        ring = sorted(self._ttft_ring)
        if len(ring) < 8:
            return max(self._hedge_min_s, 1.0)
        k = min(
            len(ring) - 1,
            int(len(ring) * self._hedge_percentile / 100.0),
        )
        return max(self._hedge_min_s, ring[k])

    async def _hedge_watch(self, request: ServeRequest) -> None:
        """Arm the hedge timer for one request: if no first token lands
        within the adaptive threshold, speculatively re-issue it on the
        next-healthiest replica.  Both arms feed the TTFT ring."""
        threshold = self._hedge_threshold_s()
        t0 = time.monotonic()
        try:
            await asyncio.wait_for(request.first_token.wait(), threshold)
        except asyncio.TimeoutError:
            if not request.done and not self._closed:
                await self._launch_hedge(request)
        finally:
            await request.first_token.wait()
            self._ttft_ring.append(
                request.ttft_s
                if request.ttft_s is not None
                else time.monotonic() - t0
            )

    async def _launch_hedge(self, request: ServeRequest) -> None:
        """Issue the speculative second arm and arbitrate the winner.

        The SAME ServeRequest is submitted to a second supervisor: both
        arms feed one token buffer through the exactly-once idx splice,
        so duplicate chunks drop and the stream is byte-identical no
        matter which arm wins.  The first arm to deliver a token is the
        winner (``request.served_by``); the loser's lane is released
        with a fire-and-forget ``serve_cancel`` (``abandon``)."""
        if self._hedge_issued + 1 > max(
            1.0, self._requests_issued * self._hedge_budget_pct / 100.0
        ):
            SERVE_HEDGES_TOTAL.labels(outcome="budget").inc()
            return
        primary = next(
            (
                sup for sup in self._replicas.values()
                if request.rid in sup._requests
            ),
            None,
        )
        views = self._views()
        candidates = [
            sup for rid, sup in self._replicas.items()
            if sup.routable
            and sup is not primary
            and not views[rid].quarantined
            and views[rid].capacity - views[rid].load > 0
        ]
        if not candidates:
            SERVE_HEDGES_TOTAL.labels(outcome="no_target").inc()
            return
        candidates.sort(
            key=lambda sup: (
                HEALTH.rank(sup.sid),
                -HEALTH.score(sup.sid),
                sup.in_flight,
            )
        )
        target = candidates[0]
        request.hedged = True
        self._hedge_issued += 1
        SERVE_HEDGES_TOTAL.labels(outcome="launched").inc()
        obs_events.emit(
            "serve.hedge",
            set=self.name,
            rid=request.rid,
            primary=(primary.sid if primary is not None else ""),
            target=target.sid,
        )
        try:
            await target.submit(
                request, fail_on_error=False, wait_ready=False
            )
        except BaseException:
            # The hedge arm failing to launch is not the request's
            # problem — the primary is still streaming.
            self._hedge_issued -= 1
            SERVE_HEDGES_TOTAL.labels(outcome="no_target").inc()
            return
        await request.first_token.wait()
        winner = request.served_by
        if winner == target.sid:
            self._hedge_wins += 1
            SERVE_HEDGES_TOTAL.labels(outcome="won").inc()
            if primary is not None:
                primary.abandon(request.rid)
                # The winner's TTFT lands on the winner's health record;
                # the primary would otherwise accrue NO signal from a
                # request that hedged away.  Charge it the censored
                # observation (it had not delivered by now — a lower
                # bound on its true TTFT) plus a straggler fault, so a
                # replica losing hedge after hedge degrades instead of
                # staying invisible to the health monitor.
                if request.t_dispatched is not None:
                    HEALTH.record_latency(
                        primary.sid,
                        time.monotonic() - request.t_dispatched,
                        group=self.name,
                    )
                HEALTH.record_fault(
                    primary.sid, label="hedge_lost", group=self.name
                )
        else:
            SERVE_HEDGES_TOTAL.labels(outcome="lost").inc()
            target.abandon(request.rid)

    # -- health hooks (supervisor callbacks, event-loop context) ------------

    def _on_replica_change(self, _supervisor: SessionSupervisor) -> None:
        self._publish_replica_states()
        if not self._closed and self.router.queued:
            self._schedule_pump()

    def _on_replica_failed(
        self, supervisor: SessionSupervisor, failure: BaseException
    ) -> bool:
        """Drain-on-death: a replica past its retry budget hands its
        in-flight requests here; survivors absorb them exactly-once (the
        requests keep their token high-water marks, so the fresh
        replica's from-zero streams splice with no duplicate and no
        hole).  Returns True — the supervisor must not fail them."""
        replica_id = (
            supervisor.replica_of[1]
            if supervisor.replica_of
            else supervisor.sid
        )
        detached = supervisor.detach_requests()
        self.router.forget_replica(replica_id)
        obs_events.emit(
            "serve.replica_failed",
            set=self.name,
            replica=replica_id,
            error=repr(failure),
            rerouted=len(detached),
        )
        for request in detached:
            self._reroute(request)
        if not any(s.alive for s in self._replicas.values()):
            # The LAST replica just died: nothing will ever pump the
            # router queue again, so its waiters fail now with the cause
            # instead of hanging until the set closes.
            for item in self.router.drain():
                request = item.task_metadata.get("request")
                if request is not None and not request.done:
                    request._fail(ServeError(
                        f"replica set {self.name} has no live replicas: "
                        f"{failure}"
                    ))
        self._publish_replica_states()
        return True

    def _schedule_pump(self) -> None:
        task = asyncio.ensure_future(self._pump())
        self._pump_tasks.add(task)
        task.add_done_callback(
            lambda t: (
                self._pump_tasks.discard(t),
                None if t.cancelled() else t.exception(),
            )
        )

    async def _pump(self) -> None:
        if self._closed:
            return
        t0 = time.perf_counter()
        assignments = self.router.pump(self._views())
        if assignments:
            elapsed = time.perf_counter() - t0
            self.decision_s.append(elapsed / len(assignments))
            SERVE_ROUTER_DECISION_SECONDS.observe(
                elapsed / len(assignments)
            )
            await self._dispatch_assignments(assignments)

    # -- scaling ------------------------------------------------------------

    async def scale_to(self, replicas: int) -> int:
        """Grow or shrink the live replica count; returns the new count.

        Scale-up opens fresh sessions on affinity-ranked targets
        (concurrently); scale-down retires the least-loaded replicas —
        each stops receiving new work, drain-closes (the worker finishes
        every admitted and queued request first), releases its fleet
        capacity pin, and reaps its per-session AND per-replica metric
        series through the supervisor's ``_drop_live``.

        ``scale_to(0)`` is **scale-to-zero**: every replica drain-closes
        and the set suspends — the next :meth:`request` (or a later
        scale-up) re-warms it from the staged factory payload.  A request
        racing the teardown waits for the drain and re-warms; it is
        never dropped, and its stream is exactly-once like any other.
        """
        if self._closed:
            raise ServeError(f"replica set {self.name} is closed")
        replicas = int(replicas)
        if replicas < 0:
            raise ValueError(f"replicas must be >= 0, got {replicas}")
        async with self._scale_lock:
            count = await self._scale_locked(replicas)
        journal_mod.record(
            "replica_set", name=self.name, replicas=self.replicas_wanted
        )
        return count

    async def _scale_locked(self, replicas: int) -> int:
        live = {
            rid: sup for rid, sup in self._replicas.items() if sup.alive
        }
        if replicas == 0:
            # Remember the width a demand-triggered resume restores; the
            # flag is up BEFORE the drain so a request arriving
            # mid-teardown queues behind the lock and re-warms after.
            self._resume_to = max(1, min(self.replicas_wanted, len(live)))
            self._suspended = True
            for rid in list(live):
                await self._retire_replica(rid)
            self.replicas_wanted = 0
            if self.router.queued:
                # Demand slipped in while the drain held the lock (a
                # request that still saw a live replica queued into the
                # router, whose items only worker-ADMITTED drains
                # finish): a suspended set never pumps, so those waiters
                # would hang until unrelated new traffic re-warmed it.
                # Queued requests ARE demand — re-warm immediately
                # instead of suspending over them.  A re-warm that opens
                # NOTHING fails the stranded waiters loudly (the set
                # stays suspended and resumable).
                revived = await self._scale_locked(max(1, self._resume_to))
                if revived == 0:
                    self._suspended = True
                    for item in self.router.drain():
                        request = item.task_metadata.get("request")
                        if request is not None and not request.done:
                            request._fail(ServeError(
                                f"replica set {self.name}: re-warm "
                                f"failed with queued requests"
                            ))
                return revived
            self._publish_replica_states()
            obs_events.emit(
                "serve.replica_set_suspended",
                set=self.name,
                resume_to=self._resume_to,
            )
            return 0
        resumed = self._suspended
        self._suspended = False
        if replicas > len(live):
            grow = replicas - len(live)
            results = await asyncio.gather(
                *(self._open_replica() for _ in range(grow)),
                return_exceptions=True,
            )
            for failure in results:
                if isinstance(failure, BaseException):
                    app_log.warning(
                        "replica set %s scale-up open failed: %r",
                        self.name, failure,
                    )
            self._schedule_pump()
        elif replicas < len(live):
            victims = sorted(
                live, key=lambda rid: live[rid].in_flight
            )[: len(live) - replicas]
            for rid in victims:
                await self._retire_replica(rid)
        self.replicas_wanted = replicas
        self._publish_replica_states()
        now_live = len([
            s for s in self._replicas.values() if s.alive
        ])
        if resumed and now_live == 0:
            # Every resume open failed: stay suspended so the NEXT
            # demand retries the re-warm instead of hitting a dead,
            # unresumable set.
            self._suspended = True
        elif resumed:
            obs_events.emit(
                "serve.replica_set_resumed",
                set=self.name,
                replicas=now_live,
            )
        obs_events.emit(
            "serve.replica_set_scaled",
            set=self.name,
            replicas=now_live,
        )
        return now_live

    async def _ensure_live(self) -> None:
        """Re-warm a suspended set on first demand (scale-to-zero exit).

        Serialized behind the scale lock: a request that raced a
        still-draining ``scale_to(0)`` waits here for the drain, then
        re-opens ``_resume_to`` replicas and proceeds.  A re-warm that
        opens nothing raises (the caller's request fails loudly instead
        of queueing into a set nothing will ever pump); the set stays
        suspended so the next demand retries.
        """
        async with self._scale_lock:
            if self._closed:
                raise ServeError(f"replica set {self.name} is closed")
            if any(s.alive for s in self._replicas.values()):
                return
            if not self._suspended:
                raise ServeError(
                    f"replica set {self.name} has no live replicas"
                )
            revived = await self._scale_locked(max(1, self._resume_to))
            if revived == 0:
                raise ServeError(
                    f"replica set {self.name}: scale-to-zero re-warm "
                    f"failed to open a replica"
                )

    async def _retire_replica(self, replica_id: str) -> None:
        supervisor = self._replicas.pop(replica_id, None)
        self._placements.pop(replica_id, None)
        if supervisor is None:
            return
        self.router.forget_replica(replica_id)
        journal_mod.record(
            "replica", set=self.name, sid=supervisor.sid, state="closed"
        )
        try:
            await supervisor.close()
        except Exception as err:  # noqa: BLE001 - teardown is best-effort
            app_log.warning(
                "replica %s:%s close failed: %s",
                self.name, replica_id, err,
            )

    # -- close --------------------------------------------------------------

    async def close(self, timeout: float = 30.0) -> dict:
        """Drain and close every replica; returns merged closed stats."""
        if self._closed:
            return {"served": self.served}
        self._closed = True
        for task in list(self._pump_tasks):
            task.cancel()
        for item in self.router.drain():
            request = item.task_metadata.get("request")
            if request is not None and not request.done:
                request._fail(
                    ServeError(f"replica set {self.name} closed")
                )
        served = 0
        closes = await asyncio.gather(
            *(
                sup.close(timeout)
                for sup in list(self._replicas.values())
            ),
            return_exceptions=True,
        )
        for closed in closes:
            if isinstance(closed, dict):
                served += int(closed.get("served") or 0)
        for state in _REPLICA_STATES:
            SERVE_REPLICAS.remove(set=self.name, state=state)
        obs_events.emit(
            "serve.replica_set_closed", set=self.name, served=served
        )
        return {"served": served}


async def open_replica_set(
    targets: Any,
    factory: Any,
    *,
    replicas: int | None = None,
    name: str = "",
    sticky_ttl_s: float | None = None,
    router_queue_max: int | None = None,
    tenant_weights: dict[str, float] | None = None,
    prefer_stable: bool = False,
    **session_options: Any,
) -> ReplicaSet:
    """Open ``replicas`` sessions of one factory behind a routing front.

    ``targets`` is a list of fleet ``Pool``\\ s and/or ``TPUExecutor``\\ s
    (one entry also works); ``replicas`` defaults to ``len(targets)``.
    Replicas place onto targets spread-first, then by factory-digest
    affinity / warmth / free slots; a pool-backed replica pins one of its
    pool's capacity slots for its lifetime.  ``session_options`` are the
    per-session knobs ``open_session`` takes (``queue_max``,
    ``default_deadline_s``, ``stats_interval_s``, ``open_timeout_s``,
    ``retries``).
    """
    if not isinstance(targets, (list, tuple)):
        targets = [targets]
    replica_set = ReplicaSet(
        list(targets),
        factory,
        replicas=replicas,
        name=name,
        sticky_ttl_s=sticky_ttl_s,
        router_queue_max=router_queue_max,
        tenant_weights=tenant_weights,
        prefer_stable=prefer_stable,
        **session_options,
    )
    return await replica_set._open()
