"""One compiled-executable cache for the public decode entry points.

``generate`` / ``beam_search`` / ``speculative_generate`` /
``speculative_sample`` are fully traceable, but a bare call used to run
their decode loops EAGERLY — hundreds of op dispatches per token —
unless the caller remembered ``jax.jit`` (the round-4 slow-test
post-mortem found most of the CPU tier's minutes there).  Each wrapper
now asks this cache for a jitted executable keyed on its static knobs
(the hashable flax module + every non-array argument); calls under an
outer jit simply inline.  One cache, one eviction policy, instead of
four copy-pasted ``lru_cache`` scaffolds.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable

import jax

_MAX = 512
_cache: OrderedDict = OrderedDict()


def cached_jit(key: tuple, make: Callable[[], Callable]) -> Callable:
    """Return ``jax.jit(make())`` memoized on ``key`` (LRU, bounded)."""
    fn = _cache.get(key)
    if fn is None:
        fn = jax.jit(make())
        _cache[key] = fn
        if len(_cache) > _MAX:
            _cache.popitem(last=False)
    else:
        _cache.move_to_end(key)
    return fn
