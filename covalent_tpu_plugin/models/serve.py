"""Continuous batching: a fixed-slot serving loop with rolling admission.

Plain ``generate()`` batches a FIXED set of prompts: every row starts and
(effectively) finishes together, so a 10-token answer waits for the
500-token answer sharing its batch.  Production serving (vLLM-style)
instead runs a fixed number of SLOTS and admits a new request the moment
a slot finishes — no request waits on an unrelated long one, and the
accelerator never idles while work is queued.  The reference plugin has
no serving path at all (SURVEY §2; reference ``ssh.py`` runs opaque
pickled callables); this is a beyond-parity subsystem.

TPU-native design — the pieces map to the compilation model:

* **Static shapes.** ``max_batch`` slots and one (B, L) token buffer,
  compiled once.  Finished slots keep stepping on frozen tokens (their
  logits are ignored) — the standard static-shape trade.
* **Per-slot cache via vmap.**  Each slot owns a lane of a vmapped KV
  cache, so per-slot cursors, rotary offsets, and masks come from
  ``jax.vmap`` over the single-row decode step — no scalar-cursor
  surgery in the model.  A lane's numerics are exactly a batch-1
  ``generate()``'s (no cross-batch reductions anywhere), which is what
  makes the bit-equality oracle in the tests possible.  Caveat shared
  with plain batched ``generate()``: on backends whose batched-matmul
  tiling rounds differently than the batch-1 shape (TPU MXU at bf16),
  near-tie argmaxes can flip vs the batch-1 oracle — benchmarks/
  serve_bench.py reports both arms' agreement to make the attribution
  visible; on CPU (f32 and bf16) equality is bit-exact.
* **Admission at scan boundaries.**  The device runs ``sync_steps``
  decode steps per jitted call (``lax.scan``); the host only looks at
  the tiny (B,) state vectors between calls, harvests finished rows,
  zeroes their cache lanes, and writes the next queued prompt into the
  slot.  One host round-trip per ``sync_steps`` tokens instead of one
  per token — the knob trades admission latency against host chatter
  (tunnelled TPUs want it large).
* **Bucketed batched prefill at admission** (``prefill="batched"``, the
  default).  An admitted prompt runs ONE single-lane prefill pass padded
  to a power-of-two bucket, then enters the shared decode loop — time to
  first token is one pass, not ``len(prompt)`` interleaved steps.  The
  padding trick is exact: pad K/V land at slots ``>= len(prompt)``, the
  cursor is rewound to ``len(prompt)``, and the causal mask only ever
  exposes slot ``k`` to queries at positions ``>= k`` — by which step
  the decode loop has overwritten it with the real token's K/V.
  Compiles one prefill per bucket size (a handful for a whole serving
  mix).  ``prefill="stream"`` keeps the zero-extra-compiles chunk-1
  interleave: the prompt streams through the shared step loop one token
  per step.

Greedy and temperature/top-k sampling are supported; EOS finishes a slot
early.  Sampling note: greedy outputs are identical across prefill
modes, but SAMPLED outputs are not reproducible across them — batched
admission draws each first token from a dedicated admission key chain
(``fold_in(rng, 0x5E1)``) while streaming draws it from the shared loop
stream; pin ``prefill`` as well as ``rng`` for reproducible sampling.
``rolling_cache`` models are refused (slot reset assumes the plain
cache layout).
"""

from __future__ import annotations

import functools
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .decode import _decode_model, _filter_top_k, init_cache
from .speculative import _set_cursor
from .transformer import TransformerLM


class RollingCacheUnsupported(ValueError):
    """Typed refusal: continuous serving assumes the plain cache layout.

    ``rolling_cache`` models ring-rotate their KV slots, and the slot-reset
    trick at admission (zero the lane, rewind the cursor) assumes the plain
    append-only layout.  A :class:`ValueError` subclass for back-compat,
    duck-tagged for the dispatch layers: the serving RPC surfaces this as a
    PERMANENT fault (``fault_label``/``fault_transient`` — the resilience
    classifier's self-classification hook), so a misconfigured session is
    refused once instead of burning gang retries on a deterministic error.
    """

    fault_label = "serve_model_unsupported"
    fault_transient = False


def _require_plain_cache(config, what: str) -> None:
    if config.rolling_cache:
        raise RollingCacheUnsupported(
            f"{what} does not support rolling_cache models "
            "(slot reset assumes the plain cache layout)"
        )


def _choose_tokens(logits, key, temperature, top_k):
    """Shared greedy/sampling rule for the loop and the prefill."""
    logits = logits.astype(jnp.float32)
    if temperature > 0:
        scaled = logits / temperature
        if top_k is not None:
            scaled = _filter_top_k(scaled, top_k)
        return jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


@functools.lru_cache(maxsize=64)
def _make_admit(decoder, temperature, top_k, eos_token_id, batch, bucket, g):
    """One fused, donated admission wave: batch-prefill ``g`` prompts and
    scatter their cache lanes, buffer rows, and cursors in a SINGLE
    compiled call.

    Round 4's serving wall loss traced to admission overhead: every
    admitted request paid its own single-lane prefill dispatch plus one
    eager ``.at[slot].set`` per cache leaf (each a full-tree device
    copy).  Here the whole wave is one executable with the serving state
    donated, so XLA updates the caches in place and the prefill runs as
    ONE (g, bucket) batched pass — admission cost scales with waves, not
    requests.

    Exactness of the padded pass: pad positions' K/V land at slots
    >= plen; with the cursor rewound to ``plen`` they are dead until the
    decode loop overwrites them (the causal mask shows slot k only to
    queries at positions >= k) — same trick as speculative decoding's
    cache rewind (models/speculative.py).  Rows whose ``slots`` entry is
    out of range (the group padded up to a power of two) are dropped by
    the scatters (``mode="drop"``), so padding never touches live state.
    """

    @functools.partial(jax.jit, donate_argnums=(1,))
    def admit_wave(params, state, rows, padded, plens, slots, caps_in,
                   keys):
        # rows (g, length) full buffer rows; padded (g, bucket) prompt
        # tokens; plens/caps_in/slots (g,); keys (g, 2) admission keys.
        caches, buffer, pos, plen, row_cap, n_gen, done, rng = state

        def lane_prefill(tokens, pl, key):
            zero = jax.tree_util.tree_map(
                lambda c: jnp.zeros(c.shape[1:], c.dtype), caches
            )
            logits, mutated = decoder.apply(
                {"params": params, "cache": zero}, tokens[None],
                mutable=["cache"],
            )
            cache = _set_cursor(mutated["cache"], pl)
            last = jnp.take_along_axis(
                logits, (pl - 1)[None, None, None], axis=1
            )[0, 0]  # (V,)
            first = _choose_tokens(
                last[None, :], key, temperature, top_k
            )[0]
            return cache, first

        new_lanes, firsts = jax.vmap(lane_prefill)(padded, plens, keys)
        caches = jax.tree_util.tree_map(
            lambda c, nl: c.at[slots].set(nl, mode="drop"),
            caches, new_lanes,
        )
        rows = rows.at[jnp.arange(g), plens].set(firsts)
        buffer = buffer.at[slots].set(rows, mode="drop")
        pos = pos.at[slots].set(plens, mode="drop")
        plen = plen.at[slots].set(plens, mode="drop")
        row_cap = row_cap.at[slots].set(caps_in, mode="drop")
        n_gen = n_gen.at[slots].set(
            jnp.ones((g,), jnp.int32), mode="drop"
        )
        fin = caps_in <= 1
        if eos_token_id is not None:
            fin = fin | (firsts == eos_token_id)
        done = done.at[slots].set(fin, mode="drop")
        return caches, buffer, pos, plen, row_cap, n_gen, done, rng

    return admit_wave


@functools.lru_cache(maxsize=64)
def _make_prefix_admit(decoder, temperature, top_k, eos_token_id, batch,
                       bucket, g, prefix_len):
    """Fused admission wave for prompts sharing the session's prefilled
    prefix: every lane starts from the SHARED prefix cache lane (computed
    once per engine) and prefills only its suffix, padded to ``bucket``.

    This is the shared-prefix fast path: on the dominant traffic shape —
    a common system prompt ahead of a short user turn — per-request
    prefill work drops from ``bucket(prompt)`` to ``bucket(suffix)``
    positions.  Exactness is the same two tricks the full-prefill wave
    uses, shifted by ``prefix_len``: the suffix pass appends K/V at the
    prefix cursor (queries at absolute position ``prefix_len + j`` see
    the cached prefix plus the causal suffix — exactly what one full
    pass computes for those positions), and pad K/V land at slots
    ``>= prefix_len + suffix_len`` where the rewound cursor keeps them
    dead until the decode loop overwrites them.  ``prefix_lane`` rides
    as a traced argument (broadcast across the vmapped lanes), so one
    compiled wave serves every prefix of the same length.
    """

    @functools.partial(jax.jit, donate_argnums=(1,))
    def admit_wave(params, state, prefix_lane, rows, padded, slens, slots,
                   caps_in, keys):
        # rows (g, length) full buffer rows (prefix + suffix); padded
        # (g, bucket) SUFFIX tokens; slens (g,) suffix lengths;
        # slots/caps_in (g,); keys (g, 2) admission keys.
        caches, buffer, pos, plen, row_cap, n_gen, done, rng = state

        def lane_prefill(tokens, sl, key):
            logits, mutated = decoder.apply(
                {"params": params, "cache": prefix_lane}, tokens[None],
                mutable=["cache"],
            )
            cache = _set_cursor(mutated["cache"], prefix_len + sl)
            last = jnp.take_along_axis(
                logits, (sl - 1)[None, None, None], axis=1
            )[0, 0]  # (V,)
            first = _choose_tokens(
                last[None, :], key, temperature, top_k
            )[0]
            return cache, first

        new_lanes, firsts = jax.vmap(lane_prefill)(padded, slens, keys)
        plens = prefix_len + slens
        caches = jax.tree_util.tree_map(
            lambda c, nl: c.at[slots].set(nl, mode="drop"),
            caches, new_lanes,
        )
        rows = rows.at[jnp.arange(g), plens].set(firsts)
        buffer = buffer.at[slots].set(rows, mode="drop")
        pos = pos.at[slots].set(plens, mode="drop")
        plen = plen.at[slots].set(plens, mode="drop")
        row_cap = row_cap.at[slots].set(caps_in, mode="drop")
        n_gen = n_gen.at[slots].set(
            jnp.ones((g,), jnp.int32), mode="drop"
        )
        fin = caps_in <= 1
        if eos_token_id is not None:
            fin = fin | (firsts == eos_token_id)
        done = done.at[slots].set(fin, mode="drop")
        return caches, buffer, pos, plen, row_cap, n_gen, done, rng

    return admit_wave


@functools.lru_cache(maxsize=32)
def _make_run_steps(decoder, temperature, top_k, eos_token_id,
                    length, sync_steps, batch):
    """Jitted ``sync_steps``-long serving scan, cached on its statics.

    A per-call ``@jax.jit`` over a closure would retrace and recompile
    the whole scanned model on EVERY ``continuous_generate`` call (jit
    caches key on the function object); caching the compiled callable on
    the hashable statics (the flax module itself plus the loop
    constants) makes repeat calls with the same serving shape reuse one
    executable, like ``generate()`` under a caller's jit.  ``params``
    ride as a traced argument.
    """
    rows = jnp.arange(batch)

    def choose(logits, key):
        return _choose_tokens(logits, key, temperature, top_k)

    def one_step(params, state, _):
        caches, buffer, pos, plen, row_cap, n_gen, done, rng = state

        def row_step(cache, token):
            logits, mutated = decoder.apply(
                {"params": params, "cache": cache}, token[None, :],
                mutable=["cache"],
            )
            return mutated["cache"], logits[0, -1]

        token = jnp.take_along_axis(buffer, pos[:, None], axis=1)  # (B, 1)
        caches, logits = jax.vmap(row_step)(caches, token)
        rng, key = jax.random.split(rng)
        nxt = choose(logits, key)  # (B,)
        in_prompt = (pos + 1) < plen
        write_idx = jnp.minimum(pos + 1, length - 1)
        prompt_next = buffer[rows, write_idx]
        gen_now = (~in_prompt) & (~done)
        # Prompt rows "write back" their own next token (a no-op), so one
        # scatter serves streaming prefill and decode alike.
        buffer = buffer.at[rows, write_idx].set(
            jnp.where(gen_now, nxt, prompt_next)
        )
        n_gen = n_gen + gen_now.astype(jnp.int32)
        if eos_token_id is not None:
            done = done | (gen_now & (nxt == eos_token_id))
        done = done | (n_gen >= row_cap)
        # Frozen rows hold position (their lane keeps stepping on the
        # same token; logits are ignored, cache writes past the row's
        # used region are reset at admission).
        pos = jnp.where(done, pos, pos + 1)
        return (caches, buffer, pos, plen, row_cap, n_gen, done, rng), None

    @functools.partial(jax.jit, donate_argnums=(1,))
    def run_steps(params, state):
        # State donation lets XLA update the (B, layers, S, ...) caches in
        # place: without it every sync chunk copies the full serving
        # state tree host-visibly, which round 4's wall numbers showed
        # dominating the toy-scale loop.
        state, _ = jax.lax.scan(
            functools.partial(one_step, params), state, None,
            length=sync_steps,
        )
        return state

    return run_steps


def step_accounting(
    caps: Sequence[int], max_batch: int, sync_steps: int
) -> dict[str, int]:
    """Structural decode-step accounting for a serving workload: the
    device-step counts that static wave batching and this module's
    continuous loop pay for per-request budgets ``caps``, independent of
    model size or transport.  One shared model for every artifact
    (``bench.py`` ``lm_serve`` and ``benchmarks/serve_bench.py``) so the
    accounting cannot drift from the admission rule implemented above.

    Per-request cost is ``cap - 1`` decode steps (admission prefill
    yields the first token; prefill passes are counted separately by the
    callers).  Static: requests run in arrival-order waves of
    ``max_batch``, each wave to its LONGEST member's budget.
    Continuous: greedy slot packing in arrival order; a freed slot
    re-admits only at the next ``sync_steps`` boundary (the
    quantization ``continuous_generate``'s host loop actually pays),
    with ``continuous_steps_ideal`` the unquantized packing bound.
    """
    caps = [int(c) for c in caps]
    waves = [
        caps[i:i + max_batch] for i in range(0, len(caps), max_batch)
    ]
    static = sum(max(w) - 1 for w in waves)
    ideal = [0] * max_batch
    free_at = [0] * max_batch
    finish = [0] * max_batch
    for cap in caps:
        k = min(range(max_batch), key=lambda j: ideal[j])
        ideal[k] += cap - 1
        k = min(range(max_batch), key=lambda j: free_at[j])
        finish[k] = free_at[k] + cap - 1
        free_at[k] = -(-finish[k] // sync_steps) * sync_steps
    return {
        "static_wave_steps": static,
        "continuous_steps_ideal": max(ideal),
        "continuous_steps_sync": max(finish),
    }


def continuous_generate(
    model: TransformerLM,
    params: Any,
    prompts: Sequence[np.ndarray],
    max_new_tokens: int | Sequence[int],
    *,
    max_batch: int = 4,
    temperature: float = 0.0,
    top_k: int | None = None,
    rng: jax.Array | None = None,
    eos_token_id: int | None = None,
    pad_token_id: int | None = None,
    sync_steps: int = 8,
    prefill: str = "batched",
    stats: dict | None = None,
) -> list[np.ndarray]:
    """Serve ``prompts`` (each a 1-D int32 array) through ``max_batch``
    continuously-refilled slots; returns one trimmed output sequence per
    prompt, in the input order.

    Each output is ``prompt + generated`` where generation stops at
    the request's token budget or its EOS (the EOS token is included).
    ``max_new_tokens`` is one shared budget (int) or one per request —
    mixed-length workloads are continuous batching's home turf: a slot
    whose request hits its own budget is refilled immediately instead of
    idling until the longest request in a static batch finishes.  Greedy
    rows are bit-identical to ``generate(model, params, prompt[None],
    cap_i)`` on batch-rounding-invariant backends (CPU f32/bf16; see the
    module docstring for the TPU-bf16 caveat shared with plain batched
    decode) — admission order cannot change tokens, only latency.

    ``stats``, when given, is filled with host-loop counters:
    ``prefill_passes`` (fused admission waves dispatched — the cost that
    was one pass PER REQUEST before round 5), ``sync_fetches`` (blocking
    host round-trips), and ``device_chunks`` (``sync_steps``-long scans
    dispatched).
    """
    config = _decode_model(model).config
    _require_plain_cache(config, "continuous_generate")
    caps = None
    if isinstance(max_new_tokens, (float, np.floating)):
        max_new_tokens = int(max_new_tokens)  # old int-like float contract
    if not isinstance(max_new_tokens, (int, np.integer)):
        caps = [int(c) for c in max_new_tokens]
        if len(caps) != len(prompts):
            raise ValueError(
                f"per-request max_new_tokens has {len(caps)} entries for "
                f"{len(prompts)} prompts"
            )
        if any(c < 1 for c in caps):
            raise ValueError("every per-request max_new_tokens must be >= 1")
    elif max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    if sync_steps < 1:
        raise ValueError(f"sync_steps must be >= 1, got {sync_steps}")
    if prefill not in ("batched", "stream"):
        raise ValueError(
            f'prefill must be "batched" or "stream", got {prefill!r}'
        )
    if temperature > 0 and rng is None:
        raise ValueError("sampling (temperature > 0) requires rng")
    if temperature <= 0 and top_k is not None:
        raise ValueError("top_k requires sampling (temperature > 0)")
    if top_k is not None and not 1 <= top_k <= config.vocab_size:
        raise ValueError(
            f"top_k must be in [1, {config.vocab_size}], got {top_k}"
        )
    prompts = [np.asarray(p, np.int32).reshape(-1) for p in prompts]
    if not prompts:
        return []
    if any(p.size < 1 for p in prompts):
        raise ValueError("every prompt needs at least one token")
    if caps is None:
        caps = [int(max_new_tokens)] * len(prompts)
    length = max(p.size + c for p, c in zip(prompts, caps))
    if length > config.max_seq:
        raise ValueError(
            f"worst-case prompt + budget ({length}) exceeds "
            f"config.max_seq ({config.max_seq})"
        )
    batch = min(max_batch, len(prompts))
    decoder = _decode_model(model)
    pad = pad_token_id
    if pad is None:
        pad = eos_token_id if eos_token_id is not None else 0
    if rng is None:
        rng = jax.random.PRNGKey(0)
    # The serving state (rng included) is donated to the jitted chunk and
    # admission calls; a private copy keeps the CALLER's key buffer alive
    # for their next call with the same array.
    rng = jnp.array(rng, copy=True)

    # One cache lane per slot: stack B single-row caches.  Lane shape
    # keeps the model's own batch dim of 1, so the vmapped step calls the
    # decoder exactly as a batch-1 generate() would.
    lane = init_cache(model, 1)
    caches = jax.tree_util.tree_map(
        lambda leaf: jnp.broadcast_to(
            leaf[None], (batch,) + leaf.shape
        ).copy(),
        lane,
    )
    lane_zero = jax.tree_util.tree_map(jnp.zeros_like, lane)

    run_steps = _make_run_steps(
        decoder, float(temperature), top_k, eos_token_id,
        int(length), int(sync_steps), int(batch),
    )

    # --- host-side slot management ---------------------------------------
    queue = [
        (i, p, c) for i, (p, c) in enumerate(zip(prompts, caps))
    ]  # (original index, tokens, budget)
    outputs: list[np.ndarray | None] = [None] * len(prompts)
    buffer = np.full((batch, length), pad, np.int32)
    pos = np.zeros(batch, np.int32)
    plen = np.ones(batch, np.int32)
    row_cap = np.ones(batch, np.int32)
    n_gen = np.zeros(batch, np.int32)
    done = np.ones(batch, bool)  # empty slots are "done" until admitted
    slot_req = [-1] * batch  # original request index per slot

    adm_rng = {"key": jax.random.fold_in(rng, 0x5E1)}
    # Host-side lower bound on decode steps until each slot can finish
    # (exact without EOS; with EOS a slot may finish earlier, which only
    # delays its harvest, never corrupts it — frozen rows hold position).
    min_left = [0] * batch
    if stats is not None:
        stats.update(prefill_passes=0, sync_fetches=0, device_chunks=0)

    def _count(key, by=1):
        if stats is not None:
            stats[key] += by

    def admit_stream(state, slot):
        """Streaming admission: the prompt replays through the shared
        step loop one token per step (zero extra compiles)."""
        caches, buffer, pos, plen, row_cap, n_gen, done, rng = state
        req_idx, tokens, cap = queue.pop(0)
        slot_req[slot] = req_idx
        min_left[slot] = tokens.size - 1 + cap
        row = np.full((length,), pad, np.int32)
        row[: tokens.size] = tokens
        buffer = buffer.at[slot].set(jnp.asarray(row))
        plen = plen.at[slot].set(tokens.size)
        row_cap = row_cap.at[slot].set(cap)
        pos = pos.at[slot].set(0)
        n_gen = n_gen.at[slot].set(0)
        done = done.at[slot].set(False)
        caches = jax.tree_util.tree_map(
            lambda c, z: c.at[slot].set(z), caches, lane_zero
        )
        return caches, buffer, pos, plen, row_cap, n_gen, done, rng

    def admit_group(state, free_slots):
        """Admit up to ``len(free_slots)`` queued requests in fused
        waves: one `_make_admit` call per prompt bucket, each group
        padded to a power of two to bound the compile count at
        O(buckets x log2(batch))."""
        if prefill == "stream":
            for slot in free_slots:
                if queue:
                    state = admit_stream(state, slot)
            return state
        picked = []  # (slot, req_idx, tokens, cap, key, bucket)
        for slot in free_slots:
            if not queue:
                break
            req_idx, tokens, cap = queue.pop(0)
            slot_req[slot] = req_idx
            min_left[slot] = cap - 1
            bucket = min(
                1 << (int(tokens.size) - 1).bit_length(), config.max_seq
            )
            # The documented per-admission key chain: one split per
            # admitted request, in admission order, regardless of how
            # admissions group into waves.
            adm_rng["key"], key = jax.random.split(adm_rng["key"])
            picked.append((slot, req_idx, tokens, cap, key, bucket))
        for bucket in sorted({p[5] for p in picked}):
            group = [p for p in picked if p[5] == bucket]
            g = 1 << (len(group) - 1).bit_length()  # pad to power of two
            rows = np.full((g, length), pad, np.int32)
            padded = np.full((g, bucket), pad, np.int32)
            plens = np.ones(g, np.int32)
            slots = np.full(g, batch, np.int32)  # OOB rows are dropped
            caps_in = np.ones(g, np.int32)
            keys = [jax.random.PRNGKey(0)] * g
            for r, (slot, _, tokens, cap, key, _) in enumerate(group):
                rows[r, : tokens.size] = tokens
                padded[r, : tokens.size] = tokens
                plens[r] = tokens.size
                slots[r] = slot
                caps_in[r] = cap
                keys[r] = key
            wave = _make_admit(
                decoder, float(temperature), top_k, eos_token_id,
                int(batch), int(bucket), int(g),
            )
            state = wave(
                params, state, jnp.asarray(rows), jnp.asarray(padded),
                jnp.asarray(plens), jnp.asarray(slots),
                jnp.asarray(caps_in), jnp.stack(keys),
            )
            _count("prefill_passes")
        return state

    state = (
        caches, jnp.asarray(buffer), jnp.asarray(pos), jnp.asarray(plen),
        jnp.asarray(row_cap), jnp.asarray(n_gen), jnp.asarray(done), rng,
    )
    state = admit_group(state, list(range(batch)))

    while True:
        # Run as many sync chunks as the host can PROVE are finish-free
        # before paying a blocking fetch: with no EOS the per-slot budget
        # bound is exact, so fetches happen only at boundaries where a
        # request can actually complete.  With EOS the loop always stays
        # at one chunk per fetch — a slot can finish any step, and
        # multi-chunking would keep stepping frozen rows for up to the
        # residual cap after every live row has stopped.
        active = [s for s in range(batch) if slot_req[s] >= 0]
        chunks = 1
        if eos_token_id is None:
            # Without EOS the budget bound is exact, so this skips only
            # provably finish-free fetches.  With EOS a slot can finish
            # any step, and multi-chunking would keep stepping frozen
            # rows for up to the residual cap after every live row has
            # stopped — one chunk per fetch stays the honest choice.
            bound = min((min_left[s] for s in active), default=1)
            chunks = max(1, -(-bound // sync_steps))
        for _ in range(chunks):
            state = run_steps(params, state)
        _count("device_chunks", chunks)
        for s in active:
            min_left[s] = max(min_left[s] - chunks * sync_steps, 0)
        done_h = np.asarray(state[6])
        _count("sync_fetches")
        finished = [
            s for s in range(batch) if done_h[s] and slot_req[s] >= 0
        ]
        if finished:
            # Bulk-harvest: ONE fetch each of buffer/plen/n_gen per sync
            # boundary instead of three per finished slot — on tunneled
            # backends every fetch is a full host round trip, and this
            # loop's host chatter is the serving throughput floor.
            # Admissions below only mutate freed slots, so the
            # pre-admission snapshot stays valid for the other rows.
            buffer_h = np.asarray(state[1])
            plen_h = np.asarray(state[3])
            n_gen_h = np.asarray(state[5])
            for slot in finished:
                keep = int(plen_h[slot]) + int(n_gen_h[slot])
                outputs[slot_req[slot]] = buffer_h[slot, :keep].copy()
                slot_req[slot] = -1
            if queue:
                state = admit_group(state, finished)
        if not queue and all(r < 0 for r in slot_req):
            break
    return outputs  # type: ignore[return-value]


class ContinuousEngine:
    """Incremental continuous batching for a *resident* model server.

    ``continuous_generate`` serves one closed batch of prompts and
    returns; a serving session needs the same fixed-slot loop held open
    indefinitely, with requests admitted and harvested as they come.
    This class is that loop turned inside out, implementing the worker
    harness's duck-typed serving-engine surface
    (``slots`` / :meth:`admit` / :meth:`step` / :meth:`cancel`):

    * construction loads ``params`` and builds the jitted admission and
      decode programs ONCE (shared, via the same ``_make_admit`` /
      ``_make_run_steps`` caches ``continuous_generate`` compiles
      through, so a session and a batch call with the same shape reuse
      one executable);
    * :meth:`admit` queues a request for a free slot — admissions flush
      in the same fused, bucketed prefill waves as ``continuous_generate``
      (one compiled call per bucket per flush, first token included);
    * :meth:`step` runs ONE ``sync_steps`` decode chunk across every busy
      lane and returns the fresh tokens per request since the last chunk
      — the incremental stream a serving session pushes to its callers,
      so time-to-first-token is one chunk, not end-of-response.

    Numerics are ``continuous_generate``'s exactly: each lane is a vmapped
    batch-1 decode, greedy rows bit-identical to ``generate()`` on
    batch-rounding-invariant backends, and sampled requests draw from the
    dedicated admission key chain.  Buffer width is static
    (``length``, default ``config.max_seq``) — the price of compiling
    once for a session's whole lifetime.

    ``shared_prefix`` turns on shared-prefix prefill reuse for the
    dominant serving shape (a common system prompt ahead of every user
    turn): the prefix is prefilled ONCE at construction into a template
    cache lane, and an admitted prompt that starts with it prefills only
    its suffix on top of that lane — same numerics (greedy outputs stay
    bit-identical to the full-prefill road, asserted against the oracle
    in ``tests/test_continuous.py``), strictly less prefill work
    (``stats["prefill_positions"]``).  A prompt NOT extending the prefix
    silently takes the full-prefill path (``stats["prefix_misses"]``).
    """

    def __init__(
        self,
        model: TransformerLM,
        params: Any,
        *,
        max_batch: int = 4,
        temperature: float = 0.0,
        top_k: int | None = None,
        rng: jax.Array | None = None,
        eos_token_id: int | None = None,
        pad_token_id: int | None = None,
        sync_steps: int = 8,
        max_new_tokens: int = 16,
        length: int | None = None,
        shared_prefix: Sequence[int] | None = None,
    ) -> None:
        decoder = _decode_model(model)
        config = decoder.config
        _require_plain_cache(config, "ContinuousEngine")
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if sync_steps < 1:
            raise ValueError(f"sync_steps must be >= 1, got {sync_steps}")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        if temperature <= 0 and top_k is not None:
            raise ValueError("top_k requires sampling (temperature > 0)")
        if top_k is not None and not 1 <= top_k <= config.vocab_size:
            raise ValueError(
                f"top_k must be in [1, {config.vocab_size}], got {top_k}"
            )
        self._length = int(length or config.max_seq)
        if not 2 <= self._length <= config.max_seq:
            raise ValueError(
                f"length must be in [2, {config.max_seq}], got {self._length}"
            )
        self._decoder = decoder
        self._config = config
        self._params = params
        self._temperature = float(temperature)
        self._top_k = top_k
        self._eos = eos_token_id
        pad = pad_token_id
        if pad is None:
            pad = eos_token_id if eos_token_id is not None else 0
        self._pad = int(pad)
        self._sync = int(sync_steps)
        self._default_cap = int(max_new_tokens)
        self.slots = batch = int(max_batch)

        if rng is None:
            rng = jax.random.PRNGKey(0)
        rng = jnp.array(rng, copy=True)
        lane = init_cache(model, 1)
        caches = jax.tree_util.tree_map(
            lambda leaf: jnp.broadcast_to(
                leaf[None], (batch,) + leaf.shape
            ).copy(),
            lane,
        )
        self._state = (
            caches,
            jnp.full((batch, self._length), self._pad, jnp.int32),
            jnp.zeros(batch, jnp.int32),   # pos
            jnp.ones(batch, jnp.int32),    # plen
            jnp.ones(batch, jnp.int32),    # row_cap
            jnp.zeros(batch, jnp.int32),   # n_gen
            jnp.ones(batch, bool),         # done (empty slots are "done")
            rng,
        )
        self._run_steps = _make_run_steps(
            decoder, self._temperature, top_k, eos_token_id,
            self._length, self._sync, batch,
        )
        self._adm_key = jax.random.fold_in(rng, 0x5E1)
        #: slot -> rid (None = free), and generated tokens already streamed.
        self._slot_rid: list[str | None] = [None] * batch
        self._reported = [0] * batch
        self._rid_slot: dict[str, int] = {}
        #: admissions awaiting a flush: (rid, tokens, cap).
        self._pending: list[tuple[str, np.ndarray, int]] = []
        #: host-loop counters: shared-prefix hit/miss accounting plus the
        #: prefill positions each admission paid (full-prompt bucket on
        #: the slow path, suffix bucket on a prefix hit) — the measurable
        #: "prefill work" the serve_scale bench arm asserts shrinks.
        self.stats: dict[str, int] = {
            "prefix_hits": 0, "prefix_misses": 0, "prefill_positions": 0,
        }
        self._prefix_tokens: np.ndarray | None = None
        self._prefix_lane = None
        if shared_prefix is not None:
            ptoks = np.asarray(shared_prefix, np.int32).reshape(-1)
            if ptoks.size < 1:
                raise ValueError("shared_prefix needs at least one token")
            if ptoks.size + 2 > self._length:
                raise ValueError(
                    f"shared_prefix ({ptoks.size} tokens) leaves no room "
                    f"for a suffix + generation inside the session's "
                    f"static length ({self._length})"
                )
            self._prefix_tokens = ptoks
            # Prefill the shared prefix ONCE per engine (per replica):
            # one exact-length pass on a zero lane, cursor parked at the
            # prefix boundary.  Every prefix-matching admission copies
            # this lane instead of re-running the prefix positions.
            zero = jax.tree_util.tree_map(jnp.zeros_like, lane)
            _logits, mutated = decoder.apply(
                {"params": params, "cache": zero},
                jnp.asarray(ptoks)[None],
                mutable=["cache"],
            )
            self._prefix_lane = _set_cursor(
                mutated["cache"], int(ptoks.size)
            )

    # -- serving-engine surface -------------------------------------------

    def admit(self, rid: str, prompt, params: dict | None = None) -> None:
        """Reserve a lane for one request (flushed at the next step).

        ``params`` may carry ``max_new_tokens``; everything else
        (temperature, top_k, EOS) is session-static — the compiled
        programs key on them.  Raises on malformed prompts, so the
        session rejects the request instead of wedging a lane.
        """
        params = params or {}
        if rid in self._rid_slot or any(p[0] == rid for p in self._pending):
            raise ValueError(f"request id {rid!r} already admitted")
        tokens = np.asarray(prompt, np.int32).reshape(-1)
        if tokens.size < 1:
            raise ValueError("prompt needs at least one token")
        cap = int(params.get("max_new_tokens", self._default_cap))
        if cap < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {cap}")
        if tokens.size + cap > self._length:
            raise ValueError(
                f"prompt + budget ({tokens.size + cap}) exceeds the "
                f"session's static length ({self._length})"
            )
        if len(self._rid_slot) + len(self._pending) >= self.slots:
            raise RuntimeError("no free lane (all slots busy)")
        self._pending.append((rid, tokens, cap))

    def step(self) -> list[dict]:
        """Flush admissions, run one sync chunk, return fresh tokens.

        One event per request with new output since the previous chunk:
        ``{"rid", "tokens": [int, ...], "done": bool}`` — the first
        event includes the admission-prefill token, the final one the
        EOS (when configured), exactly the rows ``continuous_generate``
        would return, just delivered incrementally.
        """
        self._flush_admissions()
        if not self._rid_slot:
            return []
        self._state = self._run_steps(self._params, self._state)
        buffer_h = np.asarray(self._state[1])
        plen_h = np.asarray(self._state[3])
        n_gen_h = np.asarray(self._state[5])
        done_h = np.asarray(self._state[6])
        events: list[dict] = []
        for slot in range(self.slots):
            rid = self._slot_rid[slot]
            if rid is None:
                continue
            total = int(n_gen_h[slot])
            start = int(plen_h[slot]) + self._reported[slot]
            fresh = buffer_h[slot, start: int(plen_h[slot]) + total]
            finished = bool(done_h[slot])
            if fresh.size or finished:
                events.append({
                    "rid": rid,
                    "tokens": [int(t) for t in fresh],
                    "done": finished,
                })
            self._reported[slot] += int(fresh.size)
            if finished:
                self._slot_rid[slot] = None
                self._rid_slot.pop(rid, None)
        return events

    def cancel(self, rid: str) -> None:
        """Free a request's lane early (deadline/disconnect).

        The lane is marked done device-side — the scan freezes it like any
        finished row — and freed for re-admission (which resets the lane's
        cache and buffer anyway).
        """
        self._pending = [p for p in self._pending if p[0] != rid]
        slot = self._rid_slot.pop(rid, None)
        if slot is None:
            return
        caches, buffer, pos, plen, row_cap, n_gen, done, rng = self._state
        self._state = (
            caches, buffer, pos, plen, row_cap, n_gen,
            done.at[slot].set(True), rng,
        )
        self._slot_rid[slot] = None

    def close(self) -> None:
        """Drop device state so the backend can reclaim the cache lanes."""
        self._state = None
        self._pending.clear()
        self._rid_slot.clear()
        self._slot_rid = [None] * self.slots

    @property
    def busy(self) -> int:
        return len(self._rid_slot) + len(self._pending)

    # -- internals ---------------------------------------------------------

    def _shares_prefix(self, tokens: np.ndarray) -> bool:
        """Whether this prompt rides the shared-prefix fast path: it must
        extend the session prefix by at least one token (the suffix pass
        needs a position to read first-token logits from); an equal or
        mismatched prompt falls back to the full-prefill road."""
        prefix = self._prefix_tokens
        return (
            prefix is not None
            and tokens.size > prefix.size
            and bool(np.array_equal(tokens[: prefix.size], prefix))
        )

    def _flush_admissions(self) -> None:
        """Admit pending requests in fused bucketed waves (one compiled
        call per bucket per path), mirroring ``continuous_generate``'s
        ``admit_group`` — including the per-admission key chain, which is
        split in admission order BEFORE the prefix partition so sampled
        streams draw identically whichever prefill road they take.

        Prompts sharing the session's ``shared_prefix`` prefill only
        their suffix on top of the once-computed prefix lane
        (``_make_prefix_admit``); everything else — including a
        mismatched prefix — takes the full-prompt wave unchanged.
        """
        if not self._pending:
            return
        free = [s for s in range(self.slots) if self._slot_rid[s] is None]
        picked: list[tuple[int, np.ndarray, int, Any, int]] = []
        picked_prefix: list[tuple[int, np.ndarray, int, Any, int]] = []
        prefix_len = (
            0 if self._prefix_tokens is None else self._prefix_tokens.size
        )
        while self._pending and free:
            rid, tokens, cap = self._pending.pop(0)
            slot = free.pop(0)
            self._slot_rid[slot] = rid
            self._rid_slot[rid] = slot
            self._reported[slot] = 0
            self._adm_key, key = jax.random.split(self._adm_key)
            if self._shares_prefix(tokens):
                # Pad K/V land at cache slots >= prefix_len + suffix
                # length, so the bucket is capped to what fits BEYOND the
                # prefix (admit() already bounded prompt + budget).
                bucket = min(
                    1 << (int(tokens.size) - prefix_len - 1).bit_length(),
                    self._config.max_seq - prefix_len,
                )
                self.stats["prefix_hits"] += 1
                self.stats["prefill_positions"] += bucket
                picked_prefix.append((slot, tokens, cap, key, bucket))
            else:
                bucket = min(
                    1 << (int(tokens.size) - 1).bit_length(),
                    self._config.max_seq,
                )
                if self._prefix_tokens is not None:
                    self.stats["prefix_misses"] += 1
                self.stats["prefill_positions"] += bucket
                picked.append((slot, tokens, cap, key, bucket))
        for bucket in sorted({p[4] for p in picked}):
            group = [p for p in picked if p[4] == bucket]
            g = 1 << (len(group) - 1).bit_length()
            rows = np.full((g, self._length), self._pad, np.int32)
            padded = np.full((g, bucket), self._pad, np.int32)
            plens = np.ones(g, np.int32)
            slots = np.full(g, self.slots, np.int32)  # OOB rows dropped
            caps_in = np.ones(g, np.int32)
            keys = [jax.random.PRNGKey(0)] * g
            for r, (slot, tokens, cap, key, _) in enumerate(group):
                rows[r, : tokens.size] = tokens
                padded[r, : tokens.size] = tokens
                plens[r] = tokens.size
                slots[r] = slot
                caps_in[r] = cap
                keys[r] = key
            wave = _make_admit(
                self._decoder, self._temperature, self._top_k, self._eos,
                int(self.slots), int(bucket), int(g),
            )
            self._state = wave(
                self._params, self._state, jnp.asarray(rows),
                jnp.asarray(padded), jnp.asarray(plens),
                jnp.asarray(slots), jnp.asarray(caps_in), jnp.stack(keys),
            )
        for bucket in sorted({p[4] for p in picked_prefix}):
            group = [p for p in picked_prefix if p[4] == bucket]
            g = 1 << (len(group) - 1).bit_length()
            rows = np.full((g, self._length), self._pad, np.int32)
            padded = np.full((g, bucket), self._pad, np.int32)
            slens = np.ones(g, np.int32)
            slots = np.full(g, self.slots, np.int32)  # OOB rows dropped
            caps_in = np.ones(g, np.int32)
            keys = [jax.random.PRNGKey(0)] * g
            for r, (slot, tokens, cap, key, _) in enumerate(group):
                suffix = tokens[prefix_len:]
                rows[r, : tokens.size] = tokens
                padded[r, : suffix.size] = suffix
                slens[r] = suffix.size
                slots[r] = slot
                caps_in[r] = cap
                keys[r] = key
            wave = _make_prefix_admit(
                self._decoder, self._temperature, self._top_k, self._eos,
                int(self.slots), int(bucket), int(g), int(prefix_len),
            )
            self._state = wave(
                self._params, self._state, self._prefix_lane,
                jnp.asarray(rows), jnp.asarray(padded),
                jnp.asarray(slens), jnp.asarray(slots),
                jnp.asarray(caps_in), jnp.stack(keys),
            )


def lm_engine_factory(model: TransformerLM, params: Any, **engine_kwargs):
    """A zero-arg serving-session factory for an LM.

    The returned closure is what ``serving.open_session`` cloudpickles
    into the CAS; called inside the resident worker it builds the
    :class:`ContinuousEngine` (loading params and compiling the decode/
    prefill programs ONCE for the session's lifetime).  Note cloudpickle
    serializes this module by *reference* — workers must be able to
    import the package (or the caller registers it by value via
    ``cloudpickle.register_pickle_by_value``).
    """
    def factory() -> ContinuousEngine:
        return ContinuousEngine(model, params, **engine_kwargs)

    return factory
