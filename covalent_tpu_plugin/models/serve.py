"""Continuous batching: a fixed-slot serving loop with rolling admission.

Plain ``generate()`` batches a FIXED set of prompts: every row starts and
(effectively) finishes together, so a 10-token answer waits for the
500-token answer sharing its batch.  Production serving (vLLM-style)
instead runs a fixed number of SLOTS and admits a new request the moment
a slot finishes — no request waits on an unrelated long one, and the
accelerator never idles while work is queued.  The reference plugin has
no serving path at all (SURVEY §2; reference ``ssh.py`` runs opaque
pickled callables); this is a beyond-parity subsystem.

TPU-native design — the pieces map to the compilation model:

* **Static shapes.** ``max_batch`` slots and one (B, L) token buffer,
  compiled once.  Finished slots keep stepping on frozen tokens (their
  logits are ignored) — the standard static-shape trade.
* **Per-slot cache via vmap.**  Each slot owns a lane of a vmapped KV
  cache, so per-slot cursors, rotary offsets, and masks come from
  ``jax.vmap`` over the single-row decode step — no scalar-cursor
  surgery in the model.  A lane's numerics are exactly a batch-1
  ``generate()``'s (no cross-batch reductions anywhere), which is what
  makes the bit-equality oracle in the tests possible.  Caveat shared
  with plain batched ``generate()``: on backends whose batched-matmul
  tiling rounds differently than the batch-1 shape (TPU MXU at bf16),
  near-tie argmaxes can flip vs the batch-1 oracle — benchmarks/
  serve_bench.py reports both arms' agreement to make the attribution
  visible; on CPU (f32 and bf16) equality is bit-exact.
* **Admission at scan boundaries.**  The device runs ``sync_steps``
  decode steps per jitted call (``lax.scan``); the host only looks at
  the tiny (B,) state vectors between calls, harvests finished rows,
  zeroes their cache lanes, and writes the next queued prompt into the
  slot.  One host round-trip per ``sync_steps`` tokens instead of one
  per token — the knob trades admission latency against host chatter
  (tunnelled TPUs want it large).
* **Bucketed batched prefill at admission** (``prefill="batched"``, the
  default).  An admitted prompt runs ONE single-lane prefill pass padded
  to a power-of-two bucket, then enters the shared decode loop — time to
  first token is one pass, not ``len(prompt)`` interleaved steps.  The
  padding trick is exact: pad K/V land at slots ``>= len(prompt)``, the
  cursor is rewound to ``len(prompt)``, and the causal mask only ever
  exposes slot ``k`` to queries at positions ``>= k`` — by which step
  the decode loop has overwritten it with the real token's K/V.
  Compiles one prefill per bucket size (a handful for a whole serving
  mix).  ``prefill="stream"`` keeps the zero-extra-compiles chunk-1
  interleave: the prompt streams through the shared step loop one token
  per step.

Greedy and temperature/top-k sampling are supported; EOS finishes a slot
early.  Sampling note: greedy outputs are identical across prefill
modes, but SAMPLED outputs are not reproducible across them — batched
admission draws each first token from a dedicated admission key chain
(``fold_in(rng, 0x5E1)``) while streaming draws it from the shared loop
stream; pin ``prefill`` as well as ``rng`` for reproducible sampling.
``rolling_cache`` models are refused (slot reset assumes the plain
cache layout).
"""

from __future__ import annotations

import collections
import functools
import hashlib
import os
import pickle
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .decode import _decode_model, _filter_top_k, init_cache
from .quant import SERVING_MODES, mode_variant
from .speculative import _set_cursor, make_lane_spec_round
from .transformer import TransformerLM

#: Wire format version of a serialized KV bundle (prefill_only's output).
KV_BUNDLE_VERSION = 1

#: Environment knob bounding the NAMED slots of a multi-adapter bank
#: (the identity base rides an extra slot 0 on top of this).
ADAPTERS_MAX_ENV = "COVALENT_TPU_SERVE_ADAPTERS_MAX"


class RollingCacheUnsupported(ValueError):
    """Typed refusal: continuous serving assumes the plain cache layout.

    ``rolling_cache`` models ring-rotate their KV slots, and the slot-reset
    trick at admission (zero the lane, rewind the cursor) assumes the plain
    append-only layout.  A :class:`ValueError` subclass for back-compat,
    duck-tagged for the dispatch layers: the serving RPC surfaces this as a
    PERMANENT fault (``fault_label``/``fault_transient`` — the resilience
    classifier's self-classification hook), so a misconfigured session is
    refused once instead of burning gang retries on a deterministic error.
    """

    fault_label = "serve_model_unsupported"
    fault_transient = False


class AdapterUnsupported(ValueError):
    """Typed refusal: this engine cannot host the requested adapter set.

    Raised for deterministic construction/attach errors — a model that
    already carries adapters (the quant.py contract: quantize the base
    first, then attach — ``lora.quantize_then_lora``), a rank/shape
    geometry that does not match the bank template, an exhausted bank.
    Duck-tagged PERMANENT like :class:`RollingCacheUnsupported`, so the
    dispatch layers refuse once instead of burning gang retries.
    """

    fault_label = "serve_model_unsupported"
    fault_transient = False


class _AdapterDecoder:
    """Hashable decode-model wrapper resolving a per-lane adapter index
    against a stacked adapter bank INSIDE the compiled programs.

    With a bank configured, the serving state wraps each cache lane as
    ``{"kv": <model cache>, "adapter": <int32 bank slot>}`` and the
    params as ``{"base": [non-adapter leaves], "bank": [stacked adapter
    leaves, each (n_slots, ...)]}``.  ``apply`` gathers every bank leaf
    at the lane's slot (``jnp.take(leaf, idx, axis=0)`` — a batched
    gather under the serving loop's vmap), reassembles the full LoRA
    tree, and delegates to the wrapped decoder on the inner cache; the
    adapter index rides the returned cache untouched.  The wrapper
    hashes on ``(decoder, treedef, mask)``, so the jitted factory
    caches (:func:`_make_run_steps` and friends) treat it exactly like
    a plain decoder static — ONE compiled step serves every adapter,
    and attaching a new adapter is a bank scatter, never a recompile.
    """

    __slots__ = ("decoder", "treedef", "mask")

    def __init__(self, decoder, treedef, mask) -> None:
        self.decoder = decoder
        self.treedef = treedef
        self.mask = tuple(bool(m) for m in mask)

    @property
    def config(self):
        return self.decoder.config

    def __eq__(self, other) -> bool:
        return (
            type(other) is _AdapterDecoder
            and self.decoder == other.decoder
            and self.treedef == other.treedef
            and self.mask == other.mask
        )

    def __hash__(self) -> int:
        return hash((self.decoder, self.treedef, self.mask))

    def _merge(self, params, idx):
        base = iter(params["base"])
        bank = iter(params["bank"])
        leaves = [
            jnp.take(next(bank), idx, axis=0) if m else next(base)
            for m in self.mask
        ]
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    def apply(self, variables, tokens, mutable=()):
        cache = variables["cache"]
        merged = self._merge(variables["params"], cache["adapter"])
        out = self.decoder.apply(
            {"params": merged, "cache": cache["kv"]}, tokens,
            mutable=mutable,
        )
        if mutable:
            logits, mutated = out
            return logits, {"cache": {
                "kv": mutated["cache"], "adapter": cache["adapter"],
            }}
        return out


def _require_plain_cache(config, what: str) -> None:
    if config.rolling_cache:
        raise RollingCacheUnsupported(
            f"{what} does not support rolling_cache models "
            "(slot reset assumes the plain cache layout)"
        )


def _choose_tokens(logits, key, temperature, top_k):
    """Shared greedy/sampling rule for the loop and the prefill."""
    logits = logits.astype(jnp.float32)
    if temperature > 0:
        scaled = logits / temperature
        if top_k is not None:
            scaled = _filter_top_k(scaled, top_k)
        return jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


@functools.lru_cache(maxsize=64)
def _make_admit(decoder, temperature, top_k, eos_token_id, batch, bucket, g,
                adapters=False):
    """One fused, donated admission wave: batch-prefill ``g`` prompts and
    scatter their cache lanes, buffer rows, and cursors in a SINGLE
    compiled call.

    Round 4's serving wall loss traced to admission overhead: every
    admitted request paid its own single-lane prefill dispatch plus one
    eager ``.at[slot].set`` per cache leaf (each a full-tree device
    copy).  Here the whole wave is one executable with the serving state
    donated, so XLA updates the caches in place and the prefill runs as
    ONE (g, bucket) batched pass — admission cost scales with waves, not
    requests.

    Exactness of the padded pass: pad positions' K/V land at slots
    >= plen; with the cursor rewound to ``plen`` they are dead until the
    decode loop overwrites them (the causal mask shows slot k only to
    queries at positions >= k) — same trick as speculative decoding's
    cache rewind (models/speculative.py).  Rows whose ``slots`` entry is
    out of range (the group padded up to a power of two) are dropped by
    the scatters (``mode="drop"``), so padding never touches live state.

    With ``adapters=True`` (a multi-adapter bank: the cache lanes are
    ``{"kv": ..., "adapter": ...}`` wraps and ``decoder`` is an
    :class:`_AdapterDecoder`) the wave takes one extra ``aidxs (g,)``
    argument — each row's bank slot, written into its zero lane BEFORE
    the prefill so the pass gathers that adapter's weights.  Mixed
    adapters co-batch in one wave; the plain signature is untouched.
    """

    def _wave(params, state, rows, padded, plens, slots, caps_in, keys,
              aidxs):
        # rows (g, length) full buffer rows; padded (g, bucket) prompt
        # tokens; plens/caps_in/slots (g,); keys (g, 2) admission keys.
        caches, buffer, pos, plen, row_cap, n_gen, done, rng = state

        def lane_prefill(tokens, pl, key, aidx):
            zero = jax.tree_util.tree_map(
                lambda c: jnp.zeros(c.shape[1:], c.dtype), caches
            )
            if adapters:
                zero = {**zero, "adapter": aidx}
            logits, mutated = decoder.apply(
                {"params": params, "cache": zero}, tokens[None],
                mutable=["cache"],
            )
            cache = _set_cursor(mutated["cache"], pl)
            last = jnp.take_along_axis(
                logits, (pl - 1)[None, None, None], axis=1
            )[0, 0]  # (V,)
            first = _choose_tokens(
                last[None, :], key, temperature, top_k
            )[0]
            return cache, first

        new_lanes, firsts = jax.vmap(lane_prefill)(padded, plens, keys,
                                                   aidxs)
        caches = jax.tree_util.tree_map(
            lambda c, nl: c.at[slots].set(nl, mode="drop"),
            caches, new_lanes,
        )
        rows = rows.at[jnp.arange(g), plens].set(firsts)
        buffer = buffer.at[slots].set(rows, mode="drop")
        pos = pos.at[slots].set(plens, mode="drop")
        plen = plen.at[slots].set(plens, mode="drop")
        row_cap = row_cap.at[slots].set(caps_in, mode="drop")
        n_gen = n_gen.at[slots].set(
            jnp.ones((g,), jnp.int32), mode="drop"
        )
        fin = caps_in <= 1
        if eos_token_id is not None:
            fin = fin | (firsts == eos_token_id)
        done = done.at[slots].set(fin, mode="drop")
        return caches, buffer, pos, plen, row_cap, n_gen, done, rng

    if adapters:
        @functools.partial(jax.jit, donate_argnums=(1,))
        def admit_wave(params, state, rows, padded, plens, slots, caps_in,
                       keys, aidxs):
            return _wave(params, state, rows, padded, plens, slots,
                         caps_in, keys, aidxs)

        return admit_wave

    @functools.partial(jax.jit, donate_argnums=(1,))
    def admit_wave(params, state, rows, padded, plens, slots, caps_in,
                   keys):
        return _wave(params, state, rows, padded, plens, slots, caps_in,
                     keys, jnp.zeros((g,), jnp.int32))

    return admit_wave


@functools.lru_cache(maxsize=64)
def _make_prefix_admit(decoder, temperature, top_k, eos_token_id, batch,
                       bucket, g, prefix_len):
    """Fused admission wave for prompts sharing the session's prefilled
    prefix: every lane starts from the SHARED prefix cache lane (computed
    once per engine) and prefills only its suffix, padded to ``bucket``.

    This is the shared-prefix fast path: on the dominant traffic shape —
    a common system prompt ahead of a short user turn — per-request
    prefill work drops from ``bucket(prompt)`` to ``bucket(suffix)``
    positions.  Exactness is the same two tricks the full-prefill wave
    uses, shifted by ``prefix_len``: the suffix pass appends K/V at the
    prefix cursor (queries at absolute position ``prefix_len + j`` see
    the cached prefix plus the causal suffix — exactly what one full
    pass computes for those positions), and pad K/V land at slots
    ``>= prefix_len + suffix_len`` where the rewound cursor keeps them
    dead until the decode loop overwrites them.  ``prefix_lane`` rides
    as a traced argument (broadcast across the vmapped lanes), so one
    compiled wave serves every prefix of the same length.
    """

    @functools.partial(jax.jit, donate_argnums=(1,))
    def admit_wave(params, state, prefix_lane, rows, padded, slens, slots,
                   caps_in, keys):
        # rows (g, length) full buffer rows (prefix + suffix); padded
        # (g, bucket) SUFFIX tokens; slens (g,) suffix lengths;
        # slots/caps_in (g,); keys (g, 2) admission keys.
        caches, buffer, pos, plen, row_cap, n_gen, done, rng = state

        def lane_prefill(tokens, sl, key):
            logits, mutated = decoder.apply(
                {"params": params, "cache": prefix_lane}, tokens[None],
                mutable=["cache"],
            )
            cache = _set_cursor(mutated["cache"], prefix_len + sl)
            last = jnp.take_along_axis(
                logits, (sl - 1)[None, None, None], axis=1
            )[0, 0]  # (V,)
            first = _choose_tokens(
                last[None, :], key, temperature, top_k
            )[0]
            return cache, first

        new_lanes, firsts = jax.vmap(lane_prefill)(padded, slens, keys)
        plens = prefix_len + slens
        caches = jax.tree_util.tree_map(
            lambda c, nl: c.at[slots].set(nl, mode="drop"),
            caches, new_lanes,
        )
        rows = rows.at[jnp.arange(g), plens].set(firsts)
        buffer = buffer.at[slots].set(rows, mode="drop")
        pos = pos.at[slots].set(plens, mode="drop")
        plen = plen.at[slots].set(plens, mode="drop")
        row_cap = row_cap.at[slots].set(caps_in, mode="drop")
        n_gen = n_gen.at[slots].set(
            jnp.ones((g,), jnp.int32), mode="drop"
        )
        fin = caps_in <= 1
        if eos_token_id is not None:
            fin = fin | (firsts == eos_token_id)
        done = done.at[slots].set(fin, mode="drop")
        return caches, buffer, pos, plen, row_cap, n_gen, done, rng

    return admit_wave


@functools.lru_cache(maxsize=32)
def _make_kv_admit(eos_token_id, batch, g):
    """Fused scatter for admissions whose prefill already happened
    elsewhere (an imported KV bundle): no decoder pass at all — the wave
    only scatters the imported cache lanes, buffer rows (first generated
    token included, computed by the *prefill* tier), cursors, and budgets
    into the donated serving state.  ``mode="drop"`` pads exactly like
    the prefill waves."""

    @functools.partial(jax.jit, donate_argnums=(0,))
    def admit_wave(state, new_lanes, rows, plens, firsts, slots, caps_in):
        caches, buffer, pos, plen, row_cap, n_gen, done, rng = state
        caches = jax.tree_util.tree_map(
            lambda c, nl: c.at[slots].set(nl, mode="drop"),
            caches, new_lanes,
        )
        buffer = buffer.at[slots].set(rows, mode="drop")
        pos = pos.at[slots].set(plens, mode="drop")
        plen = plen.at[slots].set(plens, mode="drop")
        row_cap = row_cap.at[slots].set(caps_in, mode="drop")
        n_gen = n_gen.at[slots].set(
            jnp.ones((g,), jnp.int32), mode="drop"
        )
        fin = caps_in <= 1
        if eos_token_id is not None:
            fin = fin | (firsts == eos_token_id)
        done = done.at[slots].set(fin, mode="drop")
        return caches, buffer, pos, plen, row_cap, n_gen, done, rng

    return admit_wave


@functools.lru_cache(maxsize=32)
def _make_draft_admit(draft_decoder, batch, bucket, g):
    """Fused DRAFT-cache admission wave for speculative decoding: one
    batched full-prompt prefill through the draft model, lanes scattered
    into the donated draft cache stack.

    Always full-prompt (the draft skips the prefix tree — its prefill is
    a small fraction of the target's and sharing lanes across two models
    would double the tree's memory for little win).  Stale positions past
    the rewound cursor stay dead until the first spec round's repair slab
    overwrites them — the admission waves' usual exactness argument.
    """

    @functools.partial(jax.jit, donate_argnums=(1,))
    def admit_wave(d_params, dcaches, padded, plens, slots):
        def lane_prefill(tokens, pl):
            zero = jax.tree_util.tree_map(
                lambda c: jnp.zeros(c.shape[1:], c.dtype), dcaches
            )
            _, mutated = draft_decoder.apply(
                {"params": d_params, "cache": zero}, tokens[None],
                mutable=["cache"],
            )
            return _set_cursor(mutated["cache"], pl)

        lanes = jax.vmap(lane_prefill)(padded, plens)
        return jax.tree_util.tree_map(
            lambda c, nl: c.at[slots].set(nl, mode="drop"), dcaches, lanes
        )

    return admit_wave


@functools.lru_cache(maxsize=32)
def _make_spec_run_steps(decoder, draft_decoder, eos_token_id, length,
                         draft_len, rounds, batch):
    """Jitted speculative serving chunk: ``rounds`` draft-and-verify
    rounds across every lane per compiled call (cached on its statics,
    like :func:`_make_run_steps`).

    Each round is :func:`..speculative.make_lane_spec_round` vmapped over
    the slots — the verify slab is ONE fused target pass per wave, every
    lane's ``draft_len + 1`` candidate positions scored together.  The
    serving state AND the draft cache stack are donated; the returned
    ``(proposed, accepted)`` counters are the chunk's summed draft
    agreement (the accept-rate numerator/denominator the serving metrics
    export).  The rng chain rides untouched: the continuous spec path is
    greedy-only (the engine refuses a draft on sampled sessions), so
    unlike :func:`_make_run_steps` no keys are consumed.
    """
    lane_round = make_lane_spec_round(
        decoder, draft_decoder, eos_token_id, length, draft_len
    )

    def one_round(params, draft_params, carry, _):
        state, dcaches, proposed, accepted = carry
        caches, buffer, pos, plen, row_cap, n_gen, done, rng = state
        (caches, dcaches, buffer, pos, n_gen, done, prop, acc) = jax.vmap(
            lane_round, in_axes=(None, None, 0, 0, 0, 0, 0, 0, 0)
        )(params, draft_params, caches, dcaches, buffer, pos, row_cap,
          n_gen, done)
        state = (caches, buffer, pos, plen, row_cap, n_gen, done, rng)
        return (
            state, dcaches,
            proposed + jnp.sum(prop), accepted + jnp.sum(acc),
        ), None

    @functools.partial(jax.jit, donate_argnums=(2, 3))
    def run_steps(params, draft_params, state, dcaches):
        (state, dcaches, proposed, accepted), _ = jax.lax.scan(
            functools.partial(one_round, params, draft_params),
            (state, dcaches, jnp.zeros((), jnp.int32),
             jnp.zeros((), jnp.int32)),
            None, length=rounds,
        )
        return state, dcaches, proposed, accepted

    return run_steps


@functools.lru_cache(maxsize=64)
def _make_lane_prefill(decoder, temperature, top_k, bucket):
    """Standalone single-lane full prefill (``prefill_only``'s slow path).

    Structurally the SAME computation as ``_make_admit``'s inner
    ``lane_prefill`` — bucketed pass on a zero lane, cursor rewind, first
    token from the last real position — vmapped over a leading dim of 1
    so the compiled program matches the admission wave's lane exactly
    (the bit-equality contract between a disaggregated prefill and the
    in-place admission path rests on it)."""

    @jax.jit
    def prefill(params, lane_zero, padded, plens, keys):
        # padded (1, bucket); plens (1,); keys (1, 2).
        def lane_prefill(tokens, pl, key):
            logits, mutated = decoder.apply(
                {"params": params, "cache": lane_zero}, tokens[None],
                mutable=["cache"],
            )
            cache = _set_cursor(mutated["cache"], pl)
            last = jnp.take_along_axis(
                logits, (pl - 1)[None, None, None], axis=1
            )[0, 0]
            first = _choose_tokens(
                last[None, :], key, temperature, top_k
            )[0]
            return cache, first

        lanes, firsts = jax.vmap(lane_prefill)(padded, plens, keys)
        return (
            jax.tree_util.tree_map(lambda c: c[0], lanes), firsts[0]
        )

    return prefill


@functools.lru_cache(maxsize=64)
def _make_lane_prefix_prefill(decoder, temperature, top_k, bucket,
                              prefix_len):
    """Standalone single-lane suffix prefill on a cached prefix lane
    (``prefill_only``'s fast path), mirroring ``_make_prefix_admit``'s
    inner lane the same way :func:`_make_lane_prefill` mirrors the full
    wave."""

    @jax.jit
    def prefill(params, prefix_lane, padded, slens, keys):
        # padded (1, bucket) SUFFIX tokens; slens (1,); keys (1, 2).
        def lane_prefill(tokens, sl, key):
            logits, mutated = decoder.apply(
                {"params": params, "cache": prefix_lane}, tokens[None],
                mutable=["cache"],
            )
            cache = _set_cursor(mutated["cache"], prefix_len + sl)
            last = jnp.take_along_axis(
                logits, (sl - 1)[None, None, None], axis=1
            )[0, 0]
            first = _choose_tokens(
                last[None, :], key, temperature, top_k
            )[0]
            return cache, first

        lanes, firsts = jax.vmap(lane_prefill)(padded, slens, keys)
        return (
            jax.tree_util.tree_map(lambda c: c[0], lanes), firsts[0]
        )

    return prefill


def _tokens_digest(tokens: np.ndarray) -> str:
    """Content key of a token prefix (the prefix tree's index)."""
    return hashlib.sha256(
        np.ascontiguousarray(tokens, np.int32).tobytes()
    ).hexdigest()


class _PrefixEntry:
    """One cached KV lane: the exact tokens it prefilled, cursor parked
    at ``tokens.size``.  ``pinned`` marks the constructor-supplied
    ``shared_prefix`` template, exempt from LRU eviction.  ``aslot`` is
    the adapter bank slot whose weights computed the lane (0 = base) —
    a lane is only ever reused under the SAME adapter, because K/V from
    another adapter's weights would silently corrupt the stream."""

    __slots__ = ("tokens", "lane", "pinned", "aslot")

    def __init__(self, tokens: np.ndarray, lane: Any, pinned: bool,
                 aslot: int = 0) -> None:
        self.tokens = tokens
        self.lane = lane
        self.pinned = pinned
        self.aslot = aslot


@functools.lru_cache(maxsize=32)
def _make_run_steps(decoder, temperature, top_k, eos_token_id,
                    length, sync_steps, batch):
    """Jitted ``sync_steps``-long serving scan, cached on its statics.

    A per-call ``@jax.jit`` over a closure would retrace and recompile
    the whole scanned model on EVERY ``continuous_generate`` call (jit
    caches key on the function object); caching the compiled callable on
    the hashable statics (the flax module itself plus the loop
    constants) makes repeat calls with the same serving shape reuse one
    executable, like ``generate()`` under a caller's jit.  ``params``
    ride as a traced argument.
    """
    rows = jnp.arange(batch)

    def choose(logits, key):
        return _choose_tokens(logits, key, temperature, top_k)

    def one_step(params, state, _):
        caches, buffer, pos, plen, row_cap, n_gen, done, rng = state

        def row_step(cache, token):
            logits, mutated = decoder.apply(
                {"params": params, "cache": cache}, token[None, :],
                mutable=["cache"],
            )
            return mutated["cache"], logits[0, -1]

        token = jnp.take_along_axis(buffer, pos[:, None], axis=1)  # (B, 1)
        caches, logits = jax.vmap(row_step)(caches, token)
        rng, key = jax.random.split(rng)
        nxt = choose(logits, key)  # (B,)
        in_prompt = (pos + 1) < plen
        write_idx = jnp.minimum(pos + 1, length - 1)
        prompt_next = buffer[rows, write_idx]
        gen_now = (~in_prompt) & (~done)
        # Prompt rows "write back" their own next token (a no-op), so one
        # scatter serves streaming prefill and decode alike.
        buffer = buffer.at[rows, write_idx].set(
            jnp.where(gen_now, nxt, prompt_next)
        )
        n_gen = n_gen + gen_now.astype(jnp.int32)
        if eos_token_id is not None:
            done = done | (gen_now & (nxt == eos_token_id))
        done = done | (n_gen >= row_cap)
        # Frozen rows hold position (their lane keeps stepping on the
        # same token; logits are ignored, cache writes past the row's
        # used region are reset at admission).
        pos = jnp.where(done, pos, pos + 1)
        return (caches, buffer, pos, plen, row_cap, n_gen, done, rng), None

    @functools.partial(jax.jit, donate_argnums=(1,))
    def run_steps(params, state):
        # State donation lets XLA update the (B, layers, S, ...) caches in
        # place: without it every sync chunk copies the full serving
        # state tree host-visibly, which round 4's wall numbers showed
        # dominating the toy-scale loop.
        state, _ = jax.lax.scan(
            functools.partial(one_step, params), state, None,
            length=sync_steps,
        )
        return state

    return run_steps


def step_accounting(
    caps: Sequence[int], max_batch: int, sync_steps: int
) -> dict[str, int]:
    """Structural decode-step accounting for a serving workload: the
    device-step counts that static wave batching and this module's
    continuous loop pay for per-request budgets ``caps``, independent of
    model size or transport.  One shared model for every artifact
    (``bench.py`` ``lm_serve`` and ``benchmarks/serve_bench.py``) so the
    accounting cannot drift from the admission rule implemented above.

    Per-request cost is ``cap - 1`` decode steps (admission prefill
    yields the first token; prefill passes are counted separately by the
    callers).  Static: requests run in arrival-order waves of
    ``max_batch``, each wave to its LONGEST member's budget.
    Continuous: greedy slot packing in arrival order; a freed slot
    re-admits only at the next ``sync_steps`` boundary (the
    quantization ``continuous_generate``'s host loop actually pays),
    with ``continuous_steps_ideal`` the unquantized packing bound.
    """
    caps = [int(c) for c in caps]
    waves = [
        caps[i:i + max_batch] for i in range(0, len(caps), max_batch)
    ]
    static = sum(max(w) - 1 for w in waves)
    ideal = [0] * max_batch
    free_at = [0] * max_batch
    finish = [0] * max_batch
    for cap in caps:
        k = min(range(max_batch), key=lambda j: ideal[j])
        ideal[k] += cap - 1
        k = min(range(max_batch), key=lambda j: free_at[j])
        finish[k] = free_at[k] + cap - 1
        free_at[k] = -(-finish[k] // sync_steps) * sync_steps
    return {
        "static_wave_steps": static,
        "continuous_steps_ideal": max(ideal),
        "continuous_steps_sync": max(finish),
    }


def continuous_generate(
    model: TransformerLM,
    params: Any,
    prompts: Sequence[np.ndarray],
    max_new_tokens: int | Sequence[int],
    *,
    max_batch: int = 4,
    temperature: float = 0.0,
    top_k: int | None = None,
    rng: jax.Array | None = None,
    eos_token_id: int | None = None,
    pad_token_id: int | None = None,
    sync_steps: int = 8,
    prefill: str = "batched",
    stats: dict | None = None,
) -> list[np.ndarray]:
    """Serve ``prompts`` (each a 1-D int32 array) through ``max_batch``
    continuously-refilled slots; returns one trimmed output sequence per
    prompt, in the input order.

    Each output is ``prompt + generated`` where generation stops at
    the request's token budget or its EOS (the EOS token is included).
    ``max_new_tokens`` is one shared budget (int) or one per request —
    mixed-length workloads are continuous batching's home turf: a slot
    whose request hits its own budget is refilled immediately instead of
    idling until the longest request in a static batch finishes.  Greedy
    rows are bit-identical to ``generate(model, params, prompt[None],
    cap_i)`` on batch-rounding-invariant backends (CPU f32/bf16; see the
    module docstring for the TPU-bf16 caveat shared with plain batched
    decode) — admission order cannot change tokens, only latency.

    ``stats``, when given, is filled with host-loop counters:
    ``prefill_passes`` (fused admission waves dispatched — the cost that
    was one pass PER REQUEST before round 5), ``sync_fetches`` (blocking
    host round-trips), and ``device_chunks`` (``sync_steps``-long scans
    dispatched).
    """
    config = _decode_model(model).config
    _require_plain_cache(config, "continuous_generate")
    caps = None
    if isinstance(max_new_tokens, (float, np.floating)):
        max_new_tokens = int(max_new_tokens)  # old int-like float contract
    if not isinstance(max_new_tokens, (int, np.integer)):
        caps = [int(c) for c in max_new_tokens]
        if len(caps) != len(prompts):
            raise ValueError(
                f"per-request max_new_tokens has {len(caps)} entries for "
                f"{len(prompts)} prompts"
            )
        if any(c < 1 for c in caps):
            raise ValueError("every per-request max_new_tokens must be >= 1")
    elif max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    if sync_steps < 1:
        raise ValueError(f"sync_steps must be >= 1, got {sync_steps}")
    if prefill not in ("batched", "stream"):
        raise ValueError(
            f'prefill must be "batched" or "stream", got {prefill!r}'
        )
    if temperature > 0 and rng is None:
        raise ValueError("sampling (temperature > 0) requires rng")
    if temperature <= 0 and top_k is not None:
        raise ValueError("top_k requires sampling (temperature > 0)")
    if top_k is not None and not 1 <= top_k <= config.vocab_size:
        raise ValueError(
            f"top_k must be in [1, {config.vocab_size}], got {top_k}"
        )
    prompts = [np.asarray(p, np.int32).reshape(-1) for p in prompts]
    if not prompts:
        return []
    if any(p.size < 1 for p in prompts):
        raise ValueError("every prompt needs at least one token")
    if caps is None:
        caps = [int(max_new_tokens)] * len(prompts)
    length = max(p.size + c for p, c in zip(prompts, caps))
    if length > config.max_seq:
        raise ValueError(
            f"worst-case prompt + budget ({length}) exceeds "
            f"config.max_seq ({config.max_seq})"
        )
    batch = min(max_batch, len(prompts))
    decoder = _decode_model(model)
    pad = pad_token_id
    if pad is None:
        pad = eos_token_id if eos_token_id is not None else 0
    if rng is None:
        rng = jax.random.PRNGKey(0)
    # The serving state (rng included) is donated to the jitted chunk and
    # admission calls; a private copy keeps the CALLER's key buffer alive
    # for their next call with the same array.
    rng = jnp.array(rng, copy=True)

    # One cache lane per slot: stack B single-row caches.  Lane shape
    # keeps the model's own batch dim of 1, so the vmapped step calls the
    # decoder exactly as a batch-1 generate() would.
    lane = init_cache(model, 1)
    caches = jax.tree_util.tree_map(
        lambda leaf: jnp.broadcast_to(
            leaf[None], (batch,) + leaf.shape
        ).copy(),
        lane,
    )
    lane_zero = jax.tree_util.tree_map(jnp.zeros_like, lane)

    run_steps = _make_run_steps(
        decoder, float(temperature), top_k, eos_token_id,
        int(length), int(sync_steps), int(batch),
    )

    # --- host-side slot management ---------------------------------------
    queue = [
        (i, p, c) for i, (p, c) in enumerate(zip(prompts, caps))
    ]  # (original index, tokens, budget)
    outputs: list[np.ndarray | None] = [None] * len(prompts)
    buffer = np.full((batch, length), pad, np.int32)
    pos = np.zeros(batch, np.int32)
    plen = np.ones(batch, np.int32)
    row_cap = np.ones(batch, np.int32)
    n_gen = np.zeros(batch, np.int32)
    done = np.ones(batch, bool)  # empty slots are "done" until admitted
    slot_req = [-1] * batch  # original request index per slot

    adm_rng = {"key": jax.random.fold_in(rng, 0x5E1)}
    # Host-side lower bound on decode steps until each slot can finish
    # (exact without EOS; with EOS a slot may finish earlier, which only
    # delays its harvest, never corrupts it — frozen rows hold position).
    min_left = [0] * batch
    if stats is not None:
        stats.update(prefill_passes=0, sync_fetches=0, device_chunks=0)

    def _count(key, by=1):
        if stats is not None:
            stats[key] += by

    def admit_stream(state, slot):
        """Streaming admission: the prompt replays through the shared
        step loop one token per step (zero extra compiles)."""
        caches, buffer, pos, plen, row_cap, n_gen, done, rng = state
        req_idx, tokens, cap = queue.pop(0)
        slot_req[slot] = req_idx
        min_left[slot] = tokens.size - 1 + cap
        row = np.full((length,), pad, np.int32)
        row[: tokens.size] = tokens
        buffer = buffer.at[slot].set(jnp.asarray(row))
        plen = plen.at[slot].set(tokens.size)
        row_cap = row_cap.at[slot].set(cap)
        pos = pos.at[slot].set(0)
        n_gen = n_gen.at[slot].set(0)
        done = done.at[slot].set(False)
        caches = jax.tree_util.tree_map(
            lambda c, z: c.at[slot].set(z), caches, lane_zero
        )
        return caches, buffer, pos, plen, row_cap, n_gen, done, rng

    def admit_group(state, free_slots):
        """Admit up to ``len(free_slots)`` queued requests in fused
        waves: one `_make_admit` call per prompt bucket, each group
        padded to a power of two to bound the compile count at
        O(buckets x log2(batch))."""
        if prefill == "stream":
            for slot in free_slots:
                if queue:
                    state = admit_stream(state, slot)
            return state
        picked = []  # (slot, req_idx, tokens, cap, key, bucket)
        for slot in free_slots:
            if not queue:
                break
            req_idx, tokens, cap = queue.pop(0)
            slot_req[slot] = req_idx
            min_left[slot] = cap - 1
            bucket = min(
                1 << (int(tokens.size) - 1).bit_length(), config.max_seq
            )
            # The documented per-admission key chain: one split per
            # admitted request, in admission order, regardless of how
            # admissions group into waves.
            adm_rng["key"], key = jax.random.split(adm_rng["key"])
            picked.append((slot, req_idx, tokens, cap, key, bucket))
        for bucket in sorted({p[5] for p in picked}):
            group = [p for p in picked if p[5] == bucket]
            g = 1 << (len(group) - 1).bit_length()  # pad to power of two
            rows = np.full((g, length), pad, np.int32)
            padded = np.full((g, bucket), pad, np.int32)
            plens = np.ones(g, np.int32)
            slots = np.full(g, batch, np.int32)  # OOB rows are dropped
            caps_in = np.ones(g, np.int32)
            keys = [jax.random.PRNGKey(0)] * g
            for r, (slot, _, tokens, cap, key, _) in enumerate(group):
                rows[r, : tokens.size] = tokens
                padded[r, : tokens.size] = tokens
                plens[r] = tokens.size
                slots[r] = slot
                caps_in[r] = cap
                keys[r] = key
            wave = _make_admit(
                decoder, float(temperature), top_k, eos_token_id,
                int(batch), int(bucket), int(g),
            )
            state = wave(
                params, state, jnp.asarray(rows), jnp.asarray(padded),
                jnp.asarray(plens), jnp.asarray(slots),
                jnp.asarray(caps_in), jnp.stack(keys),
            )
            _count("prefill_passes")
        return state

    state = (
        caches, jnp.asarray(buffer), jnp.asarray(pos), jnp.asarray(plen),
        jnp.asarray(row_cap), jnp.asarray(n_gen), jnp.asarray(done), rng,
    )
    state = admit_group(state, list(range(batch)))

    while True:
        # Run as many sync chunks as the host can PROVE are finish-free
        # before paying a blocking fetch: with no EOS the per-slot budget
        # bound is exact, so fetches happen only at boundaries where a
        # request can actually complete.  With EOS the loop always stays
        # at one chunk per fetch — a slot can finish any step, and
        # multi-chunking would keep stepping frozen rows for up to the
        # residual cap after every live row has stopped.
        active = [s for s in range(batch) if slot_req[s] >= 0]
        chunks = 1
        if eos_token_id is None:
            # Without EOS the budget bound is exact, so this skips only
            # provably finish-free fetches.  With EOS a slot can finish
            # any step, and multi-chunking would keep stepping frozen
            # rows for up to the residual cap after every live row has
            # stopped — one chunk per fetch stays the honest choice.
            bound = min((min_left[s] for s in active), default=1)
            chunks = max(1, -(-bound // sync_steps))
        for _ in range(chunks):
            state = run_steps(params, state)
        _count("device_chunks", chunks)
        for s in active:
            min_left[s] = max(min_left[s] - chunks * sync_steps, 0)
        done_h = np.asarray(state[6])
        _count("sync_fetches")
        finished = [
            s for s in range(batch) if done_h[s] and slot_req[s] >= 0
        ]
        if finished:
            # Bulk-harvest: ONE fetch each of buffer/plen/n_gen per sync
            # boundary instead of three per finished slot — on tunneled
            # backends every fetch is a full host round trip, and this
            # loop's host chatter is the serving throughput floor.
            # Admissions below only mutate freed slots, so the
            # pre-admission snapshot stays valid for the other rows.
            buffer_h = np.asarray(state[1])
            plen_h = np.asarray(state[3])
            n_gen_h = np.asarray(state[5])
            for slot in finished:
                keep = int(plen_h[slot]) + int(n_gen_h[slot])
                outputs[slot_req[slot]] = buffer_h[slot, :keep].copy()
                slot_req[slot] = -1
            if queue:
                state = admit_group(state, finished)
        if not queue and all(r < 0 for r in slot_req):
            break
    return outputs  # type: ignore[return-value]


class ContinuousEngine:
    """Incremental continuous batching for a *resident* model server.

    ``continuous_generate`` serves one closed batch of prompts and
    returns; a serving session needs the same fixed-slot loop held open
    indefinitely, with requests admitted and harvested as they come.
    This class is that loop turned inside out, implementing the worker
    harness's duck-typed serving-engine surface
    (``slots`` / :meth:`admit` / :meth:`step` / :meth:`cancel`):

    * construction loads ``params`` and builds the jitted admission and
      decode programs ONCE (shared, via the same ``_make_admit`` /
      ``_make_run_steps`` caches ``continuous_generate`` compiles
      through, so a session and a batch call with the same shape reuse
      one executable);
    * :meth:`admit` queues a request for a free slot — admissions flush
      in the same fused, bucketed prefill waves as ``continuous_generate``
      (one compiled call per bucket per flush, first token included);
    * :meth:`step` runs ONE ``sync_steps`` decode chunk across every busy
      lane and returns the fresh tokens per request since the last chunk
      — the incremental stream a serving session pushes to its callers,
      so time-to-first-token is one chunk, not end-of-response.

    Numerics are ``continuous_generate``'s exactly: each lane is a vmapped
    batch-1 decode, greedy rows bit-identical to ``generate()`` on
    batch-rounding-invariant backends, and sampled requests draw from the
    dedicated admission key chain.  Buffer width is static
    (``length``, default ``config.max_seq``) — the price of compiling
    once for a session's whole lifetime.

    **Prefix tree.**  Prefill reuse is generalized beyond one static
    ``shared_prefix``: the engine keeps a small LRU *prefix tree* of
    reusable KV lanes keyed by token-prefix digest.  Every admission's
    post-prefill lane is inserted (cursor parked at the prompt length),
    and a later prompt reuses the DEEPEST cached lane sharing a common
    prefix with it — including a *partial* reuse, where a lane prefilled
    for ``[a b c d]`` serves a prompt ``[a b x ...]`` rewound to the
    2-token common prefix (positions past the rewound cursor are dead
    until overwritten, the same exactness argument as pad positions).
    Repeated prompts therefore hit warm KV (the previous admission's
    lane rewound one position) without any configuration; a
    ``shared_prefix`` still seeds a pinned, never-evicted entry.
    Numerics are unchanged: greedy outputs stay bit-identical to the
    full-prefill road (asserted against the oracle in
    ``tests/test_continuous.py``) and hits strictly shrink
    ``stats["prefill_positions"]``.  ``prefix_cache_size`` bounds the
    unpinned entries (0 disables reuse caching); ``prefix_min_tokens``
    is the shortest reusable prefix worth a dedicated compiled wave.

    **KV export/import (disaggregated prefill/decode).**
    :meth:`prefill_only` runs the admission prefill for one prompt and
    returns a serializable KV *bundle* — cache lane, cursor, first
    generated token, rng/sampling fingerprint — without occupying a
    decode slot; :meth:`admit_from_kv` scatters an imported bundle into
    a free slot and goes straight to decode.  A prefill-tier engine and
    a decode-tier engine composed this way stream greedy tokens
    bit-identical to one engine doing both (the serving tier's
    ``DisaggregatedSet`` rides exactly this pair through the CAS).

    **Speculative decoding (``draft_model``).**  With a draft model the
    greedy decode loop becomes draft-and-verify: each chunk runs
    ``sync_steps // (draft_len + 1)`` rounds in which every lane drafts
    ``draft_len`` tokens autoregressively through the small model, then
    the target scores all lanes' ``draft_len + 1`` slabs in ONE fused
    vmapped pass and commits the longest agreeing prefix plus its own
    choice at the first disagreement.  Every committed token is the
    target's greedy pick, so spec streams are **bit-identical** to the
    same engine without a draft; ``stats`` grows
    ``spec_proposed``/``spec_accepted`` (the accept-rate feed).  Any
    construction-time refusal — sampled session, vocab mismatch,
    rolling-cache draft, missing ``max_seq`` headroom for the verify
    slab (``length + draft_len``) — silently falls back to the plain
    loop (``spec_refusals`` counts it, ``_spec_refusal`` names it).

    **Decode-mode lane groups (``decode_modes`` + per-request
    ``quality``).**  Beyond the fp lanes, the engine can build int8 /
    kv-quant / full-quant groups (:func:`..quant.mode_variant` twins,
    each a private sub-engine with its own slots, prefix tree, and spec
    loop).  A request's ``params["quality"]`` selects its group; unknown
    or refused modes fall back to fp bit-exact (``mode_refusals``).  KV
    bundles carry a ``quant`` fingerprint and only admit into the
    matching group — a mismatch raises, and the session harness degrades
    to a full prefill.  ``stats["mode_tokens_<mode>"]`` counts per-group
    output tokens.

    **Multi-adapter bank (``adapters`` — batched LoRA multiplexing).**
    ``adapters={name: lora_params}`` keeps the BASE weights resident
    once and stacks every adapter's rank-r ``lora_a``/``lora_b`` leaves
    into ``[n_slots, ...]`` bank arrays; each lane carries an int32 bank
    slot in its cache tree and the compiled programs gather the lane's
    adapter INSIDE the jit (:class:`_AdapterDecoder`) — one compiled
    step serves every adapter, heterogeneous-adapter traffic co-batches
    in the same fused decode and admission waves, and slot 0's zero-B
    identity makes a base lane bit-equal to the plain engine.  A
    request's ``params["adapter"]`` selects by name (unknown names
    refuse cleanly); :meth:`attach_adapter` splices a new adapter — or
    hot-swaps a live name with zero drops — into the RUNNING session
    (bank scatter, never a recompile), bounded by
    ``COVALENT_TPU_SERVE_ADAPTERS_MAX`` (default 8).  Composes with
    ``decode_modes`` via ``quantize_then_lora`` semantics (each
    quantized group attaches the same adapters over its quantized base;
    refusals degrade to fp) and with the prefix tree / KV bundles via
    adapter-scoped keys and name+digest fingerprints — cross-adapter
    K/V reuse is structurally impossible.  Speculative decoding refuses
    adapter banks (plain-loop fallback).  Per-adapter
    ``stats["adapter_tokens_<name>"]`` / ``adapter_requests_<name>``
    feed the serving metrics.
    """

    def __init__(
        self,
        model: TransformerLM,
        params: Any,
        *,
        max_batch: int = 4,
        temperature: float = 0.0,
        top_k: int | None = None,
        rng: jax.Array | None = None,
        eos_token_id: int | None = None,
        pad_token_id: int | None = None,
        sync_steps: int = 8,
        max_new_tokens: int = 16,
        length: int | None = None,
        shared_prefix: Sequence[int] | None = None,
        prefix_cache_size: int = 8,
        prefix_min_tokens: int = 4,
        decode_modes: Sequence[str] = ("fp",),
        draft_model: TransformerLM | None = None,
        draft_params: Any = None,
        draft_len: int = 4,
        adapters: dict[str, Any] | None = None,
        adapter_rank: int | None = None,
        adapter_alpha: float = 16.0,
        adapters_max: int | None = None,
    ) -> None:
        decoder = _decode_model(model)
        config = decoder.config
        _require_plain_cache(config, "ContinuousEngine")
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if sync_steps < 1:
            raise ValueError(f"sync_steps must be >= 1, got {sync_steps}")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        if temperature <= 0 and top_k is not None:
            raise ValueError("top_k requires sampling (temperature > 0)")
        if top_k is not None and not 1 <= top_k <= config.vocab_size:
            raise ValueError(
                f"top_k must be in [1, {config.vocab_size}], got {top_k}"
            )
        self._length = int(length or config.max_seq)
        if not 2 <= self._length <= config.max_seq:
            raise ValueError(
                f"length must be in [2, {config.max_seq}], got {self._length}"
            )
        #: host-loop counters (created early: adapter installs seed their
        #: per-name token keys here): prefix-tree hit/miss accounting,
        #: the prefill positions each admission paid, the KV plane's
        #: traffic, spec/mode refusals, and the adapter bank's lifecycle.
        self.stats: dict[str, int] = {
            "prefix_hits": 0, "prefix_misses": 0, "prefill_positions": 0,
            "prefix_evictions": 0, "kv_admits": 0, "kv_exports": 0,
            "spec_rounds": 0, "spec_proposed": 0, "spec_accepted": 0,
            "spec_refusals": 0, "mode_refusals": 0,
            "adapter_prefix_blocked": 0, "adapter_attaches": 0,
            "adapter_detaches": 0, "adapter_swaps": 0,
        }
        #: prefix digest -> _PrefixEntry, oldest-insert first (LRU order
        #: maintained by move_to_end on every hit).
        self._prefix_tree: "collections.OrderedDict[str, _PrefixEntry]" = (
            collections.OrderedDict()
        )
        self._prefix_cache_size = max(0, int(prefix_cache_size))
        self._prefix_min = max(1, int(prefix_min_tokens))

        # -- multi-adapter bank (batched LoRA multiplexing) ----------------
        # One resident base plus up to adapters_max named rank-r adapters:
        # the lora_a/lora_b leaves stack into [n_slots, ...] bank arrays,
        # every lane carries an int32 bank slot, and the compiled
        # programs gather each lane's adapter inside the jit
        # (_AdapterDecoder) — rank-r GEMMs on top of the shared base
        # pass, one compiled step for ALL adapters.  Slot 0 holds the
        # zero-B identity adapter, so a base lane is bit-equal to the
        # plain engine's.
        self._bank: list | None = None
        self._adapter_slot: dict[str, int] = {}
        self._adapter_digests: dict[str, str] = {}
        self._adapter_free: list[int] = []
        self._adapter_retired: list[int] = []
        self._slot_refs: list[int] = []
        self._rid_adapter: dict[str, tuple[int, str]] = {}
        self._adapter_rank = 0
        self._adapter_alpha = float(adapter_alpha)
        self._adapters_max = 0
        if adapters is not None or adapter_rank is not None:
            from .lora import add_lora, lora_mask

            if getattr(config, "lora_rank", 0):
                raise AdapterUnsupported(
                    "the adapter bank needs the BASE model, and this one "
                    f"already carries adapters (lora_rank="
                    f"{config.lora_rank}) — serve the base and attach "
                    "adapters on top (lora.quantize_then_lora order)"
                )
            adapters = {str(k): v for k, v in (adapters or {}).items()}
            rank = adapter_rank
            if rank is None:
                if not adapters:
                    raise AdapterUnsupported(
                        "an empty bank needs adapter_rank to size its "
                        "template"
                    )
                try:
                    rank = int(np.asarray(self._adapter_payload_leaves(
                        next(iter(adapters.values()))
                    )[0]).shape[-1])
                except (ValueError, IndexError, TypeError) as exc:
                    raise AdapterUnsupported(
                        f"cannot infer the adapter rank: {exc}"
                    ) from exc
            if int(rank) < 1:
                raise AdapterUnsupported(
                    f"adapter_rank must be >= 1, got {rank}"
                )
            limit = adapters_max
            if limit is None:
                limit = int(os.environ.get(ADAPTERS_MAX_ENV) or 8)
            if int(limit) < max(1, len(adapters)):
                raise AdapterUnsupported(
                    f"{len(adapters)} adapters exceed the bank's "
                    f"{limit} named slots ({ADAPTERS_MAX_ENV})"
                )
            try:
                lmodel, filled = add_lora(
                    model, params, rank=int(rank),
                    alpha=float(adapter_alpha),
                )
            except ValueError as exc:
                raise AdapterUnsupported(str(exc)) from exc
            self._adapter_rank = int(rank)
            self._adapters_max = int(limit)
            leaves, lora_treedef = jax.tree_util.tree_flatten(filled)
            mask = tuple(
                bool(m)
                for m in jax.tree_util.tree_leaves(lora_mask(filled))
            )
            self._bank_base = [
                leaf for leaf, m in zip(leaves, mask) if not m
            ]
            template = [leaf for leaf, m in zip(leaves, mask) if m]
            self._adapter_shapes = [
                (tuple(leaf.shape), jnp.dtype(leaf.dtype))
                for leaf in template
            ]
            n_slots = int(limit) + 1  # + the pinned identity at slot 0
            self._bank = [
                jnp.zeros((n_slots,) + leaf.shape, leaf.dtype).at[0].set(
                    leaf
                )
                for leaf in template
            ]
            self._adapter_free = list(range(1, n_slots))
            self._slot_refs = [0] * n_slots
            decoder = _AdapterDecoder(
                _decode_model(lmodel), lora_treedef, mask
            )
            for name, payload in adapters.items():
                self._install_adapter(name, payload)
            self.stats.setdefault("adapter_tokens_base", 0)

        self._decoder = decoder
        self._config = config
        self._params = (
            {"base": self._bank_base, "bank": self._bank}
            if self._bank is not None else params
        )
        self._temperature = float(temperature)
        self._top_k = top_k
        self._eos = eos_token_id
        pad = pad_token_id
        if pad is None:
            pad = eos_token_id if eos_token_id is not None else 0
        self._pad = int(pad)
        self._sync = int(sync_steps)
        self._default_cap = int(max_new_tokens)
        self.slots = batch = int(max_batch)

        if rng is None:
            rng = jax.random.PRNGKey(0)
        rng = jnp.array(rng, copy=True)
        lane = init_cache(model, 1)
        caches = jax.tree_util.tree_map(
            lambda leaf: jnp.broadcast_to(
                leaf[None], (batch,) + leaf.shape
            ).copy(),
            lane,
        )
        if self._bank is not None:
            # Each lane's bank slot rides the cache tree itself, so the
            # donated jitted programs carry it without signature changes.
            caches = {"kv": caches, "adapter": jnp.zeros(batch, jnp.int32)}
        self._state = (
            caches,
            jnp.full((batch, self._length), self._pad, jnp.int32),
            jnp.zeros(batch, jnp.int32),   # pos
            jnp.ones(batch, jnp.int32),    # plen
            jnp.ones(batch, jnp.int32),    # row_cap
            jnp.zeros(batch, jnp.int32),   # n_gen
            jnp.ones(batch, bool),         # done (empty slots are "done")
            rng,
        )
        self._run_steps = _make_run_steps(
            decoder, self._temperature, top_k, eos_token_id,
            self._length, self._sync, batch,
        )
        self._adm_key = jax.random.fold_in(rng, 0x5E1)
        #: slot -> rid (None = free), and generated tokens already streamed.
        self._slot_rid: list[str | None] = [None] * batch
        self._reported = [0] * batch
        self._rid_slot: dict[str, int] = {}
        #: admissions awaiting a flush: (rid, tokens, cap, bank slot).
        self._pending: list[tuple[str, np.ndarray, int, int]] = []
        #: KV-bundle admissions awaiting a flush:
        #: (rid, tokens, cap, first token, imported lane, bank slot).
        self._pending_kv: list[
            tuple[str, np.ndarray, int, int, Any, int]
        ] = []
        #: canonical lane layout: the treedef every imported KV bundle is
        #: rebuilt against and the shape/dtype table it is validated by.
        lane_leaves, self._lane_treedef = jax.tree_util.tree_flatten(lane)
        self._lane_shapes = [
            (tuple(leaf.shape), jnp.dtype(leaf.dtype))
            for leaf in lane_leaves
        ]
        if shared_prefix is not None:
            ptoks = np.asarray(shared_prefix, np.int32).reshape(-1)
            if ptoks.size < 1:
                raise ValueError("shared_prefix needs at least one token")
            if ptoks.size + 2 > self._length:
                raise ValueError(
                    f"shared_prefix ({ptoks.size} tokens) leaves no room "
                    f"for a suffix + generation inside the session's "
                    f"static length ({self._length})"
                )
            # Prefill the shared prefix ONCE per engine (per replica):
            # one exact-length pass on a zero lane, cursor parked at the
            # prefix boundary.  It seeds the prefix tree as a PINNED
            # entry — every prefix-matching admission copies this lane
            # instead of re-running the prefix positions, and LRU churn
            # can never evict it.
            zero = jax.tree_util.tree_map(jnp.zeros_like, lane)
            if self._bank is not None:
                zero = {"kv": zero, "adapter": jnp.zeros((), jnp.int32)}
            _logits, mutated = decoder.apply(
                {"params": self._params, "cache": zero},
                jnp.asarray(ptoks)[None],
                mutable=["cache"],
            )
            prefix_lane = _set_cursor(mutated["cache"], int(ptoks.size))
            self._insert_prefix(ptoks, lambda: prefix_lane, pinned=True)

        # -- speculative decoding (greedy draft-and-verify) ----------------
        # The draft proposes draft_len tokens per lane per round; the
        # target verifies each lane's slab in the fused vmapped pass.
        # Every committed token is the target's own greedy choice, so a
        # spec session's streams are bit-identical to this engine without
        # the draft — which is also the fallback on ANY refusal below
        # (recorded in stats["spec_refusals"] + _spec_refusal, never an
        # error: a serving session must come up degraded, not dead).
        self._draft = None
        self._draft_params = None
        self._draft_caches = None
        self._spec_run = None
        self._spec_rounds = 0
        self._spec_refusal: str | None = None
        self._draft_len = int(draft_len)
        if draft_model is not None:
            if self._draft_len < 1:
                raise ValueError(
                    f"draft_len must be >= 1, got {draft_len}"
                )
            ddecoder = _decode_model(draft_model)
            dconfig = ddecoder.config
            reason = None
            if self._bank is not None:
                reason = (
                    "multi-adapter session (the draft-verify loop runs "
                    "one shared draft; adapter banks fall back to the "
                    "plain loop)"
                )
            elif self._temperature > 0:
                reason = (
                    "sampled session (the continuous verify path is "
                    "greedy-only; use speculative_sample offline)"
                )
            elif dconfig.vocab_size != config.vocab_size:
                reason = (
                    f"draft vocab {dconfig.vocab_size} != target "
                    f"{config.vocab_size}"
                )
            elif dconfig.rolling_cache:
                reason = "draft model uses rolling_cache"
            elif self._length + self._draft_len > config.max_seq:
                reason = (
                    f"target max_seq {config.max_seq} < length + "
                    f"draft_len = {self._length + self._draft_len} "
                    "(verify slabs need scratch headroom)"
                )
            elif self._length + self._draft_len > dconfig.max_seq:
                reason = (
                    f"draft max_seq {dconfig.max_seq} < length + "
                    f"draft_len = {self._length + self._draft_len}"
                )
            if reason is None:
                self._draft = ddecoder
                self._draft_params = draft_params
                dlane = init_cache(draft_model, 1)
                self._draft_caches = jax.tree_util.tree_map(
                    lambda leaf: jnp.broadcast_to(
                        leaf[None], (batch,) + leaf.shape
                    ).copy(),
                    dlane,
                )
                # A plain chunk decodes sync_steps tokens; a spec chunk
                # commits 1..draft_len+1 per round, so this many rounds
                # keeps the admission-latency granularity comparable.
                self._spec_rounds = max(
                    1, self._sync // (self._draft_len + 1)
                )
                self._spec_run = _make_spec_run_steps(
                    decoder, ddecoder, eos_token_id, self._length,
                    self._draft_len, self._spec_rounds, batch,
                )
            else:
                self._spec_refusal = reason
                self.stats["spec_refusals"] += 1

        # -- decode-mode lane groups (per-request quality routing) ---------
        # Each non-fp mode is a full sub-engine over the mode_variant
        # model twin: its own slots, prefix tree, compiled programs, and
        # (when a draft is configured) its own spec verify loop against
        # ITS target — so an int8 lane's spec commits the int8 model's
        # greedy choices.  The primary stays the fp group and the single
        # public surface; total concurrency across all groups is bounded
        # by ``slots`` (the ``busy`` property sums the groups), trading
        # lane memory for never refusing a routed request that the
        # session-level admission already accepted.  A mode that REFUSES
        # to build (quantize_lm on MoE/scanned/LoRA models) is recorded
        # and its requests fall back to fp, bit-exact.
        modes = tuple(dict.fromkeys(decode_modes or ("fp",)))
        for mode in modes:
            if mode not in SERVING_MODES:
                raise ValueError(
                    f"unknown decode mode {mode!r}; expected a subset "
                    f"of {SERVING_MODES}"
                )
        if "fp" not in modes:
            raise ValueError(
                "decode_modes must include 'fp' (the bit-exact fallback "
                "lane every refusal degrades to)"
            )
        self._mode = "fp"
        self._subs: dict[str, ContinuousEngine] = {}
        self._sub_stats_seen: dict[str, dict[str, int]] = {}
        self._rid_mode: dict[str, str] = {}
        self._mode_refusal: dict[str, str] = {}
        for mode in modes:
            if mode == "fp":
                continue
            sub_kwargs: dict[str, Any] = {}
            if self._bank is not None:
                # quantize_then_lora composition: the twin quantizes the
                # BASE model, then the sub-engine attaches the SAME
                # adapter set on top — exactly lora.quantize_then_lora's
                # order.  A variant the composition refuses (quantize_lm
                # on MoE/scanned bases, adapter-template mismatch) is a
                # recorded per-mode refusal with fp fallback, never an
                # error.
                sub_kwargs = dict(
                    adapters=adapters,
                    adapter_rank=self._adapter_rank,
                    adapter_alpha=self._adapter_alpha,
                    adapters_max=self._adapters_max,
                )
            try:
                sub_model, sub_params = mode_variant(model, params, mode)
                sub = ContinuousEngine(
                    sub_model, sub_params,
                    max_batch=max_batch, temperature=temperature,
                    top_k=top_k, rng=rng, eos_token_id=eos_token_id,
                    pad_token_id=pad_token_id, sync_steps=sync_steps,
                    max_new_tokens=max_new_tokens, length=self._length,
                    shared_prefix=shared_prefix,
                    prefix_cache_size=prefix_cache_size,
                    prefix_min_tokens=prefix_min_tokens,
                    draft_model=draft_model, draft_params=draft_params,
                    draft_len=draft_len,
                    **sub_kwargs,
                )
            except ValueError as exc:
                self._mode_refusal[mode] = str(exc)
                self.stats["mode_refusals"] += 1
                continue
            sub._mode = mode
            self._subs[mode] = sub
            self._sub_stats_seen[mode] = {}
        for mode in modes:
            self.stats.setdefault(f"mode_tokens_{mode}", 0)

    # -- serving-engine surface -------------------------------------------

    def _dup(self, rid: str) -> bool:
        """True when ``rid`` is already admitted anywhere: a live or
        pending lane here, or routed to a mode group."""
        return (
            rid in self._rid_slot
            or rid in self._rid_mode
            or any(p[0] == rid for p in self._pending)
            or any(p[0] == rid for p in self._pending_kv)
        )

    def _route_mode(self, params: dict) -> str:
        """Resolve a request's ``quality`` knob to a decode mode.

        ``None``/``"exact"``/``"fp"`` → the fp lane.  A known mode with a
        built lane group → that group.  Anything else — an unknown value,
        or a mode this session refused/never configured — falls back to
        the bit-exact fp lane and counts a ``mode_refusals`` (a serving
        session degrades, it does not reject a request over a knob).
        """
        quality = params.get("quality")
        if quality is None:
            return self._mode
        mode = "fp" if quality == "exact" else str(quality)
        if mode == self._mode or mode in self._subs:
            return mode
        self.stats["mode_refusals"] += 1
        return self._mode

    def admit(self, rid: str, prompt, params: dict | None = None) -> None:
        """Reserve a lane for one request (flushed at the next step).

        ``params`` may carry ``max_new_tokens`` and ``quality`` (a
        decode-mode name — see :func:`..quant.mode_variant`; unknown or
        unavailable modes fall back to the bit-exact fp lane); everything
        else (temperature, top_k, EOS) is session-static — the compiled
        programs key on them.  Raises on malformed prompts, so the
        session rejects the request instead of wedging a lane.
        """
        params = params or {}
        if self._dup(rid):
            raise ValueError(f"request id {rid!r} already admitted")
        mode = self._route_mode(params)
        if mode != self._mode:
            if self.busy >= self.slots:
                raise RuntimeError("no free lane (all slots busy)")
            self._subs[mode].admit(rid, prompt, params)
            self._rid_mode[rid] = mode
            return
        tokens = np.asarray(prompt, np.int32).reshape(-1)
        if tokens.size < 1:
            raise ValueError("prompt needs at least one token")
        cap = int(params.get("max_new_tokens", self._default_cap))
        if cap < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {cap}")
        if tokens.size + cap > self._length:
            raise ValueError(
                f"prompt + budget ({tokens.size + cap}) exceeds the "
                f"session's static length ({self._length})"
            )
        if self.busy >= self.slots:
            raise RuntimeError("no free lane (all slots busy)")
        aslot, aname = self._resolve_adapter(params)
        if self._bank is not None:
            self._rid_adapter[rid] = (aslot, aname)
            self._slot_refs[aslot] += 1
            key = f"adapter_requests_{aname}"
            self.stats[key] = self.stats.get(key, 0) + 1
        self._pending.append((rid, tokens, cap, aslot))

    # -- multi-adapter bank surface ----------------------------------------

    @staticmethod
    def _adapter_payload_leaves(payload) -> list:
        """Normalize an adapter payload to its ordered leaf list.

        Accepts the CAS registry's bundle dict (``{"leaves": [...]}``),
        a bare leaf list (the wire form), or a full LoRA params tree
        (:func:`..lora.adapter_leaves` extracts the adapter leaves in
        flatten order — identical across the float and quantized model
        twins, which is what lets ONE trained adapter splice into every
        decode-mode lane group).
        """
        if isinstance(payload, dict) and "leaves" in payload:
            return list(payload["leaves"])
        if isinstance(payload, (list, tuple)):
            return list(payload)
        from .lora import adapter_leaves

        return adapter_leaves(payload)

    def _install_adapter(self, name: str, payload) -> str:
        """Write one adapter into a free bank slot; returns its digest.

        A re-install under a live name is the zero-drop hot swap: the
        NEW generation takes a fresh slot and the name repoints to it —
        lanes already decoding keep gathering the old slot's weights
        until they finish (the retired slot is only reclaimed once its
        in-flight refcount drains), while every subsequent admission
        resolves the new generation.  No lane is ever touched mid-wave.
        """
        if (
            not name or name == "base"
            or not all(ch.isalnum() or ch in "._-" for ch in name)
        ):
            raise AdapterUnsupported(
                f"invalid adapter name {name!r} ('base' is reserved; "
                "names are [A-Za-z0-9._-])"
            )
        try:
            leaves = self._adapter_payload_leaves(payload)
        except (ValueError, KeyError, TypeError) as exc:
            raise AdapterUnsupported(
                f"adapter {name!r} payload is not an adapter: {exc}"
            ) from exc
        if len(leaves) != len(self._adapter_shapes):
            raise AdapterUnsupported(
                f"adapter {name!r} has {len(leaves)} leaves; this bank's "
                f"template has {len(self._adapter_shapes)}"
            )
        cast = []
        for leaf, (shape, dtype) in zip(leaves, self._adapter_shapes):
            arr = np.asarray(leaf)
            if tuple(arr.shape) != shape:
                raise AdapterUnsupported(
                    f"adapter {name!r} leaf {tuple(arr.shape)} does not "
                    f"match the bank template {shape} (rank/geometry "
                    "mismatch)"
                )
            cast.append(arr.astype(dtype))
        if isinstance(payload, dict) and payload.get("digest"):
            digest = str(payload["digest"])
        else:
            from .lora import adapter_digest

            digest = adapter_digest(cast)
        self._reclaim_adapter_slots()
        if not self._adapter_free:
            raise AdapterUnsupported(
                f"adapter bank is full ({self._adapters_max} named slots,"
                f" {ADAPTERS_MAX_ENV}); detach one or raise the limit"
            )
        slot = self._adapter_free.pop(0)
        for i, arr in enumerate(cast):
            self._bank[i] = self._bank[i].at[slot].set(jnp.asarray(arr))
        old = self._adapter_slot.get(name)
        self._adapter_slot[name] = slot
        self._adapter_digests[name] = digest
        self.stats.setdefault(f"adapter_tokens_{name}", 0)
        if old is not None:
            self._adapter_retired.append(old)
            self._purge_prefix_slot(old)
            self.stats["adapter_swaps"] += 1
        return digest

    def attach_adapter(self, name: str, payload) -> str:
        """Splice an adapter into the RUNNING session; returns its
        digest.  Live traffic keeps decoding throughout — attachment is
        a bank scatter plus a name-table write, never a recompile (the
        compiled programs key on the bank's static shape).  Re-attaching
        a live name hot-swaps it with zero drops (see
        :meth:`_install_adapter`).  Propagates to every decode-mode lane
        group, so a ``quality``-routed request finds the adapter in its
        quantized group too (quantize_then_lora composition).
        """
        if self._bank is None:
            raise AdapterUnsupported(
                "this session hosts no adapter bank (construct the "
                "engine with adapters= or adapter_rank=)"
            )
        digest = self._install_adapter(name, payload)
        for sub in self._subs.values():
            if sub._bank is not None:
                sub.attach_adapter(name, payload)
        self.stats["adapter_attaches"] += 1
        return digest

    def detach_adapter(self, name: str) -> None:
        """Retire a named adapter: new requests refuse it immediately;
        its bank slot is reclaimed once in-flight lanes drain."""
        slot = self._adapter_slot.pop(name, None)
        if slot is None:
            raise ValueError(
                f"unknown adapter {name!r}; attached: "
                f"{sorted(self._adapter_slot) or 'none'}"
            )
        self._adapter_digests.pop(name, None)
        self._adapter_retired.append(slot)
        self._purge_prefix_slot(slot)
        self._reclaim_adapter_slots()
        for sub in self._subs.values():
            if sub._bank is not None and name in sub._adapter_slot:
                sub.detach_adapter(name)
        self.stats["adapter_detaches"] += 1

    @property
    def adapters(self) -> tuple[str, ...]:
        """Currently attached adapter names (insertion order)."""
        return tuple(self._adapter_slot)

    @property
    def adapter_digests(self) -> dict[str, str]:
        """name -> content digest of the attached generation."""
        return dict(self._adapter_digests)

    def _reclaim_adapter_slots(self) -> None:
        """Return retired bank slots whose in-flight lanes drained."""
        still = []
        for slot in self._adapter_retired:
            if self._slot_refs[slot] == 0:
                self._adapter_free.append(slot)
            else:
                still.append(slot)
        self._adapter_retired = still

    def _purge_prefix_slot(self, aslot: int) -> None:
        """Drop prefix-tree lanes computed under a retired bank slot —
        their K/V embeds the OLD generation's weights."""
        stale = [
            d for d, e in self._prefix_tree.items() if e.aslot == aslot
        ]
        for d in stale:
            del self._prefix_tree[d]

    def _release_adapter(self, rid: str) -> None:
        """Drop one request's hold on its bank slot (idempotent)."""
        entry = self._rid_adapter.pop(rid, None)
        if entry is not None and self._slot_refs:
            slot = entry[0]
            self._slot_refs[slot] = max(0, self._slot_refs[slot] - 1)

    def _resolve_adapter(self, params: dict) -> tuple[int, str]:
        """``params["adapter"]`` -> (bank slot, name); base is slot 0.

        Unknown names raise :class:`ValueError` — the session REFUSES
        the request cleanly instead of silently serving base weights.
        """
        name = str(params.get("adapter") or "")
        if self._bank is None:
            if name and name != "base":
                raise ValueError(
                    f"unknown adapter {name!r} (this session hosts no "
                    "adapter bank)"
                )
            return 0, "base"
        if not name or name == "base":
            return 0, "base"
        slot = self._adapter_slot.get(name)
        if slot is None:
            raise ValueError(
                f"unknown adapter {name!r}; attached: "
                f"{sorted(self._adapter_slot) or 'none'}"
            )
        return slot, name

    # -- disaggregated prefill/decode surface ------------------------------

    def prefill_only(self, prompt, params: dict | None = None) -> bytes:
        """Run the admission prefill for one prompt WITHOUT taking a
        decode slot; returns a serialized KV bundle.

        The bundle carries everything :meth:`admit_from_kv` needs to
        skip prefill entirely on another engine of the same model: the
        prompt, the prefilled cache lane (cursor parked at the prompt
        length), the first generated token, and the admission rng /
        sampling fingerprint.  The prefill itself is the admission
        wave's exact computation (prefix-tree hits included — a prefill
        tier warms its own tree), so a decode engine admitting the
        bundle streams greedy tokens bit-identical to one engine doing
        both phases.  Consumes one key from this engine's admission
        chain, like a normal admission.

        The bundle carries a quantization fingerprint (``quant``: this
        lane group's decode mode) validated by :meth:`admit_from_kv`
        exactly like the sampling fingerprint; a request's ``quality``
        knob routes the prefill to the matching mode group, so a
        ``kv_quant``/``full_quant`` prefill ships int8 KV leaves —
        roughly 2-4x smaller on the wire than the fp lane's f32/bf16.
        """
        params = params or {}
        mode = self._route_mode(params)
        if mode != self._mode:
            return self._subs[mode].prefill_only(prompt, params)
        tokens = np.asarray(prompt, np.int32).reshape(-1)
        if tokens.size < 1:
            raise ValueError("prompt needs at least one token")
        if tokens.size + 1 > self._length:
            raise ValueError(
                f"prompt ({tokens.size} tokens) leaves no room for "
                f"generation inside the session's static length "
                f"({self._length})"
            )
        aslot, aname = self._resolve_adapter(params)
        self._adm_key, key = jax.random.split(self._adm_key)
        m, lane_m, _entry_digest = self._lookup_prefix(tokens, aslot)
        if m:
            bucket = min(
                1 << (int(tokens.size) - m - 1).bit_length(),
                self._config.max_seq - m,
            )
            suffix = tokens[m:]
            padded = np.full((1, bucket), self._pad, np.int32)
            padded[0, : suffix.size] = suffix
            fn = _make_lane_prefix_prefill(
                self._decoder, self._temperature, self._top_k,
                int(bucket), int(m),
            )
            lane, first = fn(
                self._params, lane_m, jnp.asarray(padded),
                jnp.asarray([suffix.size], jnp.int32), key[None],
            )
            self.stats["prefix_hits"] += 1
        else:
            bucket = min(
                1 << (int(tokens.size) - 1).bit_length(),
                self._config.max_seq,
            )
            padded = np.full((1, bucket), self._pad, np.int32)
            padded[0, : tokens.size] = tokens
            lane_zero = jax.tree_util.tree_unflatten(
                self._lane_treedef,
                [
                    jnp.zeros(shape, dtype)
                    for shape, dtype in self._lane_shapes
                ],
            )
            if self._bank is not None:
                lane_zero = {
                    "kv": lane_zero,
                    "adapter": jnp.asarray(aslot, jnp.int32),
                }
            fn = _make_lane_prefill(
                self._decoder, self._temperature, self._top_k, int(bucket),
            )
            lane, first = fn(
                self._params, lane_zero, jnp.asarray(padded),
                jnp.asarray([tokens.size], jnp.int32), key[None],
            )
            if self._prefix_tree:
                self.stats["prefix_misses"] += 1
        self.stats["prefill_positions"] += bucket
        self.stats["kv_exports"] += 1
        self._insert_prefix(tokens, lambda: lane, aslot=aslot)
        # The bank slot index is ENGINE-LOCAL — the wire form carries the
        # adapter NAME + content digest, and the importer re-wraps the
        # inner lane with ITS local slot (refusing a name it does not
        # host, or a digest from a superseded generation).
        leaves = jax.tree_util.tree_leaves(
            lane["kv"] if self._bank is not None else lane
        )
        bundle = {
            "v": KV_BUNDLE_VERSION,
            "prompt": [int(t) for t in tokens],
            "first": int(first),
            "plen": int(tokens.size),
            "rng": np.asarray(key),
            "temperature": self._temperature,
            "top_k": self._top_k,
            "eos": self._eos,
            "quant": self._mode,
            "adapter": "" if aname == "base" else aname,
            "adapter_digest": self._adapter_digests.get(aname, ""),
            "leaves": [np.asarray(leaf) for leaf in leaves],
        }
        return pickle.dumps(bundle, protocol=4)

    def admit_from_kv(
        self, rid: str, bundle, params: dict | None = None
    ) -> None:
        """Reserve a lane for a request whose prefill already ran
        elsewhere (flushed at the next step, like :meth:`admit`).

        ``bundle`` is :meth:`prefill_only`'s bytes (or the already
        unpickled dict).  The lane is validated leaf-by-leaf against
        this engine's cache layout, and the bundle's sampling
        fingerprint (temperature / top_k / eos) against this engine's
        statics — a bundle from a different model shape OR a
        differently-configured engine raises :class:`ValueError` so the
        session falls back to a full prefill instead of decoding a
        stream whose first token was drawn under different rules.  The
        bundle's QUANTIZATION fingerprint (``quant``, default ``fp`` for
        pre-0.17 bundles) routes it to the matching decode-mode lane
        group; a bundle for a mode this session never built raises the
        same way — degrade to full prefill, never decode fp tokens
        against int8 K/V.  No admission key is consumed (the first token
        was drawn by the prefill tier).
        """
        params = params or {}
        if isinstance(bundle, (bytes, bytearray)):
            bundle = pickle.loads(bytes(bundle))
        if not isinstance(bundle, dict) or int(
            bundle.get("v") or 0
        ) != KV_BUNDLE_VERSION:
            raise ValueError("unrecognized KV bundle")
        if self._dup(rid):
            raise ValueError(f"request id {rid!r} already admitted")
        quant = str(bundle.get("quant", "fp") or "fp")
        if quant != self._mode:
            sub = self._subs.get(quant)
            if sub is None:
                raise ValueError(
                    f"KV bundle quantization fingerprint {quant!r} does "
                    f"not match this engine's {self._mode!r} and no "
                    f"{quant!r} lane group is configured"
                )
            if self.busy >= self.slots:
                raise RuntimeError("no free lane (all slots busy)")
            sub._admit_from_kv_dict(rid, bundle, params)
            self._rid_mode[rid] = quant
            return
        self._admit_from_kv_dict(rid, bundle, params)

    def _admit_from_kv_dict(
        self, rid: str, bundle: dict, params: dict
    ) -> None:
        """Validate + queue one unpickled bundle into THIS lane group."""
        quant = str(bundle.get("quant", "fp") or "fp")
        if quant != self._mode:
            raise ValueError(
                f"KV bundle quantization fingerprint {quant!r} does not "
                f"match this lane group's {self._mode!r}"
            )
        fingerprint = (
            float(bundle.get("temperature", 0.0) or 0.0),
            bundle.get("top_k"),
            bundle.get("eos"),
        )
        ours = (self._temperature, self._top_k, self._eos)
        if fingerprint != ours:
            raise ValueError(
                f"KV bundle sampling fingerprint {fingerprint} does not "
                f"match this engine's {ours}"
            )
        if self._dup(rid):
            raise ValueError(f"request id {rid!r} already admitted")
        tokens = np.asarray(bundle.get("prompt") or (), np.int32).reshape(-1)
        if tokens.size < 1:
            raise ValueError("KV bundle has an empty prompt")
        cap = int(params.get("max_new_tokens", self._default_cap))
        if cap < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {cap}")
        if tokens.size + cap > self._length:
            raise ValueError(
                f"prompt + budget ({tokens.size + cap}) exceeds the "
                f"session's static length ({self._length})"
            )
        if self.busy >= self.slots:
            raise RuntimeError("no free lane (all slots busy)")
        aname = str(bundle.get("adapter") or "")
        if aname and self._bank is None:
            raise ValueError(
                f"KV bundle was prefilled under adapter {aname!r} and "
                "this session hosts no adapter bank"
            )
        aslot, alabel = self._resolve_adapter(
            {"adapter": aname} if aname else {}
        )
        if aname:
            want = str(bundle.get("adapter_digest") or "")
            have = self._adapter_digests.get(alabel, "")
            if want and have and want != have:
                raise ValueError(
                    f"KV bundle adapter digest {want[:12]} does not match "
                    f"the attached {aname!r} generation {have[:12]} "
                    "(stale bundle after a hot swap)"
                )
        leaves = bundle.get("leaves")
        if not isinstance(leaves, (list, tuple)) or len(leaves) != len(
            self._lane_shapes
        ):
            raise ValueError(
                "KV bundle does not match this engine's cache layout "
                f"({len(leaves) if isinstance(leaves, (list, tuple)) else 0}"
                f" leaves, want {len(self._lane_shapes)})"
            )
        imported = []
        for leaf, (shape, dtype) in zip(leaves, self._lane_shapes):
            arr = np.asarray(leaf)
            if tuple(arr.shape) != shape or jnp.dtype(arr.dtype) != dtype:
                raise ValueError(
                    f"KV bundle lane leaf {arr.shape}/{arr.dtype} does "
                    f"not match this engine's {shape}/{dtype}"
                )
            imported.append(jnp.asarray(arr))
        lane = jax.tree_util.tree_unflatten(self._lane_treedef, imported)
        if self._bank is not None:
            lane = {"kv": lane, "adapter": jnp.asarray(aslot, jnp.int32)}
            self._rid_adapter[rid] = (aslot, alabel)
            self._slot_refs[aslot] += 1
            key = f"adapter_requests_{alabel}"
            self.stats[key] = self.stats.get(key, 0) + 1
        first = int(bundle.get("first") or 0)
        self._pending_kv.append((rid, tokens, cap, first, lane, aslot))
        self.stats["kv_admits"] += 1

    def step(self) -> list[dict]:
        """Flush admissions, run one sync chunk, return fresh tokens.

        One event per request with new output since the previous chunk:
        ``{"rid", "tokens": [int, ...], "done": bool}`` — the first
        event includes the admission-prefill token, the final one the
        EOS (when configured), exactly the rows ``continuous_generate``
        would return, just delivered incrementally.  Busy decode-mode
        lane groups step in the same call (their events merge in), and
        per-mode token counters plus the groups' own stats fold into
        :attr:`stats` here, so one dict stays the whole session's view.
        """
        if self._bank is not None:
            self._reclaim_adapter_slots()
        events = self._step_local()
        fresh = sum(len(ev["tokens"]) for ev in events)
        if fresh:
            key = f"mode_tokens_{self._mode}"
            self.stats[key] = self.stats.get(key, 0) + fresh
        for mode, sub in self._subs.items():
            if not sub.busy:
                continue
            for ev in sub.step():
                if ev.get("done"):
                    self._rid_mode.pop(ev["rid"], None)
                events.append(ev)
        self._sync_sub_stats()
        return events

    def _sync_sub_stats(self) -> None:
        """Delta-merge the mode groups' counters into the primary's
        stats dict: subs keep counting monotonically, the primary adds
        only what is new since its last sync — ``engine.stats`` stays a
        plain live dict covering every lane group."""
        for mode, sub in self._subs.items():
            seen = self._sub_stats_seen[mode]
            for key, value in sub.stats.items():
                if not isinstance(value, int):
                    continue
                delta = value - seen.get(key, 0)
                if delta:
                    self.stats[key] = self.stats.get(key, 0) + delta
                    seen[key] = value

    def _step_local(self) -> list[dict]:
        """One sync chunk on THIS lane group only (plain or speculative
        decode, whichever the session resolved to at construction)."""
        self._flush_admissions()
        if not self._rid_slot:
            return []
        if self._spec_run is not None:
            (self._state, self._draft_caches, proposed, accepted) = (
                self._spec_run(
                    self._params, self._draft_params, self._state,
                    self._draft_caches,
                )
            )
            self.stats["spec_rounds"] += self._spec_rounds
            self.stats["spec_proposed"] += int(proposed)
            self.stats["spec_accepted"] += int(accepted)
        else:
            self._state = self._run_steps(self._params, self._state)
        buffer_h = np.asarray(self._state[1])
        plen_h = np.asarray(self._state[3])
        n_gen_h = np.asarray(self._state[5])
        done_h = np.asarray(self._state[6])
        events: list[dict] = []
        for slot in range(self.slots):
            rid = self._slot_rid[slot]
            if rid is None:
                continue
            total = int(n_gen_h[slot])
            start = int(plen_h[slot]) + self._reported[slot]
            fresh = buffer_h[slot, start: int(plen_h[slot]) + total]
            finished = bool(done_h[slot])
            if fresh.size or finished:
                events.append({
                    "rid": rid,
                    "tokens": [int(t) for t in fresh],
                    "done": finished,
                })
            if self._bank is not None and fresh.size:
                aname = self._rid_adapter.get(rid, (0, "base"))[1]
                key = f"adapter_tokens_{aname}"
                self.stats[key] = self.stats.get(key, 0) + int(fresh.size)
            self._reported[slot] += int(fresh.size)
            if finished:
                self._slot_rid[slot] = None
                self._rid_slot.pop(rid, None)
                self._release_adapter(rid)
        return events

    def cancel(self, rid: str) -> None:
        """Free a request's lane early (deadline/disconnect).

        The lane is marked done device-side — the scan freezes it like any
        finished row — and freed for re-admission (which resets the lane's
        cache and buffer anyway).
        """
        mode = self._rid_mode.pop(rid, None)
        if mode is not None:
            sub = self._subs.get(mode)
            if sub is not None:
                sub.cancel(rid)
            return
        self._pending = [p for p in self._pending if p[0] != rid]
        self._pending_kv = [p for p in self._pending_kv if p[0] != rid]
        self._release_adapter(rid)
        slot = self._rid_slot.pop(rid, None)
        if slot is None:
            return
        caches, buffer, pos, plen, row_cap, n_gen, done, rng = self._state
        self._state = (
            caches, buffer, pos, plen, row_cap, n_gen,
            done.at[slot].set(True), rng,
        )
        self._slot_rid[slot] = None

    def close(self) -> None:
        """Drop device state so the backend can reclaim the cache lanes."""
        self._state = None
        self._draft_caches = None
        self._pending.clear()
        self._pending_kv.clear()
        self._prefix_tree.clear()
        self._rid_slot.clear()
        self._slot_rid = [None] * self.slots
        self._rid_adapter.clear()
        self._slot_refs = [0] * len(self._slot_refs)
        for sub in self._subs.values():
            sub.close()
        self._rid_mode.clear()

    @property
    def busy(self) -> int:
        return (
            len(self._rid_slot) + len(self._pending)
            + len(self._pending_kv)
            + sum(sub.busy for sub in self._subs.values())
        )

    @property
    def spec_active(self) -> bool:
        """True when any lane group is verifying draft proposals — the
        harness keys its ``spec_verify`` waterfall attribution on this."""
        return self._spec_run is not None or any(
            sub._spec_run is not None for sub in self._subs.values()
        )

    @property
    def decode_modes(self) -> tuple[str, ...]:
        """The built lane groups, fp first (refused modes absent)."""
        return (self._mode,) + tuple(self._subs)

    # -- internals ---------------------------------------------------------

    def _lookup_prefix(
        self, tokens: np.ndarray, aslot: int = 0
    ) -> tuple[int, Any, str]:
        """``(m, lane, entry_digest)`` of the deepest cached prefix
        usable for ``tokens`` — ``(0, None, "")`` when none qualifies.

        An entry is usable at depth ``m`` when its first ``m`` tokens
        equal the prompt's (m capped at ``len(prompt) - 1``: the suffix
        pass needs at least one position to read first-token logits
        from) and ``m >= prefix_min_tokens``.  A partial match rewinds
        the entry's lane cursor to ``m`` — positions past the rewound
        cursor hold stale K/V that stays dead until the suffix pass
        overwrites it, the same exactness argument the pad positions
        ride.  Touches the winning entry's LRU slot; counts nothing
        (callers own the hit/miss stats) EXCEPT the adapter fence:
        entries are scoped to the bank slot whose weights computed them,
        so a cross-adapter prompt match never reuses another adapter's
        K/V — the admission degrades to a full prefill (byte-equal, just
        slower) and ``stats["adapter_prefix_blocked"]`` counts the
        would-have-hit.
        """
        best_m, best_digest, best_entry = 0, "", None
        blocked = False
        limit_all = int(tokens.size) - 1
        for digest, entry in self._prefix_tree.items():
            limit = min(int(entry.tokens.size), limit_all)
            if limit < self._prefix_min:
                continue
            if entry.aslot != aslot:
                eq = entry.tokens[:limit] == tokens[:limit]
                m = limit if bool(eq.all()) else int(np.argmin(eq))
                if m >= self._prefix_min:
                    blocked = True
                continue
            if limit <= best_m:
                continue
            eq = entry.tokens[:limit] == tokens[:limit]
            m = limit if bool(eq.all()) else int(np.argmin(eq))
            if m >= self._prefix_min and m > best_m:
                best_m, best_digest, best_entry = m, digest, entry
        if best_entry is None:
            if blocked:
                self.stats["adapter_prefix_blocked"] += 1
            return 0, None, ""
        self._prefix_tree.move_to_end(best_digest)
        lane = best_entry.lane
        if best_m != int(best_entry.tokens.size):
            lane = _set_cursor(lane, best_m)
        return best_m, lane, best_digest

    def _insert_prefix(
        self, tokens: np.ndarray, lane_fn: Callable[[], Any],
        pinned: bool = False, aslot: int = 0,
    ) -> None:
        """Cache one prefilled lane under its token digest (LRU-bounded).

        ``lane_fn`` defers the (device-gather) lane materialization until
        the entry is known to be fresh and cacheable; pinned entries
        (the constructor's ``shared_prefix``) never count against the
        bound and never evict.  The key is scoped by the adapter bank
        slot (``aslot``), so the same prompt under two adapters is two
        entries — cross-adapter reuse is structurally impossible.
        """
        if not pinned and (
            self._prefix_cache_size <= 0
            or int(tokens.size) < self._prefix_min + 1
        ):
            return
        digest = f"{int(aslot)}:{_tokens_digest(tokens)}"
        if digest in self._prefix_tree:
            self._prefix_tree.move_to_end(digest)
            return
        self._prefix_tree[digest] = _PrefixEntry(
            np.array(tokens, np.int32, copy=True), lane_fn(), pinned,
            int(aslot),
        )
        unpinned = [
            d for d, e in self._prefix_tree.items() if not e.pinned
        ]
        while len(unpinned) > self._prefix_cache_size:
            del self._prefix_tree[unpinned.pop(0)]
            self.stats["prefix_evictions"] += 1

    def _flush_admissions(self) -> None:
        """Admit pending requests in fused bucketed waves (one compiled
        call per bucket per path), mirroring ``continuous_generate``'s
        ``admit_group`` — including the per-admission key chain, which is
        split in admission order BEFORE the prefix partition so sampled
        streams draw identically whichever prefill road they take.

        A prompt with a usable prefix-tree lane prefills only its suffix
        on top of it (``_make_prefix_admit``, grouped by entry + depth +
        bucket); everything else takes the full-prompt wave; KV-bundle
        admissions skip prefill entirely (``_make_kv_admit``).  After
        the waves run, each freshly prefilled lane is inserted back into
        the prefix tree, so repeated prompts and shared prefixes across
        later requests hit warm KV.
        """
        if not (self._pending or self._pending_kv):
            return
        free = [s for s in range(self.slots) if self._slot_rid[s] is None]
        picked: list[tuple[int, np.ndarray, int, Any, int, int]] = []
        #: (entry digest, m, bucket) ->
        #:   (lane, [(slot, tokens, cap, key, aslot)]) — entry digests
        #: are adapter-scoped, so a group is adapter-homogeneous and the
        #: reused lane already carries the right bank slot.
        picked_prefix: dict[tuple[str, int, int], tuple[Any, list]] = {}
        picked_kv: list[tuple[int, np.ndarray, int, int, Any, int]] = []
        while self._pending and free:
            rid, tokens, cap, aslot = self._pending.pop(0)
            slot = free.pop(0)
            self._slot_rid[slot] = rid
            self._rid_slot[rid] = slot
            self._reported[slot] = 0
            self._adm_key, key = jax.random.split(self._adm_key)
            m, lane_m, entry_digest = self._lookup_prefix(tokens, aslot)
            if m:
                # Pad K/V land at cache slots >= m + suffix length, so
                # the bucket is capped to what fits BEYOND the reused
                # prefix (admit() already bounded prompt + budget).
                bucket = min(
                    1 << (int(tokens.size) - m - 1).bit_length(),
                    self._config.max_seq - m,
                )
                self.stats["prefix_hits"] += 1
                self.stats["prefill_positions"] += bucket
                lane_g, group = picked_prefix.setdefault(
                    (entry_digest, m, bucket), (lane_m, [])
                )
                group.append((slot, tokens, cap, key, aslot))
            else:
                bucket = min(
                    1 << (int(tokens.size) - 1).bit_length(),
                    self._config.max_seq,
                )
                if self._prefix_tree:
                    self.stats["prefix_misses"] += 1
                self.stats["prefill_positions"] += bucket
                picked.append((slot, tokens, cap, key, bucket, aslot))
        while self._pending_kv and free:
            rid, tokens, cap, first, lane, aslot = self._pending_kv.pop(0)
            slot = free.pop(0)
            self._slot_rid[slot] = rid
            self._rid_slot[rid] = slot
            self._reported[slot] = 0
            picked_kv.append((slot, tokens, cap, first, lane, aslot))
        for bucket in sorted({p[4] for p in picked}):
            group = [p for p in picked if p[4] == bucket]
            g = 1 << (len(group) - 1).bit_length()
            rows = np.full((g, self._length), self._pad, np.int32)
            padded = np.full((g, bucket), self._pad, np.int32)
            plens = np.ones(g, np.int32)
            slots = np.full(g, self.slots, np.int32)  # OOB rows dropped
            caps_in = np.ones(g, np.int32)
            aidxs = np.zeros(g, np.int32)
            keys = [jax.random.PRNGKey(0)] * g
            for r, (slot, tokens, cap, key, _, aslot) in enumerate(group):
                rows[r, : tokens.size] = tokens
                padded[r, : tokens.size] = tokens
                plens[r] = tokens.size
                slots[r] = slot
                caps_in[r] = cap
                aidxs[r] = aslot
                keys[r] = key
            wave = _make_admit(
                self._decoder, self._temperature, self._top_k, self._eos,
                int(self.slots), int(bucket), int(g),
                adapters=self._bank is not None,
            )
            args = [
                self._params, self._state, jnp.asarray(rows),
                jnp.asarray(padded), jnp.asarray(plens),
                jnp.asarray(slots), jnp.asarray(caps_in), jnp.stack(keys),
            ]
            if self._bank is not None:
                args.append(jnp.asarray(aidxs))
            self._state = wave(*args)
        for (_entry, m, bucket), (lane_m, group) in picked_prefix.items():
            g = 1 << (len(group) - 1).bit_length()
            rows = np.full((g, self._length), self._pad, np.int32)
            padded = np.full((g, bucket), self._pad, np.int32)
            slens = np.ones(g, np.int32)
            slots = np.full(g, self.slots, np.int32)  # OOB rows dropped
            caps_in = np.ones(g, np.int32)
            keys = [jax.random.PRNGKey(0)] * g
            for r, (slot, tokens, cap, key, _aslot) in enumerate(group):
                suffix = tokens[m:]
                rows[r, : tokens.size] = tokens
                padded[r, : suffix.size] = suffix
                slens[r] = suffix.size
                slots[r] = slot
                caps_in[r] = cap
                keys[r] = key
            wave = _make_prefix_admit(
                self._decoder, self._temperature, self._top_k, self._eos,
                int(self.slots), int(bucket), int(g), int(m),
            )
            self._state = wave(
                self._params, self._state, lane_m,
                jnp.asarray(rows), jnp.asarray(padded),
                jnp.asarray(slens), jnp.asarray(slots),
                jnp.asarray(caps_in), jnp.stack(keys),
            )
        if picked_kv:
            g = 1 << (len(picked_kv) - 1).bit_length()
            rows = np.full((g, self._length), self._pad, np.int32)
            plens = np.ones(g, np.int32)
            firsts = np.zeros(g, np.int32)
            slots = np.full(g, self.slots, np.int32)  # OOB rows dropped
            caps_in = np.ones(g, np.int32)
            lanes = [p[4] for p in picked_kv]
            lanes += [lanes[0]] * (g - len(lanes))  # padded rows drop
            for r, (slot, tokens, cap, first, _lane, _aslot) in enumerate(
                picked_kv
            ):
                rows[r, : tokens.size] = tokens
                rows[r, tokens.size] = first
                plens[r] = tokens.size
                firsts[r] = first
                slots[r] = slot
                caps_in[r] = cap
            stacked = jax.tree_util.tree_map(
                lambda *leaves: jnp.stack(leaves), *lanes
            )
            wave = _make_kv_admit(self._eos, int(self.slots), int(g))
            self._state = wave(
                self._state, stacked, jnp.asarray(rows),
                jnp.asarray(plens), jnp.asarray(firsts),
                jnp.asarray(slots), jnp.asarray(caps_in),
            )
        # Draft lanes: every admission (full, prefix-hit, or KV-import)
        # also full-prompt-prefills the DRAFT model's lane for its slot,
        # in fused bucketed waves like the target's — the spec rounds'
        # repair slab picks up from the parked cursor.  KV bundles ship
        # only target K/V, so an imported admission pays this small pass
        # too; the draft is the cheap model by construction.
        if self._draft is not None:
            admitted = (
                [(slot, tokens) for slot, tokens, *_ in picked]
                + [
                    (slot, tokens)
                    for _key, (_lane, group) in picked_prefix.items()
                    for slot, tokens, *_rest in group
                ]
                + [(slot, tokens) for slot, tokens, *_ in picked_kv]
            )
            by_bucket: dict[int, list] = {}
            for slot, tokens in admitted:
                bucket = min(
                    1 << (int(tokens.size) - 1).bit_length(),
                    self._draft.config.max_seq,
                )
                by_bucket.setdefault(bucket, []).append((slot, tokens))
            for bucket in sorted(by_bucket):
                group = by_bucket[bucket]
                g = 1 << (len(group) - 1).bit_length()
                padded = np.full((g, bucket), self._pad, np.int32)
                plens = np.ones(g, np.int32)
                slots = np.full(g, self.slots, np.int32)  # OOB drop
                for r, (slot, tokens) in enumerate(group):
                    padded[r, : tokens.size] = tokens
                    plens[r] = tokens.size
                    slots[r] = slot
                wave = _make_draft_admit(
                    self._draft, int(self.slots), int(bucket), int(g)
                )
                self._draft_caches = wave(
                    self._draft_params, self._draft_caches,
                    jnp.asarray(padded), jnp.asarray(plens),
                    jnp.asarray(slots),
                )
        # Feed the tree: every admission's post-wave lane (cursor already
        # parked at the prompt length by its wave — or carried by the
        # imported bundle) becomes a reusable prefix for later prompts.
        if self._prefix_cache_size > 0:
            state = self._state
            candidates = [
                (p[0], p[1], p[5]) for p in picked
            ] + [
                (slot, tokens, aslot)
                for _, (_lane, group) in picked_prefix.items()
                for slot, tokens, _cap, _key, aslot in group
            ] + [
                (p[0], p[1], p[5]) for p in picked_kv
            ]
            for slot, tokens, aslot in candidates:
                self._insert_prefix(
                    tokens,
                    lambda slot=slot: jax.tree_util.tree_map(
                        lambda c: c[slot], state[0]
                    ),
                    aslot=aslot,
                )


def lm_engine_factory(model: TransformerLM, params: Any, **engine_kwargs):
    """A zero-arg serving-session factory for an LM.

    The returned closure is what ``serving.open_session`` cloudpickles
    into the CAS; called inside the resident worker it builds the
    :class:`ContinuousEngine` (loading params and compiling the decode/
    prefill programs ONCE for the session's lifetime).  Note cloudpickle
    serializes this module by *reference* — workers must be able to
    import the package (or the caller registers it by value via
    ``cloudpickle.register_pickle_by_value``).
    """
    def factory() -> ContinuousEngine:
        return ContinuousEngine(model, params, **engine_kwargs)

    return factory
