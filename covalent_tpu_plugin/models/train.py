"""Sharded training: state construction and jitted train steps.

The flax scale-up recipe, packaged: ``jax.eval_shape`` the state, read the
logical axis names off the boxed params, translate them to NamedShardings
through the rules, then jit init and step with explicit in/out shardings and
donated state.  Everything under ``jit`` — no data-dependent Python control
flow; XLA sees one static graph per (mesh, shapes) pair and inserts all
collectives (gradient psum over data axes, all-gathers for fsdp, etc.).
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from flax.training import train_state
from jax.sharding import Mesh

from ..parallel.sharding import DEFAULT_RULES, replicated


class TrainState(train_state.TrainState):
    """flax TrainState (params + optax state + step)."""


def cross_entropy_loss(
    logits: jax.Array, labels: jax.Array, mask: jax.Array | None = None
) -> jax.Array:
    """Mean softmax cross-entropy in float32."""
    logits = logits.astype(jnp.float32)
    losses = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    if mask is not None:
        return (losses * mask).sum() / jnp.maximum(mask.sum(), 1)
    return losses.mean()


def make_sharded_train_state(
    model: nn.Module,
    tx: optax.GradientTransformation,
    rng: jax.Array,
    sample_input: Any,
    mesh: Mesh,
    rules=DEFAULT_RULES,
) -> tuple[TrainState, Any]:
    """Initialise a TrainState with every leaf placed per the logical rules.

    Returns ``(state, state_shardings)``; the shardings pytree feeds the
    train step's in/out shardings.  Parameters are materialised *directly
    into their shards* (init under jit with out_shardings), so a model too
    big for one host's memory still initialises.
    """

    def init_fn(rng):
        variables = model.init(rng, sample_input)
        return TrainState.create(
            apply_fn=model.apply, params=variables["params"], tx=tx
        )

    abstract = jax.eval_shape(init_fn, rng)
    logical_specs = nn.get_partition_spec(abstract)
    shardings = nn.logical_to_mesh_sharding(logical_specs, mesh, list(rules))
    # Deliberately NOT under `with mesh:`: the params are boxed with
    # *logical* axis names via nn.with_partitioning, and flax's
    # Partitioned.unbox applies those names verbatim as a sharding
    # constraint whenever a global mesh is active — "vocab"/"embed" are not
    # physical mesh axes, so tracing init (or apply) under an ambient mesh
    # raises.  Placement comes entirely from the explicit out_shardings,
    # which logical_to_mesh_sharding already translated through the rules.
    state = jax.jit(init_fn, out_shardings=shardings)(rng)
    return state, shardings


def make_train_step(
    loss_fn: Callable[[Any, Any, Any], jax.Array],
    mesh: Mesh,
    state_shardings: Any,
    rules=DEFAULT_RULES,
    donate_state: bool = True,
    accumulate_steps: int = 1,
) -> Callable[[TrainState, Any], tuple[TrainState, dict]]:
    """Build the jitted sharded train step.

    ``loss_fn(params, apply_fn, batch) -> scalar loss``.  The batch arrives
    sharded over the data axes; gradients and metrics come out as the mesh
    demands (XLA inserts the psums).  The state is donated — its buffers are
    reused for the updated state, halving peak HBM.

    ``accumulate_steps > 1`` enables gradient accumulation: every batch
    leaf carries a leading microbatch axis of that length (dim 1 is then
    the data-sharded batch dim), a ``lax.scan`` accumulates mean gradients
    across the microbatches — activation memory stays one microbatch — and
    the optimizer applies once.  With mean-reducing losses and equal-size
    microbatches this equals the full-batch gradient up to f32
    reduction-order rounding (the accumulator is f32 regardless of param
    dtype).
    """
    def grads_of(params, apply_fn, batch):
        return jax.value_and_grad(
            lambda p: loss_fn(p, apply_fn, batch)
        )(params)

    def step(state: TrainState, batch: Any) -> tuple[TrainState, dict]:
        with nn.logical_axis_rules(list(rules)):
            if accumulate_steps == 1:
                loss, grads = grads_of(state.params, state.apply_fn, batch)
            else:
                lead = {
                    leaf.shape[0] for leaf in jax.tree_util.tree_leaves(batch)
                }
                if lead != {accumulate_steps}:
                    raise ValueError(
                        f"accumulate_steps={accumulate_steps} but batch "
                        f"leaves have leading axis {sorted(lead)}; every "
                        "leaf needs a leading microbatch axis of that length"
                    )

                def micro(carry, microbatch):
                    loss_acc, grads_acc = carry
                    loss, grads = grads_of(
                        state.params, state.apply_fn, microbatch
                    )
                    return (
                        loss_acc + loss,
                        jax.tree_util.tree_map(jnp.add, grads_acc, grads),
                    ), None

                zeros = jax.tree_util.tree_map(
                    lambda p: jnp.zeros(p.shape, jnp.float32), state.params
                )
                (loss, grads), _ = jax.lax.scan(
                    micro, (jnp.zeros((), jnp.float32), zeros), batch
                )
                scale = 1.0 / accumulate_steps
                loss = loss * scale
                grads = jax.tree_util.tree_map(
                    lambda g, p: (g * scale).astype(p.dtype),
                    grads, state.params,
                )
            new_state = state.apply_gradients(grads=grads)
            metrics = {
                "loss": loss,
                "grad_norm": optax.global_norm(grads),
                "step": new_state.step,
            }
            return new_state, metrics

    metrics_sharding = {
        "loss": replicated(mesh),
        "grad_norm": replicated(mesh),
        "step": replicated(mesh),
    }
    return jax.jit(
        step,
        in_shardings=(state_shardings, None),
        out_shardings=(state_shardings, metrics_sharding),
        donate_argnums=(0,) if donate_state else (),
    )


def classifier_loss(params, apply_fn, batch):
    logits = apply_fn({"params": params}, batch["image"])
    return cross_entropy_loss(logits, batch["label"])


def lm_loss(params, apply_fn, batch, vocab_chunk: int | None = None):
    """Next-token loss over a {"tokens": (B, S)} batch.

    ``vocab_chunk`` switches to the fused vocab-chunked cross-entropy
    (``ops/xent.py``): the model returns final FEATURES and the loss
    streams over lm_head chunks, so the (B, S, vocab) logits tensor is
    never materialised in HBM — the loss-side bandwidth lever the round-4
    step sweep left on the table.  Requires a plain float lm_head kernel
    (no lm_head LoRA, unquantized)."""
    tokens = batch["tokens"]
    if vocab_chunk is None:
        logits = apply_fn({"params": params}, tokens[:, :-1])
        return cross_entropy_loss(logits, tokens[:, 1:])
    from ..ops.xent import fused_cross_entropy

    feats = apply_fn(
        {"params": params}, tokens[:, :-1], return_features=True
    )
    from flax.core import meta as flax_meta

    # The kernel may ride in a flax Partitioned box (sharded init path).
    head = params["lm_head"]
    kernel = flax_meta.unbox(head["kernel"])
    if "lora_a" in head or not jnp.issubdtype(
        jnp.asarray(kernel).dtype, jnp.floating
    ):
        # A LoRA head's adapters would be silently dropped (and get zero
        # grads); a quantized head's kernel is int8 + scales.  Both take
        # the standard logits path.
        raise ValueError(
            "vocab_chunk needs a plain float lm_head kernel "
            "(quantized/LoRA heads take the standard path)"
        )
    flat = feats.reshape(-1, feats.shape[-1])
    labels = tokens[:, 1:].reshape(-1)
    return fused_cross_entropy(flat, kernel, labels, vocab_chunk)


def make_lm_train_step(mesh, state_shardings, rules=DEFAULT_RULES):
    return make_train_step(lm_loss, mesh, state_shardings, rules)


def make_classifier_train_step(mesh, state_shardings, rules=DEFAULT_RULES):
    return make_train_step(classifier_loss, mesh, state_shardings, rules)
