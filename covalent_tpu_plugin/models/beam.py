"""Beam search over the KV-cache decoder.

Width-``W`` beam search per prompt row: every step scores all ``W * V``
continuations, keeps the global top ``W``, and reorders the KV caches to
follow their parent beams (a batch-axis gather on every cache leaf — no
recompute).  ``beam_width=1`` degenerates to exactly :func:`..decode
.generate`'s greedy path, which is the correctness oracle.

TPU shape notes: beams ride the batch axis (``B*W`` rows), so every
matmul stays a single large GEMM; the top-W is one ``lax.top_k`` over
``(B, W*V)``; the cache reorder is a gather XLA fuses with the step.
The prompt is prefilled already tiled to ``B*W`` rows — W× redundant
prefill compute for a much simpler cache story (one shape end to end);
fine at serving prompt lengths, noted here for honesty.

EOS semantics: a finished beam is frozen — its only continuation is
another EOS at zero additional log-probability — so finished hypotheses
compete with ongoing ones on their final score.  ``length_penalty``
(GNMT-style ``len**alpha`` divisor) applies to the final ranking.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from ..ops.attention import NEG_INF
from .decode import _decode_model, init_cache
from .transformer import TransformerLM


def _gather_beams(cache: Any, rows: jax.Array, n_rows: int) -> Any:
    """Reorder every cache leaf's batch axis by ``rows``.

    K/V leaves are ``(B, S, H, D)`` unrolled or ``(L, B, S, H, D)`` under
    scanned layers, so the batch axis is ``ndim - 4`` — a layout fact,
    not a size heuristic (sizes can collide, e.g. ``L == B*W``).  Cursor
    leaves (ndim < 4) pass through: they are per-layer, not per-beam.
    """

    def gather(leaf):
        if leaf.ndim < 4:
            return leaf
        axis = leaf.ndim - 4
        assert leaf.shape[axis] == n_rows, (leaf.shape, n_rows)
        return jnp.take(leaf, rows, axis=axis)

    return jax.tree_util.tree_map(gather, cache)


def rank_hypotheses(
    scores: jax.Array, lengths: jax.Array, length_penalty: float
) -> jax.Array:
    """GNMT-style ranking keys: each hypothesis's raw log-prob sum over
    ITS OWN generated length (frozen EOS padding excluded) to the
    ``length_penalty`` power — short finished beams compete fairly with
    long ongoing ones.  ``length_penalty=0`` ranks by raw sums."""
    return scores / (lengths ** length_penalty)


def beam_search(
    model: TransformerLM,
    params: Any,
    prompt: jax.Array,
    max_new_tokens: int,
    beam_width: int = 4,
    eos_token_id: int | None = None,
    length_penalty: float = 1.0,
) -> tuple[jax.Array, jax.Array]:
    """Beam-decode ``prompt`` ((B, P) int32).

    Returns ``(tokens, scores)``: tokens ``(B, W, P+N)`` and total
    log-probabilities ``(B, W)``, sorted best-first per row (scores
    divided by ``len**length_penalty`` for the ranking; the returned
    scores are the raw sums).  Fully jittable.
    """
    if not 1 <= beam_width <= model.config.vocab_size:
        raise ValueError(
            f"beam_width must be in [1, vocab_size={model.config.vocab_size}]"
            f", got {beam_width}"
        )
    decoder = _decode_model(model)
    config = decoder.config
    batch, prompt_len = prompt.shape
    total = prompt_len + max(max_new_tokens, 0)
    if total > config.max_seq:
        raise ValueError(
            f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) "
            f"exceeds config.max_seq ({config.max_seq})"
        )
    width = beam_width
    vocab = config.vocab_size
    if max_new_tokens <= 0:
        tokens = jnp.broadcast_to(
            prompt[:, None, :], (batch, width, prompt_len)
        ).astype(jnp.int32)
        return tokens, jnp.zeros((batch, width), jnp.float32)

    rows = batch * width
    tiled = jnp.repeat(prompt, width, axis=0)  # (B*W, P)
    cache = init_cache(model, rows)
    buffer = jnp.zeros((rows, total), jnp.int32)
    buffer = jax.lax.dynamic_update_slice(buffer, tiled, (0, 0))

    prefill_logits, mutated = decoder.apply(
        {"params": params, "cache": cache}, tiled, mutable=["cache"]
    )
    cache = mutated["cache"]
    logprobs = jax.nn.log_softmax(
        prefill_logits[:, -1].astype(jnp.float32), axis=-1
    )  # (B*W, V); all W copies of a row are identical here

    # First step: top-W distinct tokens per original row seed the beams.
    first_scores, first_tokens = jax.lax.top_k(
        logprobs.reshape(batch, width, vocab)[:, 0], width
    )  # (B, W)
    scores = first_scores  # (B, W)
    buffer = jax.lax.dynamic_update_slice(
        buffer,
        first_tokens.reshape(rows, 1).astype(jnp.int32),
        (0, prompt_len),
    )
    finished = (
        (first_tokens == eos_token_id)
        if eos_token_id is not None
        else jnp.zeros((batch, width), bool)
    )
    lengths = jnp.ones((batch, width), jnp.float32)  # generated tokens

    def body(carry):
        buffer, cache, scores, finished, lengths, t = carry
        token = jax.lax.dynamic_slice(buffer, (0, t), (rows, 1))
        logits, mutated = decoder.apply(
            {"params": params, "cache": cache}, token, mutable=["cache"]
        )
        cache = mutated["cache"]
        logprobs = jax.nn.log_softmax(
            logits[:, 0].astype(jnp.float32), axis=-1
        ).reshape(batch, width, vocab)
        if eos_token_id is not None:
            # Frozen beams: only EOS continues, for free.
            frozen = jnp.full((vocab,), NEG_INF).at[eos_token_id].set(0.0)
            logprobs = jnp.where(
                finished[:, :, None], frozen[None, None, :], logprobs
            )
        candidates = scores[:, :, None] + logprobs  # (B, W, V)
        scores, flat_idx = jax.lax.top_k(
            candidates.reshape(batch, width * vocab), width
        )
        parent = flat_idx // vocab  # (B, W) beam each winner extends
        chosen = (flat_idx % vocab).astype(jnp.int32)

        # Follow the parents: reorder buffer rows + every cache leaf.
        row_idx = (
            jnp.arange(batch)[:, None] * width + parent
        ).reshape(rows)
        buffer = jnp.take(buffer, row_idx, axis=0)
        cache = _gather_beams(cache, row_idx, rows)
        lengths = jnp.take_along_axis(lengths, parent, axis=1)
        if eos_token_id is not None:
            was_finished = jnp.take_along_axis(finished, parent, axis=1)
            # A frozen beam's forced EOS padding doesn't count as length.
            lengths = jnp.where(was_finished, lengths, lengths + 1.0)
            finished = was_finished | (chosen == eos_token_id)
        else:
            lengths = lengths + 1.0
        buffer = jax.lax.dynamic_update_slice(
            buffer, chosen.reshape(rows, 1), (0, t + 1)
        )
        return buffer, cache, scores, finished, lengths, t + 1

    def cond(carry):
        _, _, _, finished, _, t = carry
        return (t < total - 1) & ~jnp.all(finished)

    buffer, _, scores, _, lengths, t = jax.lax.while_loop(
        cond,
        body,
        (buffer, cache, scores, finished, lengths,
         jnp.asarray(prompt_len)),
    )
    if eos_token_id is not None:
        # An early exit (all beams frozen) leaves columns > t unwritten;
        # stamp them with EOS as the in-loop freezing would have.
        cols = jnp.arange(total)[None, :]
        buffer = jnp.where(cols > t, jnp.int32(eos_token_id), buffer)

    tokens = buffer.reshape(batch, width, total)
    order = jnp.argsort(-rank_hypotheses(scores, lengths, length_penalty), axis=1)
    tokens = jnp.take_along_axis(tokens, order[:, :, None], axis=1)
    scores = jnp.take_along_axis(scores, order, axis=1)
    return tokens, scores
