"""Beam search over the KV-cache decoder.

Width-``W`` beam search per prompt row: every step scores all ``W * V``
continuations, keeps the global top ``W``, and reorders the KV caches to
follow their parent beams (a batch-axis gather on every cache leaf — no
recompute).  ``beam_width=1`` degenerates to exactly :func:`..decode
.generate`'s greedy path, which is the correctness oracle.

TPU shape notes: beams ride the batch axis (``B*W`` rows), so every
matmul stays a single large GEMM; the top-W is one ``lax.top_k`` over
``(B, W*V)``; the cache reorder is a gather XLA fuses with the step.
The prompt is prefilled already tiled to ``B*W`` rows — W× redundant
prefill compute for a much simpler cache story (one shape end to end);
fine at serving prompt lengths, noted here for honesty.

EOS semantics (the HF/fairseq convention): each step considers the top
``2W`` candidates; those ending in EOS are *banked* into a per-row
finished pool (the best ``W`` by ranking key) and the top ``W`` non-EOS
candidates stay active, so finished hypotheses never occupy active
slots and are never lost to eviction.  The final ranking merges the
pool with the surviving active beams.  ``length_penalty`` (GNMT-style
``len**alpha`` divisor) applies to pool retention and the final
ranking, never to the active search.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from ..ops.attention import NEG_INF
from .decode import _decode_model, init_cache
from ._jitcache import cached_jit
from .transformer import TransformerLM


def _gather_beams(cache: Any, rows: jax.Array, n_rows: int) -> Any:
    """Reorder every cache leaf's batch axis by ``rows``.

    K/V leaves are ``(B, S, H, D)`` unrolled or ``(L, B, S, H, D)`` under
    scanned layers, so the batch axis is ``ndim - 4`` — a layout fact,
    not a size heuristic (sizes can collide, e.g. ``L == B*W``).  Cursor
    leaves (ndim < 4) pass through: they are per-layer, not per-beam.
    """

    def gather(leaf):
        if leaf.ndim < 4:
            return leaf
        axis = leaf.ndim - 4
        assert leaf.shape[axis] == n_rows, (leaf.shape, n_rows)
        return jnp.take(leaf, rows, axis=axis)

    return jax.tree_util.tree_map(gather, cache)


def rank_hypotheses(
    scores: jax.Array, lengths: jax.Array, length_penalty: float
) -> jax.Array:
    """GNMT-style ranking keys: each hypothesis's raw log-prob sum over
    ITS OWN generated length (frozen EOS padding excluded) to the
    ``length_penalty`` power — short finished beams compete fairly with
    long ongoing ones.  ``length_penalty=0`` ranks by raw sums."""
    return scores / (lengths ** length_penalty)


def _beam_search_traced(
    model: TransformerLM,
    params: Any,
    prompt: jax.Array,
    max_new_tokens: int,
    beam_width: int = 4,
    eos_token_id: int | None = None,
    length_penalty: float = 1.0,
) -> tuple[jax.Array, jax.Array]:
    """Beam-decode ``prompt`` ((B, P) int32).

    Returns ``(tokens, scores)``: tokens ``(B, W, P+N)`` and total
    log-probabilities ``(B, W)``, sorted best-first per row (scores
    divided by ``len**length_penalty`` for the ranking; the returned
    scores are the raw sums).  Fully jittable.
    """
    if not 1 <= beam_width <= model.config.vocab_size:
        raise ValueError(
            f"beam_width must be in [1, vocab_size={model.config.vocab_size}]"
            f", got {beam_width}"
        )
    decoder = _decode_model(model)
    config = decoder.config
    batch, prompt_len = prompt.shape
    total = prompt_len + max(max_new_tokens, 0)
    if config.rolling_cache:
        # The circular cache frees beams from max_seq exactly as it frees
        # generate(): the ring's slot-position mask is per-absolute-position
        # and the per-layer cursor/slot vectors are shared across beams
        # (all rows advance in lockstep), so _gather_beams' batch-axis
        # reorder composes with the ring untouched.  Only the prompt (the
        # one prefill slab at position 0) must fit the ring.
        capacity = config.sliding_window + config.attention_sinks
        if prompt_len > capacity:
            raise ValueError(
                f"rolling_cache prefill of {prompt_len} tokens exceeds "
                f"the cache capacity ({capacity} = sliding_window + "
                "attention_sinks); chunk or truncate the prompt"
            )
    elif total > config.max_seq:
        raise ValueError(
            f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) "
            f"exceeds config.max_seq ({config.max_seq})"
        )
    width = beam_width
    vocab = config.vocab_size
    if max_new_tokens <= 0:
        tokens = jnp.broadcast_to(
            prompt[:, None, :], (batch, width, prompt_len)
        ).astype(jnp.int32)
        return tokens, jnp.zeros((batch, width), jnp.float32)

    rows = batch * width
    tiled = jnp.repeat(prompt, width, axis=0)  # (B*W, P)
    cache = init_cache(model, rows)
    buffer = jnp.zeros((rows, total), jnp.int32)
    buffer = jax.lax.dynamic_update_slice(buffer, tiled, (0, 0))

    prefill_logits, mutated = decoder.apply(
        {"params": params, "cache": cache}, tiled, mutable=["cache"]
    )
    cache = mutated["cache"]
    logprobs = jax.nn.log_softmax(
        prefill_logits[:, -1].astype(jnp.float32), axis=-1
    )  # (B*W, V); all W copies of a row are identical here

    # Candidate fan per step: 2W (the HF convention) so that up to W EOS
    # candidates can be banked while W non-EOS ones still fill the active
    # set.  Clamped for toy vocabularies.
    kk = min(2 * width, vocab) if eos_token_id is not None else width

    # Finished-hypothesis pool: the best W EOS-terminated candidates per
    # row so far, by ranking key.  Kept OUT of the active set — a banked
    # hypothesis can never be evicted by ongoing beams, and active slots
    # are never wasted on frozen beams.
    pool_scores = jnp.full((batch, width), NEG_INF, jnp.float32)
    pool_lengths = jnp.ones((batch, width), jnp.float32)
    pool_tokens = jnp.zeros((batch, width, total), jnp.int32)

    def bank(pool, cand_scores, cand_lengths, cand_tokens):
        """Merge EOS candidates into the pool, keep the top W by key."""
        pool_scores, pool_lengths, pool_tokens = pool
        merged_scores = jnp.concatenate([pool_scores, cand_scores], axis=1)
        merged_lengths = jnp.concatenate([pool_lengths, cand_lengths], axis=1)
        merged_tokens = jnp.concatenate([pool_tokens, cand_tokens], axis=1)
        _, keep = jax.lax.top_k(
            rank_hypotheses(merged_scores, merged_lengths, length_penalty),
            width,
        )
        return (
            jnp.take_along_axis(merged_scores, keep, axis=1),
            jnp.take_along_axis(merged_lengths, keep, axis=1),
            jnp.take_along_axis(merged_tokens, keep[:, :, None], axis=1),
        )

    # Seeding: top-kk distinct first tokens per row; EOS seeds go straight
    # to the pool, the top W non-EOS seed the active beams.
    seed_scores, seed_tokens = jax.lax.top_k(
        logprobs.reshape(batch, width, vocab)[:, 0], kk
    )  # (B, kk)
    if eos_token_id is not None:
        is_eos = seed_tokens == eos_token_id
        cols = jnp.arange(total)[None, None, :]
        # Seed hypothesis = prompt + EOS padding (same for every slot; the
        # scores mask keeps non-EOS slots out of the pool).
        padded = jnp.pad(
            prompt.astype(jnp.int32), ((0, 0), (0, total - prompt_len))
        )
        seed_rows = jnp.where(
            cols >= prompt_len, jnp.int32(eos_token_id),
            jnp.broadcast_to(padded[:, None, :], (batch, kk, total)),
        )
        pool_scores, pool_lengths, pool_tokens = bank(
            (pool_scores, pool_lengths, pool_tokens),
            jnp.where(is_eos, seed_scores, NEG_INF),
            jnp.ones((batch, kk), jnp.float32),
            seed_rows,
        )
        masked = jnp.where(is_eos, NEG_INF, seed_scores)
        scores, sel = jax.lax.top_k(masked, width)
        first_tokens = jnp.take_along_axis(seed_tokens, sel, axis=1)
        # Toy vocabularies (< 2W tokens) can leave fewer than W non-EOS
        # candidates: dead slots keep NEG_INF scores and decode EOS
        # padding rather than extending a garbage token.
        first_tokens = jnp.where(
            scores <= NEG_INF / 2, jnp.int32(eos_token_id), first_tokens
        )
    else:
        scores, first_tokens = seed_scores, seed_tokens
    buffer = jax.lax.dynamic_update_slice(
        buffer,
        first_tokens.reshape(rows, 1).astype(jnp.int32),
        (0, prompt_len),
    )
    lengths = jnp.ones((batch, width), jnp.float32)  # generated tokens

    def body(carry):
        (buffer, cache, scores, lengths,
         pool_scores, pool_lengths, pool_tokens, t) = carry
        token = jax.lax.dynamic_slice(buffer, (0, t), (rows, 1))
        logits, mutated = decoder.apply(
            {"params": params, "cache": cache}, token, mutable=["cache"]
        )
        cache = mutated["cache"]
        logprobs = jax.nn.log_softmax(
            logits[:, 0].astype(jnp.float32), axis=-1
        ).reshape(batch, width, vocab)
        candidates = scores[:, :, None] + logprobs  # (B, W, V)
        cand_scores, flat_idx = jax.lax.top_k(
            candidates.reshape(batch, width * vocab), kk
        )  # (B, kk)
        parent = flat_idx // vocab  # (B, kk) beam each candidate extends
        chosen = (flat_idx % vocab).astype(jnp.int32)

        if eos_token_id is not None:
            # Bank the EOS candidates: their hypothesis is the parent's
            # buffer row + EOS, padded with EOS to the fixed width.
            is_eos = chosen == eos_token_id
            cols = jnp.arange(total)[None, None, :]
            cand_rows = jnp.take_along_axis(
                buffer.reshape(batch, width, total),
                parent[:, :, None], axis=1,
            )  # (B, kk, total)
            cand_rows = jnp.where(
                cols > t, jnp.int32(eos_token_id), cand_rows
            )
            pool_scores, pool_lengths, pool_tokens = bank(
                (pool_scores, pool_lengths, pool_tokens),
                jnp.where(is_eos, cand_scores, NEG_INF),
                jnp.take_along_axis(lengths, parent, axis=1) + 1.0,
                cand_rows,
            )
            # Active set: the top W non-EOS candidates.  Dead slots (all
            # real candidates were EOS — only possible when vocab < 2W)
            # decode EOS padding at NEG_INF, never a garbage extension.
            masked = jnp.where(is_eos, NEG_INF, cand_scores)
            scores, sel = jax.lax.top_k(masked, width)
            parent = jnp.take_along_axis(parent, sel, axis=1)
            chosen = jnp.take_along_axis(chosen, sel, axis=1)
            chosen = jnp.where(
                scores <= NEG_INF / 2, jnp.int32(eos_token_id), chosen
            )
        else:
            scores = cand_scores

        # Follow the parents: reorder buffer rows + every cache leaf.
        row_idx = (
            jnp.arange(batch)[:, None] * width + parent
        ).reshape(rows)
        buffer = jnp.take(buffer, row_idx, axis=0)
        cache = _gather_beams(cache, row_idx, rows)
        lengths = jnp.take_along_axis(lengths, parent, axis=1) + 1.0
        buffer = jax.lax.dynamic_update_slice(
            buffer, chosen.reshape(rows, 1), (0, t + 1)
        )
        return (buffer, cache, scores, lengths,
                pool_scores, pool_lengths, pool_tokens, t + 1)

    def cond(carry):
        scores, t = carry[2], carry[7]
        keep_going = t < total - 1
        if eos_token_id is not None and length_penalty >= 0.0:
            # Sound early exit: ongoing raw scores only decrease
            # (logprobs <= 0), and for lp >= 0 a non-positive score's
            # ranking key s / len**lp is largest at the longest possible
            # length — so s_best / max_new**lp bounds every future
            # candidate's key.  Once each row's full pool beats that
            # bound, no future candidate can enter the final top-W.
            pool_keys = rank_hypotheses(carry[4], carry[5], length_penalty)
            best_future = jnp.max(scores, axis=1) / (
                float(max_new_tokens) ** length_penalty
            )
            can_improve = (
                best_future > jnp.min(pool_keys, axis=1)
            ).any()
            keep_going = keep_going & can_improve
        return keep_going

    (buffer, _, scores, lengths,
     pool_scores, pool_lengths, pool_tokens, t) = jax.lax.while_loop(
        cond,
        body,
        (buffer, cache, scores, lengths,
         pool_scores, pool_lengths, pool_tokens, jnp.asarray(prompt_len)),
    )

    tokens = buffer.reshape(batch, width, total)
    if eos_token_id is not None:
        # Early exit leaves active columns > t unwritten: stamp with EOS
        # (those rows lose to the pool anyway, but must read uniformly).
        cols = jnp.arange(total)[None, None, :]
        tokens = jnp.where(cols > t, jnp.int32(eos_token_id), tokens)
        # Final ranking over survivors AND the banked finished pool.
        tokens = jnp.concatenate([tokens, pool_tokens], axis=1)
        scores = jnp.concatenate([scores, pool_scores], axis=1)
        lengths = jnp.concatenate([lengths, pool_lengths], axis=1)
    order = jnp.argsort(
        -rank_hypotheses(scores, lengths, length_penalty), axis=1
    )[:, :width]
    tokens = jnp.take_along_axis(tokens, order[:, :, None], axis=1)
    scores = jnp.take_along_axis(scores, order, axis=1)
    return tokens, scores


def _beam_jit(model, max_new_tokens, beam_width, eos_token_id,
              length_penalty):
    def make():
        def run(params, prompt):
            return _beam_search_traced(
                model, params, prompt, max_new_tokens, beam_width,
                eos_token_id, length_penalty,
            )

        return run

    return cached_jit(
        ("beam", model, max_new_tokens, beam_width, eos_token_id,
         length_penalty),
        make,
    )


def beam_search(
    model: TransformerLM,
    params: Any,
    prompt: jax.Array,
    max_new_tokens: int,
    beam_width: int = 4,
    eos_token_id: int | None = None,
    length_penalty: float = 1.0,
) -> tuple[jax.Array, jax.Array]:
    """Jit-cached wrapper; semantics in `_beam_search_traced` (a bare
    call used to run the decode loop eagerly — see decode._generate_jit
    for the rationale)."""
    if max_new_tokens <= 0:
        return _beam_search_traced(
            model, params, prompt, max_new_tokens, beam_width,
            eos_token_id, length_penalty,
        )
    fn = _beam_jit(
        model, int(max_new_tokens), int(beam_width), eos_token_id,
        float(length_penalty),
    )
    return fn(params, jnp.asarray(prompt))
