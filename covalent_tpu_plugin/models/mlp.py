"""MNIST-class models (BASELINE configs 3-4) and synthetic data.

Data is generated, not downloaded — the deployment targets are zero-egress
TPU VMs, and the benchmark measures framework+compute performance, not
dataset IO.  ``synthetic_mnist`` produces a deterministic, learnable
class-conditional image distribution so "loss goes down" is a meaningful
assertion in tests and benchmarks.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


class MLP(nn.Module):
    """Flax MLP — the north star's "Flax MLP on MNIST" electron body."""

    features: tuple[int, ...] = (256, 128)
    num_classes: int = 10

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        for width in self.features:
            x = nn.relu(nn.Dense(width)(x))
        return nn.Dense(self.num_classes)(x)


class MnistCNN(nn.Module):
    """Small convnet for 28×28 inputs (BASELINE config 4)."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(32, (3, 3))(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (3, 3))(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(256)(x))
        return nn.Dense(self.num_classes)(x)


def synthetic_mnist(
    batch_size: int, *, seed: int = 0, flat: bool = False
) -> dict[str, np.ndarray]:
    """Class-conditional 28×28 images: each class is a distinct low-frequency
    template plus noise, so small models separate them quickly."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, size=(batch_size,))
    yy, xx = np.mgrid[0:28, 0:28].astype(np.float32) / 28.0
    templates = np.stack(
        [
            np.sin(2 * np.pi * (xx * (1 + c % 5) + yy * (1 + c // 5)) + c)
            for c in range(10)
        ]
    )
    images = templates[labels] + 0.3 * rng.standard_normal((batch_size, 28, 28)).astype(
        np.float32
    )
    images = images.astype(np.float32)[..., None]  # NHWC
    if flat:
        images = images.reshape(batch_size, -1)
    return {"image": images, "label": labels.astype(np.int32)}
