"""Pipeline-parallel forward/loss for the transformer LM.

Glue between :mod:`..parallel.pipeline` (the generic GPipe schedule) and
``TransformerLM``: the scanned block stack's leading layer axis becomes the
pipeline's stage axis — each ``pipe`` device holds ``n_layers / n_stages``
layers — while the (cheap) embedding, final norm, and lm_head replicate and
run outside the pipelined region.  One ``jax.grad`` of
:func:`pipeline_lm_loss` trains the pipeline; the transpose of the
scan + ppermute schedule is the backward pipeline.

Requires ``config.scan_layers=True`` (the stacked-parameter layout IS the
stage partition) and a per-microbatch-shape-preserving block, which the
transformer's blocks are.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ..ops.attention import flash_attention, mha_reference, on_tpu
from ..parallel.pipeline import pipeline_stages, pipelined
from ..parallel.sharding import unbox
from .train import cross_entropy_loss
from .transformer import TransformerLM, _rotary


def _rmsnorm(scale: jax.Array, x: jax.Array, dtype) -> jax.Array:
    x32 = x.astype(jnp.float32)
    norm = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + 1e-6)
    return (norm * scale).astype(dtype)


def _block_forward(cfg, p: Any, x: jax.Array) -> jax.Array:
    """One transformer block on RAW (unboxed) params.

    Functional mirror of ``transformer.Block`` — flax module machinery
    (param boxing, logical constraints) misfires inside shard_map's manual
    mesh, so the pipelined region computes with plain einsums.  Numerical
    equality with ``Block.apply`` is pinned by the pipeline LM tests.
    """
    dt = cfg.dtype
    att = p["attention"]

    h = _rmsnorm(p["ln_attn"]["scale"], x, dt)
    q = jnp.einsum("bsd,dhk->bshk", h, att["q_proj"]["kernel"].astype(dt))
    k = jnp.einsum("bsd,dhk->bshk", h, att["k_proj"]["kernel"].astype(dt))
    v = jnp.einsum("bsd,dhk->bshk", h, att["v_proj"]["kernel"].astype(dt))
    q = _rotary(q, base=cfg.rope_base)
    k = _rotary(k, base=cfg.rope_base)
    qh, kh, vh = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
    impl = cfg.attention
    if impl == "auto":
        impl = "flash" if on_tpu() else "reference"
    if impl == "flash":
        out = flash_attention(
            qh, kh, vh, causal=True, window=cfg.sliding_window,
            sinks=cfg.attention_sinks,
        )
    else:
        out = mha_reference(
            qh, kh, vh, causal=True, window=cfg.sliding_window,
            sinks=cfg.attention_sinks,
        )
    out = out.transpose(0, 2, 1, 3)
    attn = jnp.einsum("bshk,hkd->bsd", out, att["out_proj"]["kernel"].astype(dt))
    x = x + attn

    h = _rmsnorm(p["ln_mlp"]["scale"], x, dt)
    h = jnp.einsum("bsd,df->bsf", h, p["mlp"]["wi"]["kernel"].astype(dt))
    h = jax.nn.gelu(h)
    h = jnp.einsum("bsf,fd->bsd", h, p["mlp"]["wo"]["kernel"].astype(dt))
    return x + h


def pipeline_lm_forward(
    model: TransformerLM,
    params: Any,
    tokens: jax.Array,
    mesh: Mesh,
    n_micro: int,
    axis_name: str = "pipe",
) -> jax.Array:
    """Logits for (B, S) tokens with the block stack pipelined over
    ``mesh``'s ``pipe`` axis, ``n_micro`` microbatches deep.

    ``params`` is the ordinary (possibly flax-``Partitioned``-boxed)
    ``model.init(...)['params']`` tree; batch must divide ``n_micro``.
    """
    cfg = model.config
    if not cfg.scan_layers:
        raise ValueError("pipeline parallelism needs config.scan_layers=True")
    n_stages = mesh.shape[axis_name]
    raw = unbox(params)
    batch, seq_len = tokens.shape
    if batch % n_micro:
        raise ValueError(f"batch {batch} not divisible by n_micro {n_micro}")

    # Embedding — replicated, outside the pipelined region.
    x = jnp.asarray(raw["embedding"], cfg.dtype)[tokens]

    micro = x.reshape(n_micro, batch // n_micro, seq_len, cfg.d_model)
    stacked = pipeline_stages(raw["layers"], n_stages)

    block = _block_forward
    if cfg.remat:
        # Honour the config's rematerialisation on the pipelined path too:
        # recompute block internals in backward instead of storing every
        # per-tick activation (the scan over ticks multiplies what would
        # otherwise be stored).
        block = jax.checkpoint(_block_forward, static_argnums=(0,))

    def stage_fn(stage_layers, h):
        def body(h, layer_params):
            return block(cfg, layer_params, h), None

        h, _ = jax.lax.scan(body, h, stage_layers)
        return h

    out = pipelined(stage_fn, mesh, axis_name=axis_name)(stacked, micro)
    x = out.reshape(batch, seq_len, cfg.d_model)

    # Final norm + head — replicated, outside the pipeline.
    x = _rmsnorm(raw["ln_final"]["scale"], x, cfg.dtype)
    logits = jnp.einsum(
        "bsd,dv->bsv",
        x.astype(cfg.logits_dtype),
        jnp.asarray(raw["lm_head"]["kernel"], cfg.logits_dtype),
    )
    return logits


def pipeline_lm_loss(
    model: TransformerLM,
    params: Any,
    batch: dict,
    mesh: Mesh,
    n_micro: int,
    axis_name: str = "pipe",
) -> jax.Array:
    """Next-token loss over ``{"tokens": (B, S)}``, pipelined.

    Differentiable: ``jax.value_and_grad`` of this (w.r.t. ``params``) is a
    pipeline-parallel train step's core.
    """
    tokens = batch["tokens"]
    logits = pipeline_lm_forward(
        model, params, tokens[:, :-1], mesh, n_micro, axis_name
    )
    return cross_entropy_loss(logits, tokens[:, 1:])
