"""Mixture-of-experts MLP: Switch-style top-1 routing, einsum dispatch.

The TPU-native MoE formulation (Mesh-TensorFlow lineage): routing becomes
dense one-hot dispatch/combine einsums over a capacity-bounded buffer —
no gathers, no dynamic shapes, so XLA tiles everything onto the MXU and,
with the ``expert`` logical axis mapped to a mesh axis, inserts the
expert-parallel all-to-alls automatically from the shardings (the
scaling-book recipe; nothing here hand-writes a collective).

Semantics (Switch Transformer):
  * top-1 routing with softmax gate scaling;
  * per-call capacity ``C = ceil(capacity_factor * N / E)`` over the
    flattened token set; tokens over capacity are *dropped* — they
    contribute zero from the expert layer and ride the residual;
  * the standard load-balance auxiliary loss is sown into the
    ``"intermediates"`` collection (``moe_aux``) for the loss function to
    collect (:func:`lm_loss_with_moe_aux`).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


class MoEMlp(nn.Module):
    """Drop-in MLP replacement: route each token to one of ``n_experts``."""

    config: object  # TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        n_experts = cfg.moe_experts
        batch, seq_len, d_model = x.shape
        n_tokens = batch * seq_len
        capacity = int(
            -(-cfg.moe_capacity_factor * n_tokens // n_experts)  # ceil
        )
        capacity = max(1, min(capacity, n_tokens))

        router = nn.DenseGeneral(
            features=n_experts,
            use_bias=False,
            dtype=jnp.float32,  # routing decisions in f32, always
            param_dtype=cfg.param_dtype,
            kernel_init=nn.with_partitioning(
                nn.initializers.normal(0.02), ("embed", None)
            ),
            name="router",
        )
        tokens = x.reshape(n_tokens, d_model)
        gates = jax.nn.softmax(router(tokens.astype(jnp.float32)), axis=-1)
        expert_index = jnp.argmax(gates, axis=-1)                 # (N,)
        expert_gate = jnp.max(gates, axis=-1)                     # (N,)
        expert_onehot = jax.nn.one_hot(expert_index, n_experts)   # (N, E)

        # Load-balance aux (Switch eq. 4): E * sum_e f_e * P_e, minimised
        # at uniform routing where it equals 1.
        fraction = expert_onehot.mean(axis=0)
        prob_mass = gates.mean(axis=0)
        self.sow(
            "intermediates", "moe_aux",
            n_experts * jnp.sum(fraction * prob_mass),
        )

        # Position of each token within its expert's capacity buffer; the
        # cumsum is over the flat token order (deterministic priority).
        position = jnp.cumsum(expert_onehot, axis=0) * expert_onehot - 1.0
        kept = (position >= 0) & (position < capacity)
        position = jnp.clip(position, 0, capacity - 1).astype(jnp.int32)
        # Dispatch tensor (N, E, C): one-hot in both expert and slot.
        dispatch = (
            expert_onehot[:, :, None]
            * jax.nn.one_hot(position, capacity)
            * kept[:, :, None]
        ).astype(cfg.dtype)

        expert_in = jnp.einsum(
            "nec,nd->ecd", dispatch, tokens.astype(cfg.dtype)
        )
        wi = self.param(
            "wi",
            nn.with_partitioning(
                nn.initializers.normal(0.02), ("expert", "embed", "expert_mlp")
            ),
            (n_experts, d_model, cfg.d_ff),
            cfg.param_dtype,
        )
        wo = self.param(
            "wo",
            nn.with_partitioning(
                nn.initializers.normal(0.02 / (2 * cfg.n_layers) ** 0.5),
                ("expert", "expert_mlp", "embed"),
            ),
            (n_experts, cfg.d_ff, d_model),
            cfg.param_dtype,
        )
        h = jnp.einsum("ecd,edf->ecf", expert_in, wi.astype(cfg.dtype))
        h = nn.gelu(h)
        expert_out = jnp.einsum("ecf,efd->ecd", h, wo.astype(cfg.dtype))

        # Combine: gate-scaled return trip; dropped tokens get zero (their
        # dispatch row is all-zero) and survive through the residual.
        combine = dispatch * expert_gate[:, None, None].astype(cfg.dtype)
        out = jnp.einsum("nec,ecd->nd", combine, expert_out)
        out = out.reshape(batch, seq_len, d_model)
        return nn.with_logical_constraint(out, ("batch", "seq", "embed"))


def collect_moe_aux(intermediates) -> jax.Array:
    """Sum every sown ``moe_aux`` scalar in an intermediates collection.

    Filters by key so unrelated sown diagnostics can never leak into the
    training loss.
    """
    total = jnp.zeros((), jnp.float32)
    flat = jax.tree_util.tree_flatten_with_path(intermediates)[0]
    for path, leaf in flat:
        if any(getattr(entry, "key", None) == "moe_aux" for entry in path):
            total = total + jnp.sum(leaf)
    return total


def lm_loss_with_moe_aux(params, apply_fn, batch, aux_weight: float = 0.01):
    """Next-token loss + weighted MoE load-balance loss.

    Use in place of :func:`..train.lm_loss` for MoE configs; works with
    ``make_train_step`` unchanged.
    """
    from .train import cross_entropy_loss

    tokens = batch["tokens"]
    logits, variables = apply_fn(
        {"params": params}, tokens[:, :-1], mutable=["intermediates"]
    )
    loss = cross_entropy_loss(logits, tokens[:, 1:])
    aux = collect_moe_aux(variables.get("intermediates", {}))
    return loss + aux_weight * aux
