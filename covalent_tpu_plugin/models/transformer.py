"""Decoder-only transformer LM, mesh-first (BASELINE config 5: 125M pretrain).

Every parameter is annotated with *logical* axis names via
``nn.with_partitioning``; the rules in
:mod:`covalent_tpu_plugin.parallel.sharding` map them onto the physical
mesh (heads/mlp/vocab -> ``tensor``, embed -> ``fsdp``, activations ->
``batch``/``seq``), so the one module definition runs data-parallel on a
single host or tensor+sequence-parallel across a pod with no code changes —
XLA inserts the collectives.

TPU-minded choices: bfloat16 activations (MXU-native), dimensions multiples
of 128 (MXU tiling), RMSNorm + rotary embeddings (no learned position
table), layers rolled up with ``nn.scan`` (one compiled block, weights
stacked on a ``layers`` axis) and optionally rematerialised
(``jax.checkpoint``) to trade FLOPs for HBM.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops.attention import (
    NEG_INF,
    flash_attention,
    flash_attention_sharded,
    mha_reference,
    on_tpu,
)
from ..ops.ring_attention import sequence_parallel_attention
from .moe import MoEMlp
from .quant import dense_general


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32768
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    #: kv heads for grouped-query attention; None = n_heads (plain MHA).
    n_kv_heads: int | None = None
    d_ff: int = 3072
    max_seq: int = 1024
    dtype: Any = jnp.bfloat16        # activations
    param_dtype: Any = jnp.float32   # master weights
    #: lm_head matmul dtype.  f32 is the conservative default; bf16 runs the
    #: head on the MXU's fast path (the loss re-casts to f32 for softmax).
    logits_dtype: Any = jnp.float32
    attention: str = "auto"      # auto | flash | reference | ring | ulysses
    #: incremental decoding: layers keep a (max_seq) K/V cache in the flax
    #: "cache" collection and consume one token slice per apply.
    decode: bool = False
    #: mixture-of-experts: > 0 replaces every block's MLP with a Switch-
    #: style top-1 MoE of that many experts (models/moe.py); the "expert"
    #: logical axis shards them over the tensor mesh axis.
    moe_experts: int = 0
    moe_capacity_factor: float = 1.25
    remat: bool = False
    #: "full" recomputes everything in backward; "dots" saves matmul outputs
    #: (jax dots_with_no_batch_dims_saveable) — ~half the recompute FLOPs for
    #: a modest activation-memory increase.
    remat_policy: str = "full"
    #: lax.scan over the block stack keeps compile time O(1) in depth, but
    #: blocks XLA from fusing/scheduling across block boundaries — unrolled
    #: (False) measured ~33% faster on the v5e train step at 12 layers
    #: (benchmarks/LM_STEP_SWEEP.md).  Scan stays the default for
    #: compile-latency-sensitive paths; flip it off for long runs.
    scan_layers: bool = True
    #: device mesh: required for attention="ring"; with attention="flash"
    #: it switches the kernel to the shard_map (collective-free) path.
    mesh: Any = None
    #: weight-only int8 serving: every dense layer stores an int8 kernel +
    #: per-channel scale (models/quant.py).  Build via quantize_lm(), not
    #: by hand — the param tree shape changes.
    quantized: bool = False
    #: sliding-window (Mistral-style local) attention: each query sees
    #: only the `sliding_window` most recent positions.  Flash grids visit
    #: only the band's tiles (compute AND DMA O(S·w)); with
    #: attention="ring" the banded ring truncates to the hops the band
    #: reaches (ops/ring_attention.py).
    sliding_window: int | None = None
    #: StreamingLLM-style circular KV cache for decode: cache length is
    #: `sliding_window + attention_sinks` instead of `max_seq` and
    #: generation can run past max_seq at O(window) memory.  Requires
    #: sliding_window; exact for the generate() flow at ANY chunking —
    #: multi-token slabs attend the pre-write ring snapshot plus the slab
    #: itself, so a wrapping write cannot erase entries earlier slab rows
    #: still need (slabs stay <= sliding_window so the scatter never
    #: lands two slab tokens in one slot).
    rolling_cache: bool = False
    #: attention sinks (StreamingLLM): the first `attention_sinks`
    #: positions stay visible to every query alongside the sliding band,
    #: and the rolling cache pins their slots (never overwritten).  Known
    #: to stabilise long windowed decode where window-only attention
    #: drifts once position 0 rolls out of the band.  Requires
    #: sliding_window; for sequence parallelism use attention="ulysses"
    #: (the rotating ring cannot keep shard 0's sinks resident).
    attention_sinks: int = 0
    #: rotary embedding wavelength base (theta).  10k is the GPT-NeoX/
    #: llama default; raising it (e.g. 500k, llama-3 style) stretches the
    #: position resolution for long-context training — the standard knob
    #: behind context extension.
    rope_base: float = 10000.0
    #: int8 KV cache for decode: cached K/V store as int8 with one f32
    #: scale per (batch, position, kv head), halving the per-step cache
    #: reads and the cache's HBM footprint vs bf16 (4x vs f32).  Decode
    #: is cache-bandwidth-bound at long contexts, so this is the standard
    #: serving lever; quantization error is ~1e-2 relative (not exact —
    #: tests pin logit cosine > 0.999).  Orthogonal to `quantized`
    #: (weight int8): compose both for fully-int8 serving reads.
    quantized_kv_cache: bool = False
    #: LoRA fine-tuning (models/lora.py): > 0 attaches rank-r adapters to
    #: the targeted denses.  Build via add_lora()/quantize_then_lora().
    lora_rank: int = 0
    lora_alpha: float = 16.0
    #: which dense layers get adapters (attention + MLP, not the lm_head).
    lora_targets: tuple = (
        "q_proj", "k_proj", "v_proj", "out_proj", "wi", "wo",
    )

    def __post_init__(self):
        if self.sliding_window is not None and self.sliding_window < 1:
            # Validated here (not only in the kernels) because the cached
            # decode path masks the band itself — a 0/negative window there
            # would silently attend nothing and softmax over garbage.
            raise ValueError(
                f"sliding_window must be >= 1, got {self.sliding_window}"
            )
        if self.rolling_cache and self.sliding_window is None:
            raise ValueError("rolling_cache requires sliding_window")
        if self.attention_sinks:
            if self.attention_sinks < 0:
                raise ValueError(
                    f"attention_sinks must be >= 0, got {self.attention_sinks}"
                )
            if self.sliding_window is None:
                raise ValueError("attention_sinks require sliding_window")

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def lm_125m_config(**overrides) -> TransformerConfig:
    """GPT-2-small-class preset (~125M params with a 32k vocab)."""
    return TransformerConfig(**overrides)


def _rotary(x: jax.Array, base: float = 10000.0, offset=0) -> jax.Array:
    """Rotary position embedding over (B, S, H, D) with D even.

    ``offset`` shifts the position index — incremental decoding applies the
    embedding for absolute position ``offset + t`` to a length-1 slice.
    """
    _, seq_len, _, head_dim = x.shape
    half = head_dim // 2
    freqs = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    positions = offset + jnp.arange(seq_len, dtype=jnp.float32)
    angles = positions[:, None] * freqs[None, :]
    cos = jnp.cos(angles)[None, :, None, :].astype(x.dtype)
    sin = jnp.sin(angles)[None, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)


class RMSNorm(nn.Module):
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        scale = self.param(
            "scale",
            nn.with_partitioning(nn.initializers.ones_init(), ("embed",)),
            (x.shape[-1],),
            jnp.float32,
        )
        x32 = x.astype(jnp.float32)
        norm = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + 1e-6)
        return (norm * scale).astype(self.dtype)


class Attention(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dense = lambda name, features, axes: dense_general(  # noqa: E731
            cfg.quantized,
            features=features,
            axis=-1,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.initializers.normal(0.02),
            kernel_axes=axes,
            name=name,
            lora_rank=cfg.lora_rank if name in cfg.lora_targets else 0,
            lora_alpha=cfg.lora_alpha,
        )
        kv_heads = cfg.n_kv_heads or cfg.n_heads
        if cfg.n_heads % kv_heads:
            raise ValueError(
                f"n_heads {cfg.n_heads} must be divisible by n_kv_heads {kv_heads}"
            )
        # GQA kv projections take the "kv_heads" logical axis (replicated
        # across tensor shards by DEFAULT_RULES) — the small kv head count
        # generally doesn't divide the tensor axis the way "heads" must.
        kv_axis = "heads" if kv_heads == cfg.n_heads else "kv_heads"
        q = dense("q_proj", (cfg.n_heads, cfg.head_dim), ("embed", "heads", "kv"))(x)
        k = dense("k_proj", (kv_heads, cfg.head_dim), ("embed", kv_axis, "kv"))(x)
        v = dense("v_proj", (kv_heads, cfg.head_dim), ("embed", kv_axis, "kv"))(x)
        q = nn.with_logical_constraint(q, ("batch", "seq", "heads", "kv"))
        k = nn.with_logical_constraint(k, ("batch", "seq", kv_axis, "kv"))
        v = nn.with_logical_constraint(v, ("batch", "seq", kv_axis, "kv"))

        if cfg.decode:
            return self._decode_step(q, k, v, kv_heads)

        q = _rotary(q, base=cfg.rope_base)
        k = _rotary(k, base=cfg.rope_base)

        # (B, S, H, D) -> (B, H, S, D) for the attention kernels
        qh, kh, vh = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
        impl = cfg.attention
        if impl == "auto":
            impl = "flash" if on_tpu() else "reference"
        if impl in ("ring", "ulysses"):
            if cfg.mesh is None:
                raise ValueError(f"attention={impl!r} requires config.mesh")
            if cfg.attention_sinks and impl == "ring":
                # Sink columns live on shard 0 only; every hop would need
                # them resident (a broadcast, not a rotation).  Use
                # attention='ulysses' — its full-sequence local attention
                # composes with sinks unchanged.
                raise ValueError(
                    "attention_sinks are unsupported with attention='ring'"
                    " — use attention='ulysses'"
                )
            if impl == "ring" and kv_heads != cfg.n_heads:
                # Ring shards over sequence, not heads: materialising the
                # group repeat is cheap relative to the ring's kv transfers.
                # (Ulysses repeats internally only when needed.)
                group = cfg.n_heads // kv_heads
                kh = jnp.repeat(kh, group, axis=1)
                vh = jnp.repeat(vh, group, axis=1)
            # sliding_window composes: the banded ring masks each hop by
            # global positions and (contiguous layout) truncates the ring
            # to the hops intersecting the band; ulysses swaps
            # sequence<->heads and runs the banded full-sequence kernel
            # locally (ops/ring_attention.py).
            out = sequence_parallel_attention(
                qh, kh, vh, cfg.mesh, causal=True,
                window=cfg.sliding_window, sinks=cfg.attention_sinks,
                impl="ulysses" if impl == "ulysses" else None,
            )
        elif impl == "flash":
            if cfg.mesh is not None:
                # Bare pallas_call is opaque to sharding propagation — under
                # a sharded jit it would all-gather Q/K/V to every device;
                # the shard_map wrapper keeps each (batch, head) block local.
                out = flash_attention_sharded(
                    qh, kh, vh, cfg.mesh, causal=True,
                    window=cfg.sliding_window, sinks=cfg.attention_sinks,
                )
            else:
                out = flash_attention(
                    qh, kh, vh, causal=True, window=cfg.sliding_window,
                    sinks=cfg.attention_sinks,
                )
        else:
            out = mha_reference(
                qh, kh, vh, causal=True, window=cfg.sliding_window,
                sinks=cfg.attention_sinks,
            )
        out = out.transpose(0, 2, 1, 3)

        out = self._out_proj(out)
        return nn.with_logical_constraint(out, ("batch", "seq", "embed"))

    def _out_proj(self, out):
        cfg = self.config
        return dense_general(
            cfg.quantized,
            features=cfg.d_model,
            axis=(-2, -1),
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            # residual-output kernel: depth-scaled init (GPT-2 convention,
            # matching MlpBlock's wo) keeps residual-stream variance flat
            kernel_init=nn.initializers.normal(0.02 / (2 * cfg.n_layers) ** 0.5),
            kernel_axes=("heads", "kv", "embed"),
            name="out_proj",
            lora_rank=cfg.lora_rank if "out_proj" in cfg.lora_targets else 0,
            lora_alpha=cfg.lora_alpha,
        )(out)

    def _decode_step(self, q, k, v, kv_heads: int):
        """Incremental attention against the layer's K/V cache.

        A multi-token call is a *prefill*: the whole slab's K/V land in the
        cache at the cursor, then the slab attends the cache with per-row
        causal visibility — correct at cursor 0 (classic prefill) and at a
        non-zero cursor (chunked prefill keeps its cached context).  A
        single-token call is a decode step.  The cache lives in the flax
        "cache" collection (zero-initialised via ``decode=True`` init);
        decode is bandwidth-bound, so the attention is a plain einsum — no
        flash.

        Contract for direct cache users: the cursor plus the slab must not
        exceed ``max_seq`` — the cursor is traced, so an overflow cannot be
        detected here; ``generate()`` enforces it for the packaged path
        (``dynamic_update_slice`` would clamp and silently corrupt slots).
        """
        cfg = self.config
        batch, slab = q.shape[:2]
        rolling = cfg.rolling_cache
        sinks = cfg.attention_sinks
        # Rolling ring = pinned sink slots [0, sinks) + circular band
        # region [sinks, sinks + window).
        cache_len = (
            cfg.sliding_window + sinks if rolling else cfg.max_seq
        )
        if slab > cache_len:
            raise ValueError(
                f"slab of {slab} tokens exceeds the cache length {cache_len}"
            )
        quant_kv = cfg.quantized_kv_cache
        kv_dtype = jnp.int8 if quant_kv else cfg.dtype
        cached_k = self.variable(
            "cache", "cached_k", jnp.zeros,
            (batch, cache_len, kv_heads, cfg.head_dim), kv_dtype,
        )
        cached_v = self.variable(
            "cache", "cached_v", jnp.zeros,
            (batch, cache_len, kv_heads, cfg.head_dim), kv_dtype,
        )
        if quant_kv:
            # One f32 scale per (batch, slot, kv head): zero-init means
            # never-written slots dequantise to exact zeros, same as the
            # unquantised cache (and they are masked anyway).
            k_scale = self.variable(
                "cache", "k_scale", jnp.zeros,
                (batch, cache_len, kv_heads, 1), jnp.float32,
            )
            v_scale = self.variable(
                "cache", "v_scale", jnp.zeros,
                (batch, cache_len, kv_heads, 1), jnp.float32,
            )
        cursor = self.variable(
            "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
        )
        if rolling:
            # Which absolute position each circular slot currently holds;
            # -1 = never written.  Makes the band mask exact across wraps
            # with no modular-arithmetic reconstruction.
            slot_pos = self.variable(
                "cache", "slot_positions",
                lambda: jnp.full((cache_len,), -1, jnp.int32),
            )
        if self.is_initializing():
            # init only materialises the zeroed cache; no attention math.
            return self._out_proj(jnp.zeros_like(q))

        pos = cursor.value
        q = _rotary(q, base=cfg.rope_base, offset=pos)
        k = _rotary(k, base=cfg.rope_base, offset=pos)
        q_positions = pos + jnp.arange(slab)

        def quantize(x):
            """Symmetric per-(b, s, h) int8: scale = amax/127 over D."""
            amax = jnp.max(
                jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True
            )
            scale = jnp.maximum(amax, 1e-8) / 127.0
            qx = jnp.clip(
                jnp.round(x.astype(jnp.float32) / scale), -127, 127
            ).astype(jnp.int8)
            return qx, scale

        if quant_kv:
            k_store, k_s = quantize(k)
            v_store, v_s = quantize(v)
        else:
            k_store, v_store = k.astype(cfg.dtype), v.astype(cfg.dtype)
        # Rolling multi-token slabs attend the PRE-write cache plus the
        # slab itself (concatenated): the scatter below may overwrite ring
        # slots that earlier slab rows still need (slot p+j-W dies when
        # slab token j lands), so post-write attention would silently drop
        # band-edge entries for every row but the last — the r3
        # "documented-lossy" case that forced prefill_chunk=1.  With the
        # pre-write snapshot every chunk <= sliding_window is EXACT: in-
        # slab context comes from the slab branch, pre-slab context from
        # slots the scatter has not yet touched (row i's oldest band need
        # is p+i-W+1 > p-W-1+L-W ... all alive pre-write).
        pre_k, pre_v = cached_k.value, cached_v.value
        if quant_kv:
            pre_ks, pre_vs = k_scale.value, v_scale.value
        if rolling:
            pre_sp = slot_pos.value
        if rolling:
            # Circular write: token at absolute position p lands in slot
            # p (pinned) while p < sinks, else sinks + (p - sinks) % W —
            # sink tokens are never overwritten by the rolling band (a
            # scatter — dynamic_update_slice can't wrap).
            if sinks:
                idx = jnp.where(
                    q_positions < sinks,
                    q_positions,
                    sinks + (q_positions - sinks) % cfg.sliding_window,
                )
            else:
                idx = q_positions % cache_len
            cached_k.value = cached_k.value.at[:, idx].set(k_store)
            cached_v.value = cached_v.value.at[:, idx].set(v_store)
            if quant_kv:
                k_scale.value = k_scale.value.at[:, idx].set(k_s)
                v_scale.value = v_scale.value.at[:, idx].set(v_s)
            slot_pos.value = slot_pos.value.at[idx].set(q_positions)
        else:
            cached_k.value = jax.lax.dynamic_update_slice(
                cached_k.value, k_store, (0, pos, 0, 0)
            )
            cached_v.value = jax.lax.dynamic_update_slice(
                cached_v.value, v_store, (0, pos, 0, 0)
            )
            if quant_kv:
                k_scale.value = jax.lax.dynamic_update_slice(
                    k_scale.value, k_s, (0, pos, 0, 0)
                )
                v_scale.value = jax.lax.dynamic_update_slice(
                    v_scale.value, v_s, (0, pos, 0, 0)
                )
        cursor.value = pos + slab

        # One path for prefill slabs AND single-token steps: the slab's
        # queries attend the attend-set with per-row causal visibility
        # (query at absolute position pos+i sees columns <= pos+i), so
        # chunked prefill at a non-zero cursor keeps its cached context.
        # The attend-set is the post-write cache except for rolling
        # multi-token slabs, which use the pre-write snapshot + the slab
        # itself (the exact-chunked-prefill path; see the snapshot note).
        # Column-position vector: the mask reads each column's recorded
        # absolute position (-1 = never written), which is exact across
        # ring wraps with no modular reconstruction; non-rolling slots ARE
        # their positions.
        if rolling and slab > 1:
            attend_k = jnp.concatenate([pre_k, k_store], axis=1)
            attend_v = jnp.concatenate([pre_v, v_store], axis=1)
            if quant_kv:
                attend_ks = jnp.concatenate([pre_ks, k_s], axis=1)
                attend_vs = jnp.concatenate([pre_vs, v_s], axis=1)
            col_pos = jnp.concatenate([pre_sp, q_positions])
        else:
            attend_k, attend_v = cached_k.value, cached_v.value
            if quant_kv:
                attend_ks, attend_vs = k_scale.value, v_scale.value
            col_pos = (
                slot_pos.value if rolling else jnp.arange(cache_len)
            )
        group = cfg.n_heads // kv_heads
        qg = q.reshape(batch, slab, kv_heads, group, cfg.head_dim)
        scores = jnp.einsum(
            "bqhgd,bshd->bhgqs", qg, attend_k.astype(cfg.dtype),
            preferred_element_type=jnp.float32,
        ) * (cfg.head_dim**-0.5)
        if quant_kv:
            # The scale is constant over D, so it factors out of the dot:
            # apply per-(b, s, h) AFTER the matmul — HBM reads stay int8.
            scores = scores * jnp.transpose(
                attend_ks[..., 0], (0, 2, 1)
            )[:, :, None, None, :]
        # Band mask by column position: a query sees a column iff it is
        # written, causal-past, and in the band — sink positions stay
        # visible at any distance (their slots are pinned in the rolling
        # ring, so they are always present to see).
        sp = col_pos[None, :]
        visible = (sp >= 0) & (sp <= q_positions[:, None])
        if cfg.sliding_window is not None:
            in_band = sp > q_positions[:, None] - cfg.sliding_window
            if sinks:
                in_band |= sp < sinks
            visible &= in_band
        scores = jnp.where(visible[None, None, None, :, :], scores, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        if quant_kv:
            # Fold the V scale into the probabilities (constant over D).
            probs = probs * jnp.transpose(
                attend_vs[..., 0], (0, 2, 1)
            )[:, :, None, None, :]
        probs = probs.astype(cfg.dtype)
        out = jnp.einsum(
            "bhgqs,bshd->bqhgd", probs, attend_v.astype(cfg.dtype),
            preferred_element_type=jnp.float32,
        )
        out = out.reshape(batch, slab, cfg.n_heads, cfg.head_dim)
        return self._out_proj(out.astype(cfg.dtype))


class MlpBlock(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        h = dense_general(
            cfg.quantized,
            features=cfg.d_ff,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.initializers.normal(0.02),
            kernel_axes=("embed", "mlp"),
            name="wi",
            lora_rank=cfg.lora_rank if "wi" in cfg.lora_targets else 0,
            lora_alpha=cfg.lora_alpha,
        )(x)
        h = nn.with_logical_constraint(h, ("batch", "seq", "mlp"))
        h = nn.gelu(h)
        h = dense_general(
            cfg.quantized,
            features=cfg.d_model,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.initializers.normal(0.02 / (2 * cfg.n_layers) ** 0.5),
            kernel_axes=("mlp", "embed"),
            name="wo",
            lora_rank=cfg.lora_rank if "wo" in cfg.lora_targets else 0,
            lora_alpha=cfg.lora_alpha,
        )(h)
        return nn.with_logical_constraint(h, ("batch", "seq", "embed"))


class Block(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x):
        x = x + Attention(self.config, name="attention")(
            RMSNorm(self.config.dtype, name="ln_attn")(x)
        )
        if self.config.moe_experts > 0:
            mlp = MoEMlp(self.config, name="moe")
        else:
            mlp = MlpBlock(self.config, name="mlp")
        x = x + mlp(RMSNorm(self.config.dtype, name="ln_mlp")(x))
        return nn.with_logical_constraint(x, ("batch", "seq", "embed"))


class TransformerLM(nn.Module):
    """Causal LM: tokens (B, S) -> logits (B, S, vocab)."""

    config: TransformerConfig

    @nn.compact
    def __call__(self, tokens, return_features: bool = False):
        cfg = self.config
        if tokens.shape[-1] > cfg.max_seq:
            raise ValueError(
                f"sequence length {tokens.shape[-1]} exceeds config.max_seq "
                f"{cfg.max_seq}"
            )
        embedding = self.param(
            "embedding",
            nn.with_partitioning(nn.initializers.normal(0.02), ("vocab", "embed")),
            (cfg.vocab_size, cfg.d_model),
            cfg.param_dtype,
        )
        x = jnp.asarray(embedding, cfg.dtype)[tokens]
        x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))

        block_cls = Block
        if cfg.remat:
            policy = None
            if cfg.remat_policy == "dots":
                policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
            elif cfg.remat_policy != "full":
                raise ValueError(
                    f"remat_policy must be 'full' or 'dots', got {cfg.remat_policy!r}"
                )
            block_cls = nn.remat(Block, prevent_cse=False, policy=policy)
        if cfg.scan_layers:
            x, _ = nn.scan(
                lambda module, carry, _: (module(carry), None),
                # "intermediates" must be declared or scan silently drops
                # sown values (the MoE load-balance aux loss rides there).
                variable_axes={"params": 0, "cache": 0, "intermediates": 0},
                split_rngs={"params": True},
                length=cfg.n_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(block_cls(cfg, name="layers"), x, None)
        else:
            for i in range(cfg.n_layers):
                x = block_cls(cfg, name=f"layer_{i}")(x)

        x = RMSNorm(cfg.dtype, name="ln_final")(x)
        if return_features:
            # The fused-xent training path (ops/xent.py) consumes the
            # final features and the lm_head kernel directly, so the
            # (B, S, vocab) logits tensor is never materialised.  Safe to
            # skip the head here: apply() with unused params is fine, and
            # init() always runs the full path (return_features defaults
            # False) so the lm_head params always exist.
            return x
        logits = dense_general(
            cfg.quantized,
            features=cfg.vocab_size,
            dtype=cfg.logits_dtype,  # f32 default; bf16 for the MXU fast path
            param_dtype=cfg.param_dtype,
            kernel_init=nn.initializers.normal(0.02),
            kernel_axes=("embed", "vocab"),
            name="lm_head",
            lora_rank=cfg.lora_rank if "lm_head" in cfg.lora_targets else 0,
            lora_alpha=cfg.lora_alpha,
        )(x)
        return nn.with_logical_constraint(logits, ("batch", "seq", "vocab"))

    def parameter_count(self, params) -> int:
        return sum(
            leaf.size for leaf in jax.tree_util.tree_leaves(params)
        )
