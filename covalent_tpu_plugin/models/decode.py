"""Autoregressive generation with a per-layer KV cache.

The training-side ``TransformerLM`` recomputes attention over the full
prefix; generation instead runs the model in ``decode=True`` mode: one
batched *prefill* pass pushes the whole prompt's K/V into each layer's
cache (flax "cache" collection), then each decode step appends a single
token at the cache cursor and attends the cached prefix — a step costs
O(S·D) attention reads instead of O(S²·D) recompute, and time-to-first-
token is one forward pass, not P sequential steps.

The decode loop is a ``lax.while_loop`` writing into a fixed (B, P+N)
token buffer — fully jittable, one compilation for any prompt content of
a given shape, with an early exit once every row has emitted EOS (when
``eos_token_id`` is set; otherwise it runs the full ``max_new_tokens``).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from ._jitcache import cached_jit
from .transformer import TransformerLM


def _decode_model(model: TransformerLM) -> TransformerLM:
    if model.config.decode:
        return model
    return TransformerLM(dataclasses.replace(model.config, decode=True))


def init_cache(model: TransformerLM, batch_size: int) -> Any:
    """Zeroed per-layer KV cache sized ``config.max_seq``.

    Shapes come from ``jax.eval_shape`` over the decoder's init — no
    parameters are ever materialised (a bare init would sample the full
    weight set just to throw it away).
    """
    decoder = _decode_model(model)
    abstract = jax.eval_shape(
        lambda rng, tokens: decoder.init(rng, tokens),
        jax.random.PRNGKey(0),
        jnp.zeros((batch_size, 1), jnp.int32),
    )

    def materialise(path, leaf):
        if any(getattr(e, "key", None) == "slot_positions" for e in path):
            # The rolling cache's "never written" sentinel is -1; zeroing
            # it would make every empty ring slot claim absolute position
            # 0 and leak phantom zero-K/V entries into early softmaxes.
            return jnp.full(leaf.shape, -1, leaf.dtype)
        return jnp.zeros(leaf.shape, leaf.dtype)

    return jax.tree_util.tree_map_with_path(materialise, abstract["cache"])


def inference_params(params: Any) -> Any:
    """Cast f32 master weights to bf16 for serving.

    Decode steps are HBM-bandwidth-bound — every step re-reads the full
    weight set — so halving the bytes is a direct speedup: measured +10%
    tokens/s scanned and +48% with ``scan_layers=False`` on the v5e 125M
    decode (benchmarks/DECODE_SWEEP.md).  Non-f32 leaves (e.g. int
    embeddings) pass through untouched; training should keep the f32
    masters, this is a serving-side copy.
    """
    return jax.tree_util.tree_map(
        lambda p: p.astype(jnp.bfloat16) if p.dtype == jnp.float32 else p,
        params,
    )


def _filter_top_k(logits: jax.Array, top_k: int) -> jax.Array:
    """Mask all but the ``top_k`` largest logits per row to NEG_INF.

    ``jax.lax.top_k`` keeps the shape static, so the filter is jittable for
    any fixed ``top_k``.
    """
    from ..ops.attention import NEG_INF

    kth = jax.lax.top_k(logits, top_k)[0][..., -1:]  # (..., 1)
    return jnp.where(logits < kth, NEG_INF, logits)


def _filter_min_p(logits: jax.Array, min_p: float) -> jax.Array:
    """min-p filter: keep tokens whose probability is at least ``min_p``
    times the most likely token's — a relative floor that adapts to the
    distribution's confidence (tight on peaked steps, permissive on flat
    ones), unlike top-k/top-p's absolute budgets."""
    from ..ops.attention import NEG_INF

    logprobs = jax.nn.log_softmax(logits, axis=-1)
    floor = jnp.max(logprobs, axis=-1, keepdims=True) + jnp.log(min_p)
    return jnp.where(logprobs < floor, NEG_INF, logits)


def _apply_repetition_penalty(
    logits: jax.Array, seen: jax.Array, penalty: float
) -> jax.Array:
    """CTRL-style repetition penalty over the ``seen`` token multiset:
    logits of already-emitted tokens divide by ``penalty`` when positive
    and multiply when negative (the HF convention), making repeats
    uniformly less likely.  ``seen`` is (B, L) int32 with -1 padding for
    not-yet-written slots."""
    batch, vocab = logits.shape
    safe = jnp.where(seen >= 0, seen, vocab)  # -1 pads -> overflow column
    appeared = jnp.zeros((batch, vocab + 1), bool).at[
        jnp.arange(batch)[:, None], safe
    ].set(True)[:, :vocab]
    penalised = jnp.where(
        logits > 0, logits / penalty, logits * penalty
    )
    return jnp.where(appeared, penalised, logits)


def _filter_top_p(logits: jax.Array, top_p: float) -> jax.Array:
    """Nucleus filter: keep the smallest prefix of the sorted distribution
    whose cumulative probability reaches ``top_p``; mask the rest.

    Static-shape formulation: sort once, compute the cumulative softmax
    mass *before* each position, and mask tokens whose preceding mass
    already covers ``top_p`` (the first token always survives).

    Tie semantics: the filter thresholds by logit *value*, so every token
    tied with the cutoff logit survives and the kept nucleus can exceed
    ``top_p`` mass by the tied tokens' probability (HF masks by sorted
    index instead, arbitrarily breaking the tie by sort order).  Keeping
    all equal-probability tokens is the deliberate choice here: which of
    two identical-logit tokens "ranks" first is numerically meaningless.
    """
    from ..ops.attention import NEG_INF

    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    mass_before = jnp.cumsum(probs, axis=-1) - probs
    cutoff_idx = jnp.sum((mass_before < top_p).astype(jnp.int32), axis=-1)
    # Logit value at the last kept (sorted) position is the threshold.
    threshold = jnp.take_along_axis(
        sorted_logits, jnp.maximum(cutoff_idx - 1, 0)[..., None], axis=-1
    )
    return jnp.where(logits < threshold, NEG_INF, logits)


def _generate_traced(
    model: TransformerLM,
    params: Any,
    prompt: jax.Array,
    max_new_tokens: int,
    temperature: float = 0.0,
    rng: jax.Array | None = None,
    top_k: int | None = None,
    top_p: float | None = None,
    eos_token_id: int | None = None,
    pad_token_id: int | None = None,
    prefill_chunk: int | None = None,
    min_p: float | None = None,
    repetition_penalty: float | None = None,
) -> jax.Array:
    """Generate ``max_new_tokens`` continuations of ``prompt`` ((B, P) int32).

    ``temperature=0`` is greedy argmax; otherwise softmax sampling at the
    given temperature (requires ``rng``), optionally restricted to the
    ``top_k`` highest logits, the ``top_p`` nucleus, and/or the ``min_p``
    relative-probability floor (applied in that order, the
    HF/transformers convention).  ``repetition_penalty`` (CTRL-style,
    works for greedy AND sampling) divides positive / multiplies
    negative logits of every token already in the row's buffer before
    the other filters.  ``eos_token_id`` stops a row
    once it emits EOS: its remaining slots fill with ``pad_token_id``
    (default: the EOS id), and the loop exits early when every row has
    finished.  ``prefill_chunk`` streams the prompt into the caches in
    fixed-size slabs instead of one pass — the decode cache attends a
    chunk's queries against everything already cached, so the result is
    exact while prefill activation memory is bounded O(chunk·S) for long
    prompts.  With ``rolling_cache``, prompts past the ring capacity
    stream in chunks of at most ``sliding_window`` tokens (the default
    when unset) — exact at any such width, ~window× fewer prefill steps
    than the old forced token-by-token stream.  Returns the full
    (B, P+N) token buffer.  Wrap in
    ``jax.jit`` for repeated use — everything inside is a single compiled
    loop.
    """
    decoder = _decode_model(model)
    config = decoder.config
    batch, prompt_len = prompt.shape
    total = prompt_len + max(max_new_tokens, 0)
    if config.rolling_cache:
        # The circular cache frees generation from max_seq: prompts past
        # capacity stream in as chunks of at most ``sliding_window``
        # tokens.  Any such chunk is EXACT — the decode step attends the
        # pre-write ring snapshot plus the slab itself, so a wrapping
        # scatter can no longer erase band-edge entries earlier slab rows
        # need (the r3 lossy case that forced prefill_chunk=1 and made
        # long-prompt prefill O(P) sequential steps).  Wider-than-window
        # chunks would land two slab tokens in one ring slot (an
        # order-undefined scatter), so they stay rejected.
        capacity = config.sliding_window + config.attention_sinks
        if prompt_len > capacity:
            if prefill_chunk is None:
                prefill_chunk = config.sliding_window
            if prefill_chunk > config.sliding_window:
                raise ValueError(
                    f"rolling_cache prefill chunks of {prefill_chunk} "
                    f"exceed sliding_window ({config.sliding_window}): "
                    "two slab tokens would scatter into the same ring "
                    "slot; use prefill_chunk <= sliding_window"
                )
    elif total > config.max_seq:
        raise ValueError(
            f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) "
            f"exceeds config.max_seq ({config.max_seq})"
        )
    # Argument-shape validation fires even for max_new_tokens <= 0 (a bad
    # combination is a caller bug worth surfacing); the rng requirement
    # only applies when sampling will actually happen, preserving the
    # original "zero new tokens is identity" contract.
    if temperature <= 0 and (
        top_k is not None or top_p is not None or min_p is not None
    ):
        raise ValueError(
            "top_k/top_p/min_p require sampling (temperature > 0)"
        )
    if top_k is not None and not 1 <= top_k <= config.vocab_size:
        raise ValueError(f"top_k must be in [1, {config.vocab_size}], got {top_k}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if min_p is not None and not 0.0 < min_p <= 1.0:
        raise ValueError(f"min_p must be in (0, 1], got {min_p}")
    if repetition_penalty is not None and repetition_penalty <= 0:
        raise ValueError(
            f"repetition_penalty must be > 0, got {repetition_penalty}"
        )
    if pad_token_id is not None and eos_token_id is None:
        raise ValueError("pad_token_id requires eos_token_id")
    if prefill_chunk is not None and prefill_chunk < 1:
        raise ValueError(f"prefill_chunk must be >= 1, got {prefill_chunk}")
    if max_new_tokens <= 0:
        return prompt.astype(jnp.int32)
    if temperature > 0 and rng is None:
        raise ValueError("sampling (temperature > 0) requires rng")
    if rng is None:
        rng = jax.random.PRNGKey(0)

    cache = init_cache(model, batch)
    buffer = jnp.zeros((batch, total), jnp.int32)
    buffer = jax.lax.dynamic_update_slice(buffer, prompt, (0, 0))

    def choose(step_logits, rng, buffer, written):
        rng, sample_key = jax.random.split(rng)
        step_logits = step_logits.astype(jnp.float32)
        if repetition_penalty is not None:
            # Unwritten buffer slots hold token 0 — mask them to -1 so a
            # legitimate token id 0 is only penalised once it appears.
            cols = jnp.arange(buffer.shape[1])[None, :]
            seen = jnp.where(cols < written, buffer, -1)
            step_logits = _apply_repetition_penalty(
                step_logits, seen, repetition_penalty
            )
        if temperature > 0:
            scaled = step_logits / temperature
            if top_k is not None:
                scaled = _filter_top_k(scaled, top_k)
            if top_p is not None:
                scaled = _filter_top_p(scaled, top_p)
            if min_p is not None:
                scaled = _filter_min_p(scaled, min_p)
            chosen = jax.random.categorical(sample_key, scaled, axis=-1)
        else:
            chosen = jnp.argmax(step_logits, axis=-1)
        return chosen.astype(jnp.int32), rng

    pad = eos_token_id if pad_token_id is None else pad_token_id

    def finish(chosen, done):
        """Apply EOS bookkeeping to a step's chosen tokens."""
        if eos_token_id is None:
            return chosen, done
        chosen = jnp.where(done, jnp.int32(pad), chosen)
        return chosen, done | (chosen == eos_token_id)

    # Prefill: batched pass(es) push the whole prompt into the caches and
    # yield the first generated token from the prompt's last logits.
    # Chunked prefill is exact (each slab attends the cached prefix with
    # per-row causal visibility); the chunk count is static so this is a
    # plain Python loop of at most two compiled shapes.
    if prefill_chunk is None or prefill_chunk >= prompt_len:
        chunks = [prompt]
    else:
        chunks = [
            prompt[:, start:start + prefill_chunk]
            for start in range(0, prompt_len, prefill_chunk)
        ]
    for slab in chunks:
        prefill_logits, mutated = decoder.apply(
            {"params": params, "cache": cache}, slab, mutable=["cache"]
        )
        cache = mutated["cache"]
    first, rng = choose(
        prefill_logits[:, -1], rng, buffer, jnp.asarray(prompt_len)
    )
    done = jnp.zeros((batch,), bool)
    first, done = finish(first, done)
    buffer = jax.lax.dynamic_update_slice(
        buffer, first[:, None], (0, prompt_len)
    )

    def body(carry):
        buffer, cache, rng, t, done = carry
        token = jax.lax.dynamic_slice(buffer, (0, t), (batch, 1))
        logits, mutated = decoder.apply(
            {"params": params, "cache": cache}, token, mutable=["cache"]
        )
        cache = mutated["cache"]
        chosen, rng = choose(logits[:, 0], rng, buffer, t + 1)
        chosen, done = finish(chosen, done)
        buffer = jax.lax.dynamic_update_slice(
            buffer, chosen[:, None], (0, t + 1)
        )
        return buffer, cache, rng, t + 1, done

    def cond(carry):
        _, _, _, t, done = carry
        return (t < total - 1) & ~jnp.all(done)

    buffer, _, _, t, done = jax.lax.while_loop(
        cond, body, (buffer, cache, rng, jnp.asarray(prompt_len), done)
    )
    if eos_token_id is not None:
        # An early exit (all rows done) leaves columns > t unwritten;
        # stamp them with the pad token so finished rows read uniformly.
        # Without early exit t == total-1 and this is a no-op.
        cols = jnp.arange(total)[None, :]
        buffer = jnp.where(cols > t, jnp.int32(pad), buffer)
    return buffer


def _generate_jit(model, max_new_tokens, temperature, top_k, top_p,
                  eos_token_id, pad_token_id, prefill_chunk, min_p,
                  repetition_penalty, has_rng):
    """One compiled executable per static generate() configuration
    (shared cache + rationale: models/_jitcache.py)."""

    def make():
        def run(params, prompt, rng):
            return _generate_traced(
                model, params, prompt, max_new_tokens, temperature,
                rng if has_rng else None, top_k, top_p, eos_token_id,
                pad_token_id, prefill_chunk, min_p, repetition_penalty,
            )

        return run

    return cached_jit(
        ("generate", model, max_new_tokens, temperature, top_k, top_p,
         eos_token_id, pad_token_id, prefill_chunk, min_p,
         repetition_penalty, has_rng),
        make,
    )


def generate(
    model: TransformerLM,
    params: Any,
    prompt: jax.Array,
    max_new_tokens: int,
    temperature: float = 0.0,
    rng: jax.Array | None = None,
    top_k: int | None = None,
    top_p: float | None = None,
    eos_token_id: int | None = None,
    pad_token_id: int | None = None,
    prefill_chunk: int | None = None,
    min_p: float | None = None,
    repetition_penalty: float | None = None,
) -> jax.Array:
    """Jit-cached wrapper around the traced generate body — see
    `_generate_traced` for the full semantics docstring.  Static knobs
    key a compiled-executable cache, so repeated plain calls (tests,
    serving oracles, benchmarks) pay one compile per configuration
    instead of eager per-token dispatch."""
    if max_new_tokens <= 0:
        # Preserve the eager identity contract (validation still fires
        # inside the traced body for the normal path).
        return _generate_traced(
            model, params, prompt, max_new_tokens, temperature, rng,
            top_k, top_p, eos_token_id, pad_token_id, prefill_chunk,
            min_p, repetition_penalty,
        )
    fn = _generate_jit(
        model, int(max_new_tokens),
        float(temperature),
        top_k, top_p, eos_token_id, pad_token_id, prefill_chunk, min_p,
        repetition_penalty, rng is not None,
    )
    if rng is None:
        rng = jax.random.PRNGKey(0)
    return fn(params, jnp.asarray(prompt), rng)
