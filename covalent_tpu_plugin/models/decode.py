"""Autoregressive generation with a per-layer KV cache.

The training-side ``TransformerLM`` recomputes attention over the full
prefix; generation instead runs the model in ``decode=True`` mode — each
layer appends this step's K/V at a cache cursor (flax "cache" collection)
and attends a single-token query over the cached prefix, so a step costs
O(S·D) attention reads instead of O(S²·D) recompute.

The loop is a ``lax.fori_loop`` writing into a fixed (B, P+N) token buffer
— fully jittable, one compilation for any prompt content of a given shape.
The prompt region is teacher-forced (generated tokens only land past it),
which warms the cache and keeps the loop body uniform for XLA.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from .transformer import TransformerLM


def _decode_model(model: TransformerLM) -> TransformerLM:
    if model.config.decode:
        return model
    return TransformerLM(dataclasses.replace(model.config, decode=True))


def init_cache(model: TransformerLM, batch_size: int) -> Any:
    """Zeroed per-layer KV cache sized ``config.max_seq``.

    Shapes come from ``jax.eval_shape`` over the decoder's init — no
    parameters are ever materialised (a bare init would sample the full
    weight set just to throw it away).
    """
    decoder = _decode_model(model)
    abstract = jax.eval_shape(
        lambda rng, tokens: decoder.init(rng, tokens),
        jax.random.PRNGKey(0),
        jnp.zeros((batch_size, 1), jnp.int32),
    )
    return jax.tree_util.tree_map(
        lambda leaf: jnp.zeros(leaf.shape, leaf.dtype), abstract["cache"]
    )


def generate(
    model: TransformerLM,
    params: Any,
    prompt: jax.Array,
    max_new_tokens: int,
    temperature: float = 0.0,
    rng: jax.Array | None = None,
) -> jax.Array:
    """Generate ``max_new_tokens`` continuations of ``prompt`` ((B, P) int32).

    ``temperature=0`` is greedy argmax; otherwise softmax sampling at the
    given temperature (requires ``rng``).  Returns the full (B, P+N) token
    buffer.  Wrap in ``jax.jit`` for repeated use — everything inside is a
    single compiled loop.
    """
    decoder = _decode_model(model)
    config = decoder.config
    batch, prompt_len = prompt.shape
    total = prompt_len + max_new_tokens
    if total > config.max_seq:
        raise ValueError(
            f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) "
            f"exceeds config.max_seq ({config.max_seq})"
        )
    if temperature > 0 and rng is None:
        raise ValueError("sampling (temperature > 0) requires rng")
    if rng is None:
        rng = jax.random.PRNGKey(0)

    cache = init_cache(model, batch)
    buffer = jnp.zeros((batch, total), jnp.int32)
    buffer = jax.lax.dynamic_update_slice(buffer, prompt, (0, 0))

    def body(t, carry):
        buffer, cache, rng = carry
        token = jax.lax.dynamic_slice(buffer, (0, t), (batch, 1))
        logits, mutated = decoder.apply(
            {"params": params, "cache": cache}, token, mutable=["cache"]
        )
        cache = mutated["cache"]
        step_logits = logits[:, 0].astype(jnp.float32)  # (B, vocab)
        rng, sample_key = jax.random.split(rng)
        if temperature > 0:
            chosen = jax.random.categorical(
                sample_key, step_logits / temperature, axis=-1
            )
        else:
            chosen = jnp.argmax(step_logits, axis=-1)
        chosen = chosen.astype(jnp.int32)
        # Inside the prompt the next token is teacher-forced; past it, the
        # model's choice lands in the buffer.
        existing = jax.lax.dynamic_slice(buffer, (0, t + 1), (batch, 1))[:, 0]
        next_token = jnp.where(t + 1 >= prompt_len, chosen, existing)
        buffer = jax.lax.dynamic_update_slice(
            buffer, next_token[:, None], (0, t + 1)
        )
        return buffer, cache, rng

    buffer, _, _ = jax.lax.fori_loop(0, total - 1, body, (buffer, cache, rng))
    return buffer
