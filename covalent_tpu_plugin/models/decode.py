"""Autoregressive generation with a per-layer KV cache.

The training-side ``TransformerLM`` recomputes attention over the full
prefix; generation instead runs the model in ``decode=True`` mode: one
batched *prefill* pass pushes the whole prompt's K/V into each layer's
cache (flax "cache" collection), then each decode step appends a single
token at the cache cursor and attends the cached prefix — a step costs
O(S·D) attention reads instead of O(S²·D) recompute, and time-to-first-
token is one forward pass, not P sequential steps.

The decode loop is a ``lax.fori_loop`` writing into a fixed (B, P+N)
token buffer — fully jittable, one compilation for any prompt content of
a given shape.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from .transformer import TransformerLM


def _decode_model(model: TransformerLM) -> TransformerLM:
    if model.config.decode:
        return model
    return TransformerLM(dataclasses.replace(model.config, decode=True))


def init_cache(model: TransformerLM, batch_size: int) -> Any:
    """Zeroed per-layer KV cache sized ``config.max_seq``.

    Shapes come from ``jax.eval_shape`` over the decoder's init — no
    parameters are ever materialised (a bare init would sample the full
    weight set just to throw it away).
    """
    decoder = _decode_model(model)
    abstract = jax.eval_shape(
        lambda rng, tokens: decoder.init(rng, tokens),
        jax.random.PRNGKey(0),
        jnp.zeros((batch_size, 1), jnp.int32),
    )
    return jax.tree_util.tree_map(
        lambda leaf: jnp.zeros(leaf.shape, leaf.dtype), abstract["cache"]
    )


def inference_params(params: Any) -> Any:
    """Cast f32 master weights to bf16 for serving.

    Decode steps are HBM-bandwidth-bound — every step re-reads the full
    weight set — so halving the bytes is a direct speedup: measured +10%
    tokens/s scanned and +48% with ``scan_layers=False`` on the v5e 125M
    decode (benchmarks/DECODE_SWEEP.md).  Non-f32 leaves (e.g. int
    embeddings) pass through untouched; training should keep the f32
    masters, this is a serving-side copy.
    """
    return jax.tree_util.tree_map(
        lambda p: p.astype(jnp.bfloat16) if p.dtype == jnp.float32 else p,
        params,
    )


def _filter_top_k(logits: jax.Array, top_k: int) -> jax.Array:
    """Mask all but the ``top_k`` largest logits per row to NEG_INF.

    ``jax.lax.top_k`` keeps the shape static, so the filter is jittable for
    any fixed ``top_k``.
    """
    from ..ops.attention import NEG_INF

    kth = jax.lax.top_k(logits, top_k)[0][..., -1:]  # (..., 1)
    return jnp.where(logits < kth, NEG_INF, logits)


def _filter_top_p(logits: jax.Array, top_p: float) -> jax.Array:
    """Nucleus filter: keep the smallest prefix of the sorted distribution
    whose cumulative probability reaches ``top_p``; mask the rest.

    Static-shape formulation: sort once, compute the cumulative softmax
    mass *before* each position, and mask tokens whose preceding mass
    already covers ``top_p`` (the first token always survives).
    """
    from ..ops.attention import NEG_INF

    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    mass_before = jnp.cumsum(probs, axis=-1) - probs
    cutoff_idx = jnp.sum((mass_before < top_p).astype(jnp.int32), axis=-1)
    # Logit value at the last kept (sorted) position is the threshold.
    threshold = jnp.take_along_axis(
        sorted_logits, jnp.maximum(cutoff_idx - 1, 0)[..., None], axis=-1
    )
    return jnp.where(logits < threshold, NEG_INF, logits)


def generate(
    model: TransformerLM,
    params: Any,
    prompt: jax.Array,
    max_new_tokens: int,
    temperature: float = 0.0,
    rng: jax.Array | None = None,
    top_k: int | None = None,
    top_p: float | None = None,
) -> jax.Array:
    """Generate ``max_new_tokens`` continuations of ``prompt`` ((B, P) int32).

    ``temperature=0`` is greedy argmax; otherwise softmax sampling at the
    given temperature (requires ``rng``), optionally restricted to the
    ``top_k`` highest logits and/or the ``top_p`` nucleus (applied in that
    order, the HF/transformers convention).  Returns the full (B, P+N)
    token buffer.  Wrap in ``jax.jit`` for repeated use — everything inside
    is a single compiled loop.
    """
    decoder = _decode_model(model)
    config = decoder.config
    batch, prompt_len = prompt.shape
    total = prompt_len + max(max_new_tokens, 0)
    if total > config.max_seq:
        raise ValueError(
            f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) "
            f"exceeds config.max_seq ({config.max_seq})"
        )
    # Argument-shape validation fires even for max_new_tokens <= 0 (a bad
    # combination is a caller bug worth surfacing); the rng requirement
    # only applies when sampling will actually happen, preserving the
    # original "zero new tokens is identity" contract.
    if temperature <= 0 and (top_k is not None or top_p is not None):
        raise ValueError("top_k/top_p require sampling (temperature > 0)")
    if top_k is not None and not 1 <= top_k <= config.vocab_size:
        raise ValueError(f"top_k must be in [1, {config.vocab_size}], got {top_k}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if max_new_tokens <= 0:
        return prompt.astype(jnp.int32)
    if temperature > 0 and rng is None:
        raise ValueError("sampling (temperature > 0) requires rng")
    if rng is None:
        rng = jax.random.PRNGKey(0)

    cache = init_cache(model, batch)
    buffer = jnp.zeros((batch, total), jnp.int32)
    buffer = jax.lax.dynamic_update_slice(buffer, prompt, (0, 0))

    def choose(step_logits, rng):
        rng, sample_key = jax.random.split(rng)
        if temperature > 0:
            scaled = step_logits.astype(jnp.float32) / temperature
            if top_k is not None:
                scaled = _filter_top_k(scaled, top_k)
            if top_p is not None:
                scaled = _filter_top_p(scaled, top_p)
            chosen = jax.random.categorical(sample_key, scaled, axis=-1)
        else:
            chosen = jnp.argmax(step_logits.astype(jnp.float32), axis=-1)
        return chosen.astype(jnp.int32), rng

    # Prefill: one batched pass pushes the whole prompt into the caches and
    # yields the first generated token from the prompt's last logits.
    prefill_logits, mutated = decoder.apply(
        {"params": params, "cache": cache}, prompt, mutable=["cache"]
    )
    cache = mutated["cache"]
    first, rng = choose(prefill_logits[:, -1], rng)
    buffer = jax.lax.dynamic_update_slice(
        buffer, first[:, None], (0, prompt_len)
    )

    def body(t, carry):
        buffer, cache, rng = carry
        token = jax.lax.dynamic_slice(buffer, (0, t), (batch, 1))
        logits, mutated = decoder.apply(
            {"params": params, "cache": cache}, token, mutable=["cache"]
        )
        cache = mutated["cache"]
        chosen, rng = choose(logits[:, 0], rng)
        buffer = jax.lax.dynamic_update_slice(
            buffer, chosen[:, None], (0, t + 1)
        )
        return buffer, cache, rng

    buffer, _, _ = jax.lax.fori_loop(
        prompt_len, total - 1, body, (buffer, cache, rng)
    )
    return buffer
