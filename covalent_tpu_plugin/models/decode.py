"""Autoregressive generation with a per-layer KV cache.

The training-side ``TransformerLM`` recomputes attention over the full
prefix; generation instead runs the model in ``decode=True`` mode: one
batched *prefill* pass pushes the whole prompt's K/V into each layer's
cache (flax "cache" collection), then each decode step appends a single
token at the cache cursor and attends the cached prefix — a step costs
O(S·D) attention reads instead of O(S²·D) recompute, and time-to-first-
token is one forward pass, not P sequential steps.

The decode loop is a ``lax.fori_loop`` writing into a fixed (B, P+N)
token buffer — fully jittable, one compilation for any prompt content of
a given shape.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from .transformer import TransformerLM


def _decode_model(model: TransformerLM) -> TransformerLM:
    if model.config.decode:
        return model
    return TransformerLM(dataclasses.replace(model.config, decode=True))


def init_cache(model: TransformerLM, batch_size: int) -> Any:
    """Zeroed per-layer KV cache sized ``config.max_seq``.

    Shapes come from ``jax.eval_shape`` over the decoder's init — no
    parameters are ever materialised (a bare init would sample the full
    weight set just to throw it away).
    """
    decoder = _decode_model(model)
    abstract = jax.eval_shape(
        lambda rng, tokens: decoder.init(rng, tokens),
        jax.random.PRNGKey(0),
        jnp.zeros((batch_size, 1), jnp.int32),
    )
    return jax.tree_util.tree_map(
        lambda leaf: jnp.zeros(leaf.shape, leaf.dtype), abstract["cache"]
    )


def generate(
    model: TransformerLM,
    params: Any,
    prompt: jax.Array,
    max_new_tokens: int,
    temperature: float = 0.0,
    rng: jax.Array | None = None,
) -> jax.Array:
    """Generate ``max_new_tokens`` continuations of ``prompt`` ((B, P) int32).

    ``temperature=0`` is greedy argmax; otherwise softmax sampling at the
    given temperature (requires ``rng``).  Returns the full (B, P+N) token
    buffer.  Wrap in ``jax.jit`` for repeated use — everything inside is a
    single compiled loop.
    """
    decoder = _decode_model(model)
    config = decoder.config
    batch, prompt_len = prompt.shape
    total = prompt_len + max(max_new_tokens, 0)
    if total > config.max_seq:
        raise ValueError(
            f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) "
            f"exceeds config.max_seq ({config.max_seq})"
        )
    if max_new_tokens <= 0:
        return prompt.astype(jnp.int32)
    if temperature > 0 and rng is None:
        raise ValueError("sampling (temperature > 0) requires rng")
    if rng is None:
        rng = jax.random.PRNGKey(0)

    cache = init_cache(model, batch)
    buffer = jnp.zeros((batch, total), jnp.int32)
    buffer = jax.lax.dynamic_update_slice(buffer, prompt, (0, 0))

    def choose(step_logits, rng):
        rng, sample_key = jax.random.split(rng)
        if temperature > 0:
            chosen = jax.random.categorical(
                sample_key, step_logits.astype(jnp.float32) / temperature,
                axis=-1,
            )
        else:
            chosen = jnp.argmax(step_logits.astype(jnp.float32), axis=-1)
        return chosen.astype(jnp.int32), rng

    # Prefill: one batched pass pushes the whole prompt into the caches and
    # yields the first generated token from the prompt's last logits.
    prefill_logits, mutated = decoder.apply(
        {"params": params, "cache": cache}, prompt, mutable=["cache"]
    )
    cache = mutated["cache"]
    first, rng = choose(prefill_logits[:, -1], rng)
    buffer = jax.lax.dynamic_update_slice(
        buffer, first[:, None], (0, prompt_len)
    )

    def body(t, carry):
        buffer, cache, rng = carry
        token = jax.lax.dynamic_slice(buffer, (0, t), (batch, 1))
        logits, mutated = decoder.apply(
            {"params": params, "cache": cache}, token, mutable=["cache"]
        )
        cache = mutated["cache"]
        chosen, rng = choose(logits[:, 0], rng)
        buffer = jax.lax.dynamic_update_slice(
            buffer, chosen[:, None], (0, t + 1)
        )
        return buffer, cache, rng

    buffer, _, _ = jax.lax.fori_loop(
        prompt_len, total - 1, body, (buffer, cache, rng)
    )
    return buffer
