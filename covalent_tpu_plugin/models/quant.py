"""Weight-only int8 quantization for serving.

Decode is HBM-bandwidth-bound — every step re-reads the full weight set —
so shrinking the bytes is a direct speedup: bf16 halves them
(:func:`..decode.inference_params`) and int8 halves them again.  The
scheme is the standard TPU-friendly weight-only symmetric quantization:

* each dense kernel is stored as **int8** with a **per-output-channel
  f32 scale** (``scale = amax(|w|, input_axes) / 127``);
* the matmul runs ``x @ kernel.astype(bf16)`` — the int8 tensor is what
  crosses HBM, the cast happens in registers on the way to the MXU —
  then multiplies the per-channel scale into the output;
* activations stay bf16 (no activation quantization, no calibration
  data needed), embeddings/norms are untouched.

Usage::

    qmodel, qparams = quantize_lm(model, params)   # f32/bf16 masters in
    out = generate(qmodel, qparams, prompt, n)     # same API as before

``TransformerConfig.quantized=True`` swaps every dense layer for
:class:`QuantDenseGeneral`; :func:`quantize_lm` builds that config, a
structure template via ``jax.eval_shape`` (no weights materialised), and
converts the trained parameters into it.  Reference has no serving path
at all (SURVEY §5 long-context: ABSENT); this is net-new capability.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


def _as_tuple(value) -> tuple:
    return tuple(value) if isinstance(value, (tuple, list)) else (value,)


class QuantDenseGeneral(nn.Module):
    """``nn.DenseGeneral`` twin consuming int8 kernels + per-channel scales.

    Declares the same module name and a ``kernel`` param of the same shape
    (dtype int8) plus a ``scale`` param shaped like the output features, so
    a quantized checkpoint lines up 1:1 with the dense model's tree.  No
    bias (none of the transformer's denses use one).
    """

    features: Any                 # int or tuple, as nn.DenseGeneral
    kernel_axes: Sequence[str]    # logical partition axes for the kernel
    axis: Any = -1                # contraction axes on the input
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        features = _as_tuple(self.features)
        axis = _as_tuple(self.axis)
        axis = tuple(a % x.ndim for a in axis)
        contract_shape = tuple(x.shape[a] for a in axis)
        kernel_shape = contract_shape + features

        kernel = self.param(
            "kernel",
            nn.with_partitioning(
                nn.initializers.zeros_init(), tuple(self.kernel_axes)
            ),
            kernel_shape,
            jnp.int8,
        )
        scale = self.param(
            "scale",
            nn.with_partitioning(
                nn.initializers.ones_init(),
                tuple(self.kernel_axes)[len(contract_shape):],
            ),
            features,
            self.param_dtype,
        )
        # int8 crosses HBM; the bf16 cast is register-resident on the way
        # to the MXU.  Contraction dims mirror nn.DenseGeneral's.
        y = jax.lax.dot_general(
            x.astype(self.dtype),
            kernel.astype(self.dtype),
            ((axis, tuple(range(len(axis)))), ((), ())),
        )
        return y * scale.astype(self.dtype)


def dense_general(
    quantized: bool,
    *,
    features,
    kernel_axes: Sequence[str],
    kernel_init,
    axis=-1,
    dtype=jnp.bfloat16,
    param_dtype=jnp.float32,
    name: str,
    lora_rank: int = 0,
    lora_alpha: float = 16.0,
):
    """The transformer's one dense-layer factory.

    Float, int8-serving, or either with LoRA adapters on top — all four
    combinations share param names, so checkpoints line up across modes.
    """
    if lora_rank:
        from .lora import LoRADenseGeneral  # deferred: lora imports quant

        return LoRADenseGeneral(
            features=features,
            kernel_axes=tuple(kernel_axes),
            rank=lora_rank,
            alpha=lora_alpha,
            axis=axis,
            dtype=dtype,
            param_dtype=param_dtype,
            quantized=quantized,
            kernel_init=kernel_init,
            name=name,
        )
    if quantized:
        return QuantDenseGeneral(
            features=features,
            kernel_axes=tuple(kernel_axes),
            axis=axis,
            dtype=dtype,
            param_dtype=param_dtype,
            name=name,
        )
    return nn.DenseGeneral(
        features=features,
        axis=axis,
        use_bias=False,
        dtype=dtype,
        param_dtype=param_dtype,
        kernel_init=nn.with_partitioning(kernel_init, tuple(kernel_axes)),
        name=name,
    )


def quantize_array(w: jax.Array, n_feature_dims: int):
    """Symmetric per-output-channel int8: returns (q, scale).

    Input (contraction) axes are the leading ``w.ndim - n_feature_dims``
    dims, matching ``nn.DenseGeneral``'s kernel layout.
    """
    input_axes = tuple(range(w.ndim - n_feature_dims))
    w32 = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=input_axes)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return q, scale


#: The serving tier's closed decode-mode set (the per-request ``quality``
#: knob's values).  ``fp`` is the bit-exact reference lane every refusal
#: falls back to; the others trade exactness for HBM bytes.
SERVING_MODES = ("fp", "int8", "kv_quant", "full_quant")


def mode_variant(model, params, mode: str) -> tuple[Any, Any]:
    """``(model, params)`` twin for one serving decode mode.

    * ``fp`` — the inputs, untouched (bit-exact reference lane);
    * ``int8`` — weight-only int8 via :func:`quantize_lm`;
    * ``kv_quant`` — same weights, int8 KV cache
      (``TransformerConfig.quantized_kv_cache``);
    * ``full_quant`` — both.

    Raises :class:`ValueError` on an unknown mode name (a config typo —
    callers should fail loudly) and propagates :func:`quantize_lm`'s
    refusals (MoE / scanned / LoRA models), which the serving engine
    treats as a per-mode refusal with fp fallback rather than an error.
    """
    if mode not in SERVING_MODES:
        raise ValueError(
            f"unknown decode mode {mode!r}; expected one of {SERVING_MODES}"
        )
    if mode == "fp":
        return model, params
    from .transformer import TransformerLM

    if mode == "int8":
        return quantize_lm(model, params)
    if mode == "kv_quant":
        return (
            TransformerLM(
                dataclasses.replace(model.config, quantized_kv_cache=True)
            ),
            params,
        )
    qmodel, qparams = quantize_lm(model, params)
    return (
        TransformerLM(
            dataclasses.replace(qmodel.config, quantized_kv_cache=True)
        ),
        qparams,
    )


def quantize_lm(model, params) -> tuple[Any, Any]:
    """(quantized model, quantized params) from a trained LM.

    Builds the ``quantized=True`` twin config, takes its parameter
    *structure* via ``jax.eval_shape`` (no weights materialised), and fills
    it: int8 ``kernel`` + f32 ``scale`` pairs from the float kernels,
    everything else (embeddings, norms) copied through.  Requires
    ``scan_layers=False`` — a scanned kernel's leading layer axis is
    indistinguishable from a contraction axis in the stacked tree, and
    unrolled is the measured serving-optimal mode anyway
    (benchmarks/DECODE_SWEEP.md).  Compose with
    :func:`..decode.inference_params` to also cast the float leftovers to
    bf16.
    """
    from .transformer import TransformerLM

    config = model.config
    if config.scan_layers:
        raise ValueError(
            "quantize_lm requires scan_layers=False (serve unrolled; see "
            "benchmarks/DECODE_SWEEP.md)"
        )
    if config.moe_experts:
        raise ValueError("quantize_lm does not support MoE models yet")
    if config.lora_rank:
        raise ValueError(
            "quantize the base first, then attach adapters "
            "(lora.quantize_then_lora)"
        )
    from ..parallel.sharding import unbox

    qmodel = TransformerLM(dataclasses.replace(config, quantized=True))
    template = unbox(
        jax.eval_shape(
            lambda: qmodel.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 1), jnp.int32)
            )["params"]
        )
    )

    def fill(template_node, params_node):
        if not isinstance(template_node, dict):
            return params_node
        if (
            "kernel" in template_node
            and getattr(template_node["kernel"], "dtype", None) == jnp.int8
        ):
            n_feature_dims = len(template_node["scale"].shape)
            q, scale = quantize_array(params_node["kernel"], n_feature_dims)
            extra = {
                k: params_node[k] for k in params_node if k != "kernel"
            }
            return {"kernel": q, "scale": scale, **extra}
        return {
            key: fill(template_node[key], params_node[key])
            for key in template_node
        }

    # Work on unboxed trees: Partitioned metadata doesn't survive a
    # structural rewrite, and serving shardings come from the quant
    # model's own init when needed.
    return qmodel, fill(template, unbox(params))
