"""Greedy speculative decoding: draft proposes, target verifies in slabs.

A small draft model decodes ``draft_len`` tokens autoregressively, then
the target model scores all of them in ONE forward slab; the longest
agreeing prefix is committed plus the target's correction at the first
mismatch.  Every committed token is the target's own greedy choice, so
the output is **bit-identical to ``generate(target, ...)``** — the draft
only changes how many target forward passes are needed (one per round
instead of one per token).  On TPU the verify slab is a k-token prefill,
far better MXU utilisation than k single-token steps.

Design notes (all static-shape, one jittable ``lax.while_loop``):

* each round REWINDS both KV caches to the committed prefix by setting
  their ``cache_index`` leaves — stale entries beyond the cursor are
  overwritten before they can be read, so no cache copying happens;
* the verify slab scores ``draft_len + 1`` positions (the last committed
  token plus all ``draft_len`` drafts), so a fully-accepted window
  commits ``draft_len + 1`` tokens — the standard "bonus token" — for
  the same one target forward pass per round;
* batched prompts accept the MINIMUM match length across rows — still
  exact (recomputed tokens are recomputed identically), just less
  speedup when rows diverge;
* sampling lives in :func:`speculative_sample` — rejection-sampling
  acceptance (Leviathan et al. 2023): accept draft token ``d`` with
  probability ``min(1, p(d)/q(d))``, resample rejections from the
  normalised residual ``max(p - q, 0)``, so every committed token is
  distributed EXACTLY as target sampling at the same
  temperature/top-k/top-p filters.

The reference has no serving path at all; this composes with the other
serving modes (bf16 cast, int8 quant — any decode-capable model pair
with one vocabulary works).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from .decode import _decode_model, init_cache
from ._jitcache import cached_jit
from .transformer import TransformerLM


def _set_cursor(cache: Any, value) -> Any:
    """Return ``cache`` with every layer's ``cache_index`` set to value.

    ``full_like`` keeps the leaf's shape: under scanned layers the cursor
    is stacked per layer (shape ``(L,)``), unrolled it is a scalar.
    """
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: (
            jnp.full_like(leaf, value)
            if any(getattr(e, "key", None) == "cache_index" for e in path)
            else leaf
        ),
        cache,
    )


def make_lane_spec_round(target_decoder, draft_decoder, eos_token_id,
                         length, draft_len):
    """Single-LANE greedy draft-then-verify round for the continuous
    serving engine (``models/serve.py`` vmaps this across its slots).

    One round on one slot lane: the draft proposes ``draft_len`` tokens
    autoregressively from the committed prefix, the target scores the
    ``draft_len + 1`` slab ``[last_committed, d_1..d_k]`` in ONE pass,
    and the longest agreeing prefix plus the target's own choice at the
    first disagreement is committed — every committed token is the
    target's greedy pick, so a spec lane's stream is bit-identical to
    the plain decode loop's (the engine's fp-fallback contract).  This
    is :func:`_speculative_generate_traced`'s round body re-shaped for
    the engine's per-slot state: per-lane commit (no cross-batch MIN —
    slots are independent requests), per-request budget/EOS truncation,
    and a masked full-row merge instead of a dynamic-slice write (donated
    buffers; no scatter-duplicate hazards at the row tail).

    Returns ``lane_round(t_params, d_params, cache, dcache, row, pos,
    cap, n_gen, done) -> (cache, dcache, row, pos', n_gen', done',
    proposed, accepted)`` where ``pos`` is the engine invariant cursor
    (buffer index of the last committed token == target cache cursor),
    and ``proposed``/``accepted`` are this round's draft-agreement
    counters (zero for frozen lanes).  Cache-capacity contract (checked
    by the engine, not here): the verify slab may write up to
    ``length - 1 + draft_len`` target positions and the draft walk up to
    ``length - 2 + draft_len``, so both models need
    ``max_seq >= length + draft_len``.
    """
    k = int(draft_len)
    steps = jnp.arange(k + 1)

    def lane_round(t_params, d_params, cache, dcache, row, pos, cap,
                   n_gen, done):
        # Draft k tokens.  The 2-token repair slab (the last two
        # committed tokens) rebuilds the K/V of the final committed
        # token — produced as an output last round, never consumed —
        # uniformly for every round shape; pos >= 1 always (admission
        # commits the prefill token first).
        dcache = _set_cursor(dcache, pos - 1)
        tail = jax.lax.dynamic_slice(row, (pos - 1,), (2,))
        dlogits, mutated = draft_decoder.apply(
            {"params": d_params, "cache": dcache}, tail[None],
            mutable=["cache"],
        )
        first = jnp.argmax(
            dlogits[0, -1].astype(jnp.float32), axis=-1
        ).astype(jnp.int32)

        def body(i, carry):
            dcache, token, drafts = carry
            lg, mut = draft_decoder.apply(
                {"params": d_params, "cache": dcache}, token[None, None],
                mutable=["cache"],
            )
            nxt = jnp.argmax(
                lg[0, -1].astype(jnp.float32), axis=-1
            ).astype(jnp.int32)
            return mut["cache"], nxt, drafts.at[i].set(nxt)

        dcache, _, drafts = jax.lax.fori_loop(
            1, k, body,
            (mutated["cache"], first,
             jnp.zeros((k,), jnp.int32).at[0].set(first)),
        )

        # Verify slab: the target cursor sits at ``pos`` (cache valid
        # through pos-1), so feeding [row[pos], d_1..d_k] yields its
        # greedy choice for k+1 positions — the (k+1)-th is the bonus
        # token when every draft agrees.
        cur = jax.lax.dynamic_slice(row, (pos,), (1,))
        slab = jnp.concatenate([cur, drafts])
        tlogits, mutated = target_decoder.apply(
            {"params": t_params, "cache": cache}, slab[None],
            mutable=["cache"],
        )
        cache = mutated["cache"]
        greedy = jnp.argmax(
            tlogits[0].astype(jnp.float32), axis=-1
        ).astype(jnp.int32)  # (k+1,)

        match = (drafts == greedy[:k]).astype(jnp.int32)
        run = jnp.sum(jnp.cumprod(match))  # leading agreement, 0..k
        new = jnp.where(
            steps < run,
            jnp.concatenate([drafts, jnp.zeros((1,), jnp.int32)]),
            greedy,
        )
        live = ~done
        commit = jnp.minimum(run + 1, cap - n_gen)
        if eos_token_id is not None:
            hits = (new == eos_token_id) & (steps < commit)
            any_eos = jnp.any(hits)
            commit = jnp.where(any_eos, jnp.argmax(hits) + 1, commit)
        else:
            any_eos = jnp.zeros((), bool)
        commit = jnp.where(live, commit, 0)

        # Full-row masked merge: token ``new[i]`` lands at row position
        # ``pos + 1 + i`` for i < commit.  A where over the whole row
        # (instead of a scatter) cannot alias clipped tail indices.
        rel = jnp.arange(length) - (pos + 1)
        gathered = new[jnp.clip(rel, 0, k)]
        row = jnp.where((rel >= 0) & (rel < commit), gathered, row)

        n_gen = n_gen + commit
        done = done | (live & ((n_gen >= cap) | any_eos))
        pos = pos + commit
        # Rewind the target cursor onto the new last-committed token:
        # cache slots pos..pos-1+k hold draft K/V past the commit point,
        # dead until the next round's slab overwrites them (the same
        # exactness argument admission's pad positions ride).
        cache = _set_cursor(cache, pos)
        proposed = jnp.where(live, k, 0).astype(jnp.int32)
        accepted = jnp.where(live, run, 0).astype(jnp.int32)
        return cache, dcache, row, pos, n_gen, done, proposed, accepted

    return lane_round


def _speculative_generate_traced(
    target_model: TransformerLM,
    target_params: Any,
    draft_model: TransformerLM,
    draft_params: Any,
    prompt: jax.Array,
    max_new_tokens: int,
    draft_len: int = 4,
    return_stats: bool = False,
):
    """Greedy generation of ``max_new_tokens``; exact target output.

    Returns the (B, P+N) buffer, plus ``{"rounds": ...}`` when
    ``return_stats`` — target forward passes = rounds + 1 (prefill), vs
    ``max_new_tokens`` for plain decoding; the ratio is the speculative
    win at whatever agreement the draft earns.
    """
    if draft_len < 1:
        raise ValueError(f"draft_len must be >= 1, got {draft_len}")
    if target_model.config.vocab_size != draft_model.config.vocab_size:
        raise ValueError("target and draft must share a vocabulary")
    if target_model.config.rolling_cache or draft_model.config.rolling_cache:
        # Verify slabs are multi-token writes at pos > 0: when one wraps
        # the ring it erases band-edge entries earlier rows still need
        # (the documented-lossy case), silently breaking the bit-exactness
        # contract.  Refuse rather than approximate.
        raise ValueError(
            "speculative_generate does not support rolling_cache models"
        )
    target = _decode_model(target_model)
    draft = _decode_model(draft_model)
    batch, prompt_len = prompt.shape
    if max_new_tokens <= 0:  # identity, like generate(): no headroom needed
        out = prompt.astype(jnp.int32)
        return (out, {"rounds": jnp.zeros((), jnp.int32)}) if return_stats else out
    total = prompt_len + max_new_tokens
    # Verify slabs may scribble up to draft_len positions past the
    # committed end; both caches and the buffer carry that headroom.
    headroom = total + draft_len
    for name, model in (("target", target), ("draft", draft)):
        if headroom > model.config.max_seq:
            raise ValueError(
                f"{name} max_seq {model.config.max_seq} < prompt + "
                f"max_new_tokens + draft_len = {headroom}"
            )

    buffer = jnp.zeros((batch, headroom), jnp.int32)
    buffer = jax.lax.dynamic_update_slice(buffer, prompt, (0, 0))

    # Prefill both models; the target's prefill logits give token #1 (the
    # same first token plain generate() emits).
    t_cache = init_cache(target_model, batch)
    d_cache = init_cache(draft_model, batch)
    t_logits, mutated = target.apply(
        {"params": target_params, "cache": t_cache}, prompt, mutable=["cache"]
    )
    t_cache = mutated["cache"]
    _, mutated = draft.apply(
        {"params": draft_params, "cache": d_cache}, prompt, mutable=["cache"]
    )
    d_cache = mutated["cache"]
    first = jnp.argmax(t_logits[:, -1].astype(jnp.float32), axis=-1)
    buffer = jax.lax.dynamic_update_slice(
        buffer, first[:, None].astype(jnp.int32), (0, prompt_len)
    )

    k = draft_len

    def draft_k(buffer, length, d_cache):
        """k sequential draft steps from the committed prefix.

        Feeds the last TWO committed tokens as a slab first: after a
        fully-accepted (bonus-token) round the draft cache is missing the
        K/V of the final committed token — it was produced as an output,
        never consumed — and re-feeding the two-token tail repairs that
        slot uniformly for every round shape (a partial-accept round just
        rewrites one already-correct position).
        """
        d_cache = _set_cursor(d_cache, length - 2)
        tail = jax.lax.dynamic_slice(buffer, (0, length - 2), (batch, 2))
        logits, mutated = draft.apply(
            {"params": draft_params, "cache": d_cache}, tail, mutable=["cache"]
        )
        d_cache = mutated["cache"]
        first = jnp.argmax(
            logits[:, -1].astype(jnp.float32), axis=-1
        ).astype(jnp.int32)[:, None]
        drafted0 = jnp.concatenate(
            [jnp.zeros((batch, k - 1), jnp.int32), first], axis=1
        )

        def body(_, carry):
            d_cache, token, drafted = carry
            logits, mutated = draft.apply(
                {"params": draft_params, "cache": d_cache},
                token,
                mutable=["cache"],
            )
            nxt = jnp.argmax(
                logits[:, -1].astype(jnp.float32), axis=-1
            ).astype(jnp.int32)[:, None]
            drafted = jnp.concatenate([drafted[:, 1:], nxt], axis=1)
            return mutated["cache"], nxt, drafted

        d_cache, _, drafted = jax.lax.fori_loop(
            0, k - 1, body, (d_cache, first, drafted0)
        )
        return d_cache, drafted  # (B, k): d_1..d_k

    def round_body(carry):
        buffer, n_generated, t_cache, d_cache, rounds = carry
        length = prompt_len + n_generated  # committed tokens in buffer

        d_cache, drafted = draft_k(buffer, length, d_cache)

        # Target verifies all k candidates in one slab: feeding
        # [committed_last, d_1..d_k] at cursor length-1 yields the
        # target's greedy choice for k+1 positions — the (k+1)-th is the
        # free "bonus token" committed when every draft agrees.
        t_cache = _set_cursor(t_cache, length - 1)
        last = jax.lax.dynamic_slice(buffer, (0, length - 1), (batch, 1))
        slab = jnp.concatenate([last, drafted], axis=1)  # (B, k+1)
        logits, mutated = target.apply(
            {"params": target_params, "cache": t_cache}, slab, mutable=["cache"]
        )
        t_cache = mutated["cache"]
        greedy = jnp.argmax(logits.astype(jnp.float32), axis=-1).astype(
            jnp.int32
        )  # (B, k+1): g_1..g_{k+1}

        match = (drafted == greedy[:, :k]).astype(jnp.int32)
        run = jnp.min(
            jnp.sum(jnp.cumprod(match, axis=1), axis=1)
        )  # min leading agreement across the batch, 0..k
        commit = run + 1  # full agreement (run == k) commits the bonus too

        # Positions < run take the draft (== target) tokens; the next
        # position takes the target's choice (correction at a mismatch,
        # bonus token after a full match); later slots are scratch that
        # the next round overwrites before reading.
        padded = jnp.concatenate(
            [drafted, jnp.zeros((batch, 1), jnp.int32)], axis=1
        )
        merged = jnp.where(jnp.arange(k + 1)[None, :] < run, padded, greedy)
        buffer = jax.lax.dynamic_update_slice(buffer, merged, (0, length))
        return (
            buffer,
            n_generated + commit,
            t_cache,
            d_cache,
            rounds + 1,
        )

    def cond(carry):
        return carry[1] < max_new_tokens

    buffer, _, _, _, rounds = jax.lax.while_loop(
        cond,
        round_body,
        (buffer, jnp.ones((), jnp.int32), t_cache, d_cache,
         jnp.zeros((), jnp.int32)),
    )
    out = jax.lax.dynamic_slice(buffer, (0, 0), (batch, total))
    return (out, {"rounds": rounds}) if return_stats else out


def _spec_gen_jit(target_model, draft_model, max_new_tokens, draft_len,
                  return_stats):
    """Compiled-executable cache for plain speculative_generate() calls
    (shared cache + rationale: models/_jitcache.py)."""

    def make():
        def run(target_params, draft_params, prompt):
            return _speculative_generate_traced(
                target_model, target_params, draft_model, draft_params,
                prompt, max_new_tokens, draft_len, return_stats,
            )

        return run

    return cached_jit(
        ("spec_gen", target_model, draft_model, max_new_tokens,
         draft_len, return_stats),
        make,
    )


def speculative_generate(
    target_model: TransformerLM,
    target_params: Any,
    draft_model: TransformerLM,
    draft_params: Any,
    prompt: jax.Array,
    max_new_tokens: int,
    draft_len: int = 4,
    return_stats: bool = False,
):
    """Jit-cached wrapper; semantics in `_speculative_generate_traced`."""
    if max_new_tokens <= 0:
        return _speculative_generate_traced(
            target_model, target_params, draft_model, draft_params,
            prompt, max_new_tokens, draft_len, return_stats,
        )
    fn = _spec_gen_jit(
        target_model, draft_model, int(max_new_tokens), int(draft_len),
        bool(return_stats),
    )
    return fn(target_params, draft_params, jnp.asarray(prompt))


def _filtered_logprobs(logits, temperature, top_k, top_p):
    """Temperature + top-k + top-p filtered log-probabilities (f32).

    The same filter chain :func:`..decode.generate` applies — rejection
    sampling is exact with respect to whatever filtered target
    distribution both models are scored under, so draft and target MUST
    share this transform.
    """
    from .decode import _filter_top_k, _filter_top_p

    scaled = logits.astype(jnp.float32) / temperature
    if top_k is not None:
        scaled = _filter_top_k(scaled, top_k)
    if top_p is not None:
        scaled = _filter_top_p(scaled, top_p)
    return jax.nn.log_softmax(scaled, axis=-1)


def _speculative_sample_traced(
    target_model: TransformerLM,
    target_params: Any,
    draft_model: TransformerLM,
    draft_params: Any,
    prompt: jax.Array,
    max_new_tokens: int,
    draft_len: int = 4,
    temperature: float = 1.0,
    rng: jax.Array | None = None,
    top_k: int | None = None,
    top_p: float | None = None,
    return_stats: bool = False,
):
    """Speculative SAMPLING: rejection-sampling acceptance, exact in
    distribution to ``generate(target, ..., temperature, top_k, top_p)``.

    Each round the draft samples ``draft_len`` tokens; the target scores
    them in one slab; draft token ``d_i`` is accepted with probability
    ``min(1, p_i(d_i) / q_i(d_i))`` (Leviathan et al. 2023).  The first
    rejected position resamples from the normalised residual
    ``max(p_i - q_i, 0)`` — which preserves the target marginal exactly —
    and a fully-accepted window commits a bonus token sampled from
    ``p_{k+1}``.  Batched rows commit the MINIMUM accepted run across the
    batch; a row's discarded accepts are re-proposed with fresh
    randomness next round, which cannot bias its marginal (the discard
    decision depends only on other rows' independent randomness).

    Returns the (B, P+N) buffer (+ ``{"rounds": ...}`` with
    ``return_stats``).  Greedy decoding (temperature 0) lives in
    :func:`speculative_generate`.
    """
    if temperature <= 0:
        raise ValueError(
            "speculative_sample requires temperature > 0; use "
            "speculative_generate for greedy decoding"
        )
    if rng is None:
        raise ValueError("speculative_sample requires rng")
    if draft_len < 1:
        raise ValueError(f"draft_len must be >= 1, got {draft_len}")
    if target_model.config.vocab_size != draft_model.config.vocab_size:
        raise ValueError("target and draft must share a vocabulary")
    if target_model.config.rolling_cache or draft_model.config.rolling_cache:
        raise ValueError(
            "speculative_sample does not support rolling_cache models"
        )
    target = _decode_model(target_model)
    draft = _decode_model(draft_model)
    batch, prompt_len = prompt.shape
    vocab = target.config.vocab_size
    if max_new_tokens <= 0:
        out = prompt.astype(jnp.int32)
        return (out, {"rounds": jnp.zeros((), jnp.int32)}) if return_stats else out
    total = prompt_len + max_new_tokens
    headroom = total + draft_len
    for name, model in (("target", target), ("draft", draft)):
        if headroom > model.config.max_seq:
            raise ValueError(
                f"{name} max_seq {model.config.max_seq} < prompt + "
                f"max_new_tokens + draft_len = {headroom}"
            )
    k = draft_len

    buffer = jnp.zeros((batch, headroom), jnp.int32)
    buffer = jax.lax.dynamic_update_slice(buffer, prompt, (0, 0))

    t_cache = init_cache(target_model, batch)
    d_cache = init_cache(draft_model, batch)
    t_logits, mutated = target.apply(
        {"params": target_params, "cache": t_cache}, prompt, mutable=["cache"]
    )
    t_cache = mutated["cache"]
    _, mutated = draft.apply(
        {"params": draft_params, "cache": d_cache}, prompt, mutable=["cache"]
    )
    d_cache = mutated["cache"]
    rng, key = jax.random.split(rng)
    first = jax.random.categorical(
        key, _filtered_logprobs(t_logits[:, -1], temperature, top_k, top_p),
        axis=-1,
    ).astype(jnp.int32)
    buffer = jax.lax.dynamic_update_slice(
        buffer, first[:, None], (0, prompt_len)
    )

    def draft_k(buffer, length, d_cache, rng):
        """k sampled draft steps; returns the drafts AND their filtered
        log-prob tables (needed for acceptance ratios + residuals)."""
        d_cache = _set_cursor(d_cache, length - 2)
        tail = jax.lax.dynamic_slice(buffer, (0, length - 2), (batch, 2))
        logits, mutated = draft.apply(
            {"params": draft_params, "cache": d_cache}, tail, mutable=["cache"]
        )
        d_cache = mutated["cache"]
        rng, key = jax.random.split(rng)
        logq0 = _filtered_logprobs(logits[:, -1], temperature, top_k, top_p)
        first = jax.random.categorical(key, logq0, axis=-1).astype(jnp.int32)

        logq = jnp.zeros((batch, k, vocab), jnp.float32)
        logq = jax.lax.dynamic_update_slice(
            logq, logq0[:, None, :], (0, 0, 0)
        )
        drafted = jnp.zeros((batch, k), jnp.int32).at[:, 0].set(first)

        def body(i, carry):
            d_cache, token, drafted, logq, rng = carry
            logits, mutated = draft.apply(
                {"params": draft_params, "cache": d_cache},
                token[:, None],
                mutable=["cache"],
            )
            rng, key = jax.random.split(rng)
            logq_i = _filtered_logprobs(
                logits[:, -1], temperature, top_k, top_p
            )
            nxt = jax.random.categorical(key, logq_i, axis=-1).astype(jnp.int32)
            drafted = jax.lax.dynamic_update_slice(
                drafted, nxt[:, None], (0, i)
            )
            logq = jax.lax.dynamic_update_slice(
                logq, logq_i[:, None, :], (0, i, 0)
            )
            return mutated["cache"], nxt, drafted, logq, rng

        d_cache, _, drafted, logq, rng = jax.lax.fori_loop(
            1, k, body, (d_cache, first, drafted, logq, rng)
        )
        return d_cache, drafted, logq, rng

    def round_body(carry):
        buffer, n_generated, t_cache, d_cache, rounds, rng = carry
        length = prompt_len + n_generated

        d_cache, drafted, logq, rng = draft_k(buffer, length, d_cache, rng)

        t_cache = _set_cursor(t_cache, length - 1)
        last = jax.lax.dynamic_slice(buffer, (0, length - 1), (batch, 1))
        slab = jnp.concatenate([last, drafted], axis=1)  # (B, k+1)
        logits, mutated = target.apply(
            {"params": target_params, "cache": t_cache}, slab, mutable=["cache"]
        )
        t_cache = mutated["cache"]
        logp = _filtered_logprobs(logits, temperature, top_k, top_p)
        # (B, k+1, V): p_1..p_{k+1}

        # Acceptance: u_i < p_i(d_i) / q_i(d_i), vectorised over the k
        # drafted positions.
        # Distinct keys for the three draws: res_tok and bonus_tok are
        # mutually exclusive today (scalar run == k selects exactly one),
        # but sharing a key would silently correlate them if boundary
        # selection ever became per-row.
        rng, akey, bkey, ckey = jax.random.split(rng, 4)
        logp_d = jnp.take_along_axis(
            logp[:, :k, :], drafted[:, :, None], axis=2
        )[..., 0]  # (B, k)
        logq_d = jnp.take_along_axis(
            logq, drafted[:, :, None], axis=2
        )[..., 0]  # (B, k)
        u = jax.random.uniform(akey, (batch, k))
        accept = u < jnp.exp(jnp.minimum(logp_d - logq_d, 0.0))
        run = jnp.min(
            jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1)
        )  # min accepted prefix across the batch, 0..k

        # Boundary token at position `run` (0-indexed in the slab's k+1
        # outputs): accepted rows keep their draft; rejected rows sample
        # the normalised residual max(p - q, 0) — exactly the Leviathan
        # correction.  On a full accept (run == k) everyone samples the
        # bonus from p_{k+1}; the residual branch is never selected there.
        p_bnd = jnp.exp(
            jax.lax.dynamic_slice(
                logp, (0, run, 0), (batch, 1, vocab)
            )[:, 0, :]
        )
        q_bnd = jnp.exp(
            jax.lax.dynamic_slice(
                logq, (0, jnp.minimum(run, k - 1), 0), (batch, 1, vocab)
            )[:, 0, :]
        )
        residual = jnp.maximum(p_bnd - q_bnd, 0.0)
        # All-zero residual is possible only through fp rounding (p <= q
        # everywhere yet the draft got rejected); fall back to p.
        res_mass = jnp.sum(residual, axis=-1, keepdims=True)
        residual = jnp.where(res_mass > 0, residual / res_mass, p_bnd)
        res_tok = jax.random.categorical(
            bkey, jnp.log(jnp.maximum(residual, 1e-37)), axis=-1
        ).astype(jnp.int32)
        bonus_tok = jax.random.categorical(
            ckey, jnp.log(jnp.maximum(p_bnd, 1e-37)), axis=-1
        ).astype(jnp.int32)
        accept_bnd = jnp.take_along_axis(
            accept, jnp.full((batch, 1), jnp.minimum(run, k - 1)), axis=1
        )[:, 0]
        drafted_bnd = jnp.take_along_axis(
            drafted, jnp.full((batch, 1), jnp.minimum(run, k - 1)), axis=1
        )[:, 0]
        boundary = jnp.where(
            run == k,
            bonus_tok,
            jnp.where(accept_bnd, drafted_bnd, res_tok),
        )

        commit = run + 1
        padded = jnp.concatenate(
            [drafted, jnp.zeros((batch, 1), jnp.int32)], axis=1
        )
        idx = jnp.arange(k + 1)[None, :]
        merged = jnp.where(
            idx < run, padded,
            jnp.where(idx == run, boundary[:, None], padded),
        )
        buffer = jax.lax.dynamic_update_slice(buffer, merged, (0, length))
        return (buffer, n_generated + commit, t_cache, d_cache, rounds + 1, rng)

    def cond(carry):
        return carry[1] < max_new_tokens

    buffer, _, _, _, rounds, _ = jax.lax.while_loop(
        cond,
        round_body,
        (buffer, jnp.ones((), jnp.int32), t_cache, d_cache,
         jnp.zeros((), jnp.int32), rng),
    )
    out = jax.lax.dynamic_slice(buffer, (0, 0), (batch, total))
    return (out, {"rounds": rounds}) if return_stats else out


def _spec_sample_jit(target_model, draft_model, max_new_tokens, draft_len,
                     temperature, top_k, top_p, return_stats):
    def make():
        def run(target_params, draft_params, prompt, rng):
            return _speculative_sample_traced(
                target_model, target_params, draft_model, draft_params,
                prompt, max_new_tokens, draft_len, temperature, rng,
                top_k, top_p, return_stats,
            )

        return run

    return cached_jit(
        ("spec_sample", target_model, draft_model, max_new_tokens,
         draft_len, temperature, top_k, top_p, return_stats),
        make,
    )


def speculative_sample(
    target_model: TransformerLM,
    target_params: Any,
    draft_model: TransformerLM,
    draft_params: Any,
    prompt: jax.Array,
    max_new_tokens: int,
    draft_len: int = 4,
    temperature: float = 1.0,
    rng: jax.Array | None = None,
    top_k: int | None = None,
    top_p: float | None = None,
    return_stats: bool = False,
):
    """Jit-cached wrapper; semantics in `_speculative_sample_traced`."""
    if max_new_tokens <= 0 or rng is None:
        # Identity path, and the traced body's own "sampling requires
        # rng"-style validation, stay eager.
        return _speculative_sample_traced(
            target_model, target_params, draft_model, draft_params,
            prompt, max_new_tokens, draft_len, temperature, rng,
            top_k, top_p, return_stats,
        )
    fn = _spec_sample_jit(
        target_model, draft_model, int(max_new_tokens), int(draft_len),
        float(temperature), top_k, top_p, bool(return_stats),
    )
    return fn(target_params, draft_params, jnp.asarray(prompt), rng)
