"""Model zoo for the BASELINE configs.

The reference ships no models (its ML coverage is one sklearn SVM inside a
functional test, ``tests/functional_tests/svm_workflow.py``); these are the
electron payloads the TPU north star names: the MNIST CNN for the
data-parallel v5e-8 config and a GPT-style 125M LM for the multi-host
pretrain config, both written mesh-first so the same code spans one chip to
a pod.
"""

from .beam import beam_search
from .data import synthetic_lm_batch, synthetic_lm_batches
from .decode import generate, inference_params, init_cache
from .moe import MoEMlp, lm_loss_with_moe_aux
from .pipeline_lm import pipeline_lm_forward, pipeline_lm_loss
from .lora import (
    LoRATrainState,
    add_lora,
    lora_mask,
    lora_optimizer,
    lora_train_params,
    make_lora_train_state,
    make_lora_train_step,
    merge_lora,
    quantize_then_lora,
)
from .quant import QuantDenseGeneral, quantize_lm
from .serve import continuous_generate, step_accounting
from .speculative import speculative_generate, speculative_sample
from .mlp import MLP, MnistCNN, synthetic_mnist
from .transformer import TransformerConfig, TransformerLM, lm_125m_config
from .train import (
    classifier_loss,
    cross_entropy_loss,
    lm_loss,
    make_classifier_train_step,
    make_lm_train_step,
    make_sharded_train_state,
    make_train_step,
)

__all__ = [
    "MLP",
    "MnistCNN",
    "synthetic_mnist",
    "synthetic_lm_batch",
    "synthetic_lm_batches",
    "beam_search",
    "generate",
    "continuous_generate",
    "step_accounting",
    "inference_params",
    "init_cache",
    "MoEMlp",
    "lm_loss_with_moe_aux",
    "pipeline_lm_forward",
    "pipeline_lm_loss",
    "QuantDenseGeneral",
    "quantize_lm",
    "speculative_generate",
    "speculative_sample",
    "LoRATrainState",
    "add_lora",
    "lora_mask",
    "lora_optimizer",
    "lora_train_params",
    "make_lora_train_state",
    "make_lora_train_step",
    "merge_lora",
    "quantize_then_lora",
    "TransformerConfig",
    "TransformerLM",
    "lm_125m_config",
    "cross_entropy_loss",
    "classifier_loss",
    "lm_loss",
    "make_sharded_train_state",
    "make_train_step",
    "make_lm_train_step",
    "make_classifier_train_step",
]
