"""Synthetic LM data: deterministic, learnable token streams.

Zero-egress TPU VMs can't download corpora, and the benchmark/test tiers
measure framework+compute behavior, not tokenization — so like
``synthetic_mnist`` (mlp.py), the LM stream is generated: each next token
follows a fixed affine map of the previous one with a small random-reset
rate.  A model that learns the bigram map drives the loss well below the
uniform-entropy floor quickly, making "loss goes down" a meaningful
assertion at tiny scales.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np

#: the learnable next-token rule: t+1 = (A * t + B) mod vocab
_A, _B = 7, 3


def synthetic_lm_batch(
    batch_size: int,
    seq_len: int,
    vocab_size: int,
    seed: int = 0,
    noise: float = 0.05,
) -> dict[str, np.ndarray]:
    """One ``{"tokens": (B, S) int32}`` batch of the affine-map stream.

    ``noise`` is the per-position probability of a random reset — it keeps
    the stream from collapsing onto one cycle and sets the achievable loss
    floor (≈ ``noise * log(vocab)``).
    """
    rng = np.random.default_rng(seed)
    tokens = np.empty((batch_size, seq_len), np.int64)
    tokens[:, 0] = rng.integers(0, vocab_size, batch_size)
    resets = rng.random((batch_size, seq_len)) < noise
    randoms = rng.integers(0, vocab_size, (batch_size, seq_len))
    for t in range(1, seq_len):
        follow = (tokens[:, t - 1] * _A + _B) % vocab_size
        tokens[:, t] = np.where(resets[:, t], randoms[:, t], follow)
    return {"tokens": tokens.astype(np.int32)}


def synthetic_lm_batches(
    steps: int,
    batch_size: int,
    seq_len: int,
    vocab_size: int,
    seed: int = 0,
    noise: float = 0.05,
) -> Iterator[dict[str, np.ndarray]]:
    """``steps`` deterministic batches (seed advances per step).

    Every pod process generating the same stream sees identical global
    batches — combine with ``parallel.process_local_slice`` so each worker
    feeds only its shard (``parallel.shard_batch_per_process``).  Per-step
    seeds derive through ``SeedSequence((seed, step))`` so no stream batch
    collides with a direct ``synthetic_lm_batch(seed=k)`` eval batch.
    """
    for step in range(steps):
        derived = int(np.random.SeedSequence((seed, step)).generate_state(1)[0])
        yield synthetic_lm_batch(
            batch_size, seq_len, vocab_size, seed=derived, noise=noise
        )
