"""LoRA / QLoRA fine-tuning over the transformer's dense layers.

Low-rank adaptation (Hu et al. 2021): each targeted dense layer learns a
rank-``r`` update ``y += (x @ A) @ B * (alpha / r)`` while the base kernel
stays frozen.  ``B`` initialises to zero, so an adapted model is *exactly*
the base model at step 0.  With ``quantized=True`` the frozen base kernel
is the weight-only int8 form (models/quant.py) — QLoRA: full fine-tuning
quality knobs at int8 serving memory.

TPU notes: the adapter matmuls are rank-``r`` GEMMs that XLA fuses into
the surrounding computation; the frozen int8 base rides the same
in-register-cast path as serving.

Two training styles:

* **Float base** — the standard train step works unchanged with
  :func:`lora_optimizer` (multi_transform routing frozen leaves to
  ``set_to_zero``; do NOT use bare ``optax.masked``, which passes
  unmasked gradients through unchanged and silently un-freezes the
  base)::

      lmodel, lparams = add_lora(model, params, rank=16)
      tx = lora_optimizer(optax.adamw(1e-4), lparams)
      ...train as usual...
      merged = merge_lora(lmodel, lparams)   # plain-model params again

* **int8 base (QLoRA)** — ``jax.grad`` refuses int8 inputs, so the step
  must differentiate only the adapter leaves.  :func:`make_lora_train_step`
  does the split/combine::

      qlmodel, qlparams = quantize_then_lora(model, params, rank=16)
      state = make_lora_train_state(qlparams, optax.adamw(1e-4))
      step = make_lora_train_step(lm_loss, qlmodel.apply)
      state, loss = step(state, batch)
      params = lora_train_params(state)      # full tree for apply/generate
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
from flax import struct

from .quant import _as_tuple


class LoRADenseGeneral(nn.Module):
    """``nn.DenseGeneral`` twin with a trainable low-rank bypass.

    Declares the base layer's own params (``kernel`` [+ ``scale`` when
    ``quantized``]) plus ``lora_a``/``lora_b``, so a pretrained checkpoint
    fills the base leaves 1:1 and the adapters start fresh.
    """

    features: Any
    kernel_axes: Sequence[str]
    rank: int
    alpha: float = 16.0
    axis: Any = -1
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    quantized: bool = False
    kernel_init: Any = nn.initializers.normal(0.02)

    @nn.compact
    def __call__(self, x):
        features = _as_tuple(self.features)
        axis = tuple(a % x.ndim for a in _as_tuple(self.axis))
        contract_shape = tuple(x.shape[a] for a in axis)
        n_in = len(contract_shape)
        kernel_axes = tuple(self.kernel_axes)
        dims = ((axis, tuple(range(n_in))), ((), ()))
        x = x.astype(self.dtype)

        if self.quantized:
            kernel = self.param(
                "kernel",
                nn.with_partitioning(nn.initializers.zeros_init(), kernel_axes),
                contract_shape + features,
                jnp.int8,
            )
            scale = self.param(
                "scale",
                nn.with_partitioning(
                    nn.initializers.ones_init(), kernel_axes[n_in:]
                ),
                features,
                self.param_dtype,
            )
            y = jax.lax.dot_general(x, kernel.astype(self.dtype), dims)
            y = y * scale.astype(self.dtype)
        else:
            kernel = self.param(
                "kernel",
                nn.with_partitioning(self.kernel_init, kernel_axes),
                contract_shape + features,
                self.param_dtype,
            )
            y = jax.lax.dot_general(x, kernel.astype(self.dtype), dims)

        # Adapters: A contracts like the kernel down to rank, B expands to
        # the feature dims.  B starts at zero => adapted == base at step 0.
        lora_a = self.param(
            "lora_a",
            nn.with_partitioning(
                nn.initializers.normal(1.0 / self.rank),
                kernel_axes[:n_in] + (None,),
            ),
            contract_shape + (self.rank,),
            self.param_dtype,
        )
        lora_b = self.param(
            "lora_b",
            nn.with_partitioning(
                nn.initializers.zeros_init(), (None,) + kernel_axes[n_in:]
            ),
            (self.rank,) + features,
            self.param_dtype,
        )
        h = jax.lax.dot_general(x, lora_a.astype(self.dtype), dims)
        update = jax.lax.dot_general(
            h, lora_b.astype(self.dtype), (((h.ndim - 1,), (0,)), ((), ()))
        )
        return y + update * (self.alpha / self.rank)


def add_lora(model, params, rank: int, alpha: float = 16.0):
    """(lora model, lora params) from a trained LM.

    The adapted config swaps targeted denses for :class:`LoRADenseGeneral`
    (``lora_rank``/``lora_alpha``); base leaves copy from ``params``
    (quantizing them first when the source model is already
    ``quantized=True``-shaped is the caller's job — pass a quantized
    model+params pair to get QLoRA), adapters materialise fresh from an
    ``jax.eval_shape`` structure template (no base weights are ever
    re-initialised).  Requires ``scan_layers=False`` like the quant path.
    """
    from .transformer import TransformerLM

    config = model.config
    if config.scan_layers:
        raise ValueError("add_lora requires scan_layers=False")
    if rank < 1:
        raise ValueError(f"rank must be >= 1, got {rank}")
    lmodel = TransformerLM(
        dataclasses.replace(config, lora_rank=rank, lora_alpha=alpha)
    )
    template = unbox_params(
        jax.eval_shape(
            lambda: lmodel.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 1), jnp.int32)
            )["params"]
        )
    )
    base = unbox_params(params)
    root_key = jax.random.PRNGKey(0)
    counter = [0]

    def fresh_adapter(name, shape, dtype):
        counter[0] += 1
        if name == "lora_b":
            return jnp.zeros(shape, dtype)  # => identity at step 0
        return (
            jax.random.normal(jax.random.fold_in(root_key, counter[0]), shape)
            / rank
        ).astype(dtype)

    def fill(template_node, base_node):
        if not isinstance(template_node, dict):
            return base_node
        return {
            key: (
                fresh_adapter(key, template_node[key].shape,
                              template_node[key].dtype)
                if key in ("lora_a", "lora_b")
                else fill(template_node[key], base_node[key])
            )
            for key in template_node
        }

    return lmodel, fill(template, base)


def lora_mask(params) -> Any:
    """Pytree of bools: True on adapter leaves — for ``optax.masked``."""

    def rec(tree, in_adapter):
        if isinstance(tree, dict):
            return {
                key: rec(value, in_adapter or key in ("lora_a", "lora_b"))
                for key, value in tree.items()
            }
        return in_adapter

    return rec(params, False)


def adapter_leaves(params) -> list:
    """The ordered ``lora_a``/``lora_b`` leaves of a LoRA params tree —
    the adapter's portable wire form.

    Flatten order is the tree's canonical key-sorted DFS, which is
    identical across the float and quantized twins of one architecture
    (the extra ``scale`` leaves a quantized base declares are not
    adapter leaves), so a list extracted from a float training tree
    splices into any serving variant of the same geometry — the
    multi-adapter bank (:class:`..serve.ContinuousEngine`) and the CAS
    registry ship exactly this list.
    """
    params = unbox_params(params)
    leaves = jax.tree_util.tree_leaves(params)
    mask = jax.tree_util.tree_leaves(lora_mask(params))
    picked = [leaf for leaf, m in zip(leaves, mask) if m]
    if not picked:
        raise ValueError(
            "params tree has no lora_a/lora_b leaves (not a LoRA tree)"
        )
    return picked


def adapter_digest(leaves) -> str:
    """Content digest of an adapter's ordered leaf list (its CAS/registry
    identity): sha256 over each leaf's shape, dtype, and bytes."""
    import hashlib

    import numpy as np

    h = hashlib.sha256()
    for leaf in leaves:
        arr = np.asarray(leaf)
        h.update(repr((tuple(arr.shape), str(arr.dtype))).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


def lora_optimizer(inner, params):
    """Optax transform training ONLY the adapters; the base is frozen.

    ``optax.multi_transform`` routes adapter leaves to ``inner`` and
    everything else to ``set_to_zero`` — the safe formulation (bare
    ``optax.masked(inner, mask)`` leaves unmasked gradients untouched and
    silently un-freezes the base).
    """
    import optax

    labels = jax.tree_util.tree_map(
        lambda is_adapter: "lora" if is_adapter else "frozen",
        lora_mask(params),
    )
    return optax.multi_transform(
        {"lora": inner, "frozen": optax.set_to_zero()}, labels
    )


def merge_lora(model, params):
    """Fold the adapters into plain dense kernels.

    Returns (plain model, plain params): ``kernel += A @ B * alpha/r``.
    Refuses quantized bases — folding a float update into an int8 kernel
    would requantize; dequantize-merge-requantize explicitly if wanted.
    """
    from .transformer import TransformerLM

    config = model.config
    if config.quantized:
        raise ValueError("merge_lora requires a float base (quantized=False)")
    if not config.lora_rank:
        raise ValueError("model has no adapters (lora_rank=0)")
    scaling = config.lora_alpha / config.lora_rank
    plain = TransformerLM(
        dataclasses.replace(config, lora_rank=0, lora_alpha=16.0)
    )

    def rec(tree):
        if not isinstance(tree, dict):
            return tree
        if "lora_a" in tree:
            a32 = tree["lora_a"].astype(jnp.float32)
            b32 = tree["lora_b"].astype(jnp.float32)
            n_in = a32.ndim - 1
            update = jax.lax.dot_general(
                a32, b32, (((n_in,), (0,)), ((), ()))
            )
            kernel = tree["kernel"]
            merged = (kernel.astype(jnp.float32) + update * scaling).astype(
                kernel.dtype
            )
            return {
                key: (merged if key == "kernel" else value)
                for key, value in tree.items()
                if key not in ("lora_a", "lora_b")
            }
        return {key: rec(value) for key, value in tree.items()}

    return plain, rec(unbox_params(params))


def unbox_params(tree):
    """Strip flax ``Partitioned`` boxes (delegates to the shared helper)."""
    from ..parallel.sharding import unbox

    return unbox(tree)


def quantize_then_lora(model, params, rank: int, alpha: float = 16.0):
    """QLoRA in one call: int8-freeze the base, then attach adapters."""
    from .quant import quantize_lm

    qmodel, qparams = quantize_lm(model, params)
    return add_lora(qmodel, qparams, rank=rank, alpha=alpha)


# --------------------------------------------------------------------- #
# Adapter-only train step (required for QLoRA: jax.grad refuses int8    #
# inputs, so the frozen base must stay outside the differentiated tree) #
# --------------------------------------------------------------------- #


@struct.dataclass
class LoRATrainState:
    """Adapters (trainable), frozen base leaves, and the optimizer state.

    ``mask``/``treedef``/``tx`` are static: the first two record where
    each flattened leaf belongs so :func:`lora_train_params` can
    reassemble the full tree; carrying ``tx`` here means the step always
    updates with the optimizer whose ``opt_state`` it holds (passing a
    second, different tx to the step would silently win otherwise).
    """

    adapters: Any
    frozen: Any
    opt_state: Any
    mask: Any = struct.field(pytree_node=False)
    treedef: Any = struct.field(pytree_node=False)
    tx: Any = struct.field(pytree_node=False)


def _combine(adapters, frozen, mask, treedef):
    it = iter(adapters)
    leaves = [next(it) if m else f for f, m in zip(frozen, mask)]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def make_lora_train_state(params, tx) -> LoRATrainState:
    """Split ``params`` into trainable adapters + frozen base."""
    params = unbox_params(params)
    mask = tuple(jax.tree_util.tree_leaves(lora_mask(params)))
    leaves, treedef = jax.tree_util.tree_flatten(params)
    if not any(mask):
        raise ValueError("params carry no lora_a/lora_b leaves — add_lora first")
    adapters = [leaf for leaf, m in zip(leaves, mask) if m]
    # Frozen slots keep their leaf; adapter slots hold a placeholder that
    # _combine never reads.
    frozen = [None if m else leaf for leaf, m in zip(leaves, mask)]
    return LoRATrainState(
        adapters=adapters,
        frozen=frozen,
        opt_state=tx.init(adapters),
        mask=mask,
        treedef=treedef,
        tx=tx,
    )


def lora_train_params(state: LoRATrainState):
    """The full parameter tree (for apply/generate/merge)."""
    return _combine(state.adapters, state.frozen, state.mask, state.treedef)


def make_lora_train_step(loss_fn, apply_fn):
    """Jitted step differentiating ONLY the adapters.

    ``loss_fn(params, apply_fn, batch) -> scalar`` — same contract as
    ``train.lm_loss``, so the existing losses drop in.  Works for float
    and int8 (QLoRA) bases alike; the frozen leaves enter the forward as
    plain inputs, never as differentiated arguments.  The optimizer is
    the one carried by the state (:func:`make_lora_train_state`).
    """
    import optax

    @jax.jit
    def step(state: LoRATrainState, batch):
        def inner(adapters):
            params = _combine(adapters, state.frozen, state.mask, state.treedef)
            return loss_fn(params, apply_fn, batch)

        loss, grads = jax.value_and_grad(inner)(state.adapters)
        updates, opt_state = state.tx.update(
            grads, state.opt_state, state.adapters
        )
        return (
            state.replace(
                adapters=optax.apply_updates(state.adapters, updates),
                opt_state=opt_state,
            ),
            loss,
        )

    return step
