"""TPU compute ops: fused attention kernels and sequence-parallel attention.

The reference contains no kernels or model code whatsoever (SURVEY §2 —
100% Python control-plane).  These ops are the compute layer the TPU north
star runs inside electrons: Pallas flash attention (FlashAttention-2
forward + backward, GQA, position-vector masking, a shard_map wrapper for
sharded meshes) and ring attention — einsum or flash-kernel block pairs —
for long-context sequence parallelism over the mesh's ``seq`` axis.
"""

from .attention import flash_attention, flash_attention_sharded, mha_reference
from .ring_attention import (
    ring_attention,
    ring_flash_attention,
    sequence_parallel_attention,
    ulysses_attention,
)

__all__ = [
    "flash_attention",
    "flash_attention_sharded",
    "mha_reference",
    "ring_attention",
    "ring_flash_attention",
    "sequence_parallel_attention",
    "ulysses_attention",
]
