"""TPU compute ops: fused attention kernels and sequence-parallel attention.

The reference contains no kernels or model code whatsoever (SURVEY §2 —
100% Python control-plane).  These ops are the compute layer the TPU north
star runs inside electrons: a Pallas flash-attention kernel for the MXU hot
path and a ring-attention implementation for long-context sequence
parallelism over the mesh's ``seq`` axis.
"""

from .attention import flash_attention, mha_reference
from .ring_attention import ring_attention

__all__ = ["flash_attention", "mha_reference", "ring_attention"]
