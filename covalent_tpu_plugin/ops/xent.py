"""Fused (vocab-chunked) softmax cross-entropy: logits never touch HBM.

The standard LM loss path materialises a (tokens, vocab) f32 logits
tensor — at the 125M bench shape (8×1024 tokens, 32k vocab) that is
~1 GB written by the lm_head matmul, re-read by the softmax, and visited
again in the backward, on a chip whose usual bottleneck is exactly that
HBM bandwidth (round-4 step sweep: 51% MFU with every matmul lever
already pulled — the residual gap is loss-side traffic).  The reference
stack has no analog (it runs opaque callables, SURVEY §2); this is a
TPU-first component in the spirit of flash attention applied to the
classifier: stream over vocabulary chunks, keep each (T, chunk) logits
tile in registers/VMEM, and carry only the O(T) online log-sum-exp state
(same rescaling trick as the attention kernels' running softmax).

Forward: one pass over chunks of ``W`` — ``s = x @ W_c`` (bf16 inputs on
the MXU's native path, f32 accumulation), online ``(m, l)`` update, and
the label logit gathered when its chunk flies by.  Backward: recompute
``s`` per chunk (FLOPs for bandwidth, the flash trade), form
``softmax - onehot`` in registers, and accumulate ``dx`` / emit ``dW``
chunks.  Peak live memory is O(T·chunk + T·d) instead of O(T·V).

``jax.grad`` composes through the ``custom_vjp``; under ``shard_map`` /
pjit the matmuls shard like any dense layer (vocab axis on the chunked
dimension).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _chunks(vocab: int, chunk: int) -> int:
    if vocab % chunk:
        raise ValueError(
            f"vocab size {vocab} must be divisible by chunk {chunk}"
        )
    return vocab // chunk


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_cross_entropy(x, w, labels, chunk: int = 8192):
    """Mean cross-entropy of ``softmax(x @ w)`` against integer labels.

    ``x``: (T, d) features (bf16 on TPU), ``w``: (d, V) lm_head kernel,
    ``labels``: (T,) int32.  Bit-for-bit it matches a bf16-input,
    f32-accumulated logits matmul followed by a stable log-softmax — NOT
    the f32-input matmul path (which is the point: that path runs at
    half MXU rate and writes the full logits tensor).
    """
    loss, _ = _fused_xent_fwd(x, w, labels, chunk)
    return loss


def _logits_chunk(x, w, j, chunk):
    wc = jax.lax.dynamic_slice_in_dim(w, j * chunk, chunk, axis=1)
    return jax.lax.dot_general(
        x, wc.astype(x.dtype),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ), wc


def _fused_xent_fwd(x, w, labels, chunk):
    tokens = x.shape[0]
    n = _chunks(w.shape[1], chunk)
    labels = labels.astype(jnp.int32)

    def body(carry, j):
        m, l, lab = carry
        s, _ = _logits_chunk(x, w, j, chunk)  # (T, chunk) f32
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        l = l * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(s - m_new[:, None]), axis=-1
        )
        idx = labels - j * chunk
        in_chunk = (idx >= 0) & (idx < chunk)
        got = jnp.take_along_axis(
            s, jnp.clip(idx, 0, chunk - 1)[:, None], axis=1
        )[:, 0]
        lab = jnp.where(in_chunk, got, lab)
        return (m_new, l, lab), None

    init = (
        jnp.full((tokens,), -jnp.inf, jnp.float32),
        jnp.zeros((tokens,), jnp.float32),
        jnp.zeros((tokens,), jnp.float32),
    )
    (m, l, lab), _ = jax.lax.scan(body, init, jnp.arange(n))
    lse = m + jnp.log(l)
    loss = jnp.mean(lse - lab)
    return loss, (x, w, labels, lse)


def _fused_xent_bwd(chunk, res, g):
    x, w, labels, lse = res
    tokens = x.shape[0]
    n = _chunks(w.shape[1], chunk)
    coef = (g / tokens).astype(jnp.float32)
    cols = jnp.arange(chunk)[None, :]

    def body(dx, j):
        s, wc = _logits_chunk(x, w, j, chunk)
        p = jnp.exp(s - lse[:, None])  # softmax chunk, recomputed
        idx = (labels - j * chunk)[:, None]
        p = p - (cols == idx).astype(jnp.float32)  # subtract onehot
        dl = (p * coef).astype(x.dtype)  # (T, chunk) back on the MXU path
        dx = dx + jax.lax.dot_general(
            dl, wc.astype(x.dtype),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dwc = jax.lax.dot_general(
            x, dl,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dx, dwc.astype(w.dtype)

    dx, dw_chunks = jax.lax.scan(
        body, jnp.zeros(x.shape, jnp.float32), jnp.arange(n)
    )
    # (n, d, chunk) -> (d, n*chunk) = (d, V): column j*chunk+c is chunk
    # j's column c, which is exactly the reshape of the moved axis.
    dw = jnp.moveaxis(dw_chunks, 0, 1).reshape(w.shape)
    d_labels = np.zeros(labels.shape, jax.dtypes.float0)
    return dx.astype(x.dtype), dw, d_labels


fused_cross_entropy.defvjp(_fused_xent_fwd, _fused_xent_bwd)
