"""Ring attention: sequence/context parallelism over the mesh ``seq`` axis.

Long-context scaling the TPU-native way: Q, K, V are sharded along the
sequence dimension across devices; each device keeps its query shard
resident while K/V shards rotate around the ring via ``lax.ppermute`` (one
ICI hop per step).  Partial attention results merge with the same online
softmax used by the flash kernel, so the full S×S score matrix never exists
on any one chip and per-device memory is O(S/n · S/n) per step.

Run inside ``shard_map`` over a mesh with a ``seq`` axis — see
``sequence_parallel_attention`` for the wrapped entry point.  The loop is a
``lax.scan`` (not fori) so reverse-mode autodiff works for training.

The reference has no model/sequence scaling at all (SURVEY §5 "long-context
— ABSENT"); this module is a new capability mandated by the TPU north star.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.collectives import ring_permute

_NEG_INF = -1e30


def _block_attend(q, k, v, q_offset, k_offset, scale, causal):
    """Score one (local-q, rotating-k) block pair; return (m, l, o) partials.

    Shapes: q (B,H,Sq,D), k/v (B,H,Sk,D).  Matmul inputs stay in the input
    dtype (bf16 on TPU — the MXU's native path; casting to f32 first costs
    3-4x, same lesson as the flash kernel) with f32 accumulation; the
    softmax statistics are f32 throughout.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        s_q, s_k = q.shape[2], k.shape[2]
        qi = q_offset + lax.broadcasted_iota(jnp.int32, (s_q, s_k), 0)
        ki = k_offset + lax.broadcasted_iota(jnp.int32, (s_q, s_k), 1)
        mask = qi >= ki
        s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)  # (B,H,Sq,1)
    p = jnp.exp(s - m)
    if causal:
        p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return m, l, o


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "seq",
    causal: bool = True,
    scale: float | None = None,
) -> jax.Array:
    """Per-shard body: call under ``shard_map`` with seq-sharded (B,H,S/n,D).

    Step ``t`` holds the K/V shard that originated on device
    ``(my_index - t) mod n``; after scoring, the shard is passed to the next
    device in the ring.
    """
    n = lax.axis_size(axis_name)
    my_index = lax.axis_index(axis_name)
    seq_local = q.shape[2]
    head_dim = q.shape[3]
    scale = head_dim**-0.5 if scale is None else scale
    q_offset = my_index * seq_local

    def step(carry, t):
        m_prev, l_prev, acc_prev, k_cur, v_cur = carry
        src = jnp.mod(my_index - t, n)
        k_offset = src * seq_local

        def attend(_):
            return _block_attend(
                q, k_cur, v_cur, q_offset, k_offset, scale, causal
            )

        if causal:
            # A strictly-future K/V shard is fully masked: skip its matmuls.
            # The ring is lockstep (every step ends at a ppermute), so this
            # saves FLOPs/energy on the skipping devices, not wall-clock —
            # latency stays bound by the device still attending.  Balanced
            # wall-clock would need striped/zigzag sequence sharding; the
            # zero partials merge as a no-op (exp(-inf - m) == 0).
            def skip(_):
                stat_shape = q.shape[:3] + (1,)
                return (
                    jnp.full(stat_shape, _NEG_INF, jnp.float32),
                    jnp.zeros(stat_shape, jnp.float32),
                    jnp.zeros(q.shape, jnp.float32),
                )

            needed = k_offset <= q_offset + seq_local - 1
            m_blk, l_blk, o_blk = lax.cond(needed, attend, skip, None)
        else:
            m_blk, l_blk, o_blk = attend(None)
        m_new = jnp.maximum(m_prev, m_blk)
        alpha_prev = jnp.exp(m_prev - m_new)
        alpha_blk = jnp.exp(m_blk - m_new)
        l_new = l_prev * alpha_prev + l_blk * alpha_blk
        acc_new = acc_prev * alpha_prev + o_blk * alpha_blk
        # Rotate K/V one hop around the ring (skipped result unused on the
        # last step but keeps the scan body uniform; XLA overlaps the
        # ppermute with the next step's einsum).
        k_next = ring_permute(k_cur, axis_name, shift=1)
        v_next = ring_permute(v_cur, axis_name, shift=1)
        return (m_new, l_new, acc_new, k_next, v_next), ()

    shape = q.shape[:3] + (1,)
    m0 = jnp.full(shape, _NEG_INF, jnp.float32)
    l0 = jnp.zeros(shape, jnp.float32)
    acc0 = jnp.zeros(q.shape, jnp.float32)
    (m, l, acc, _, _), _ = lax.scan(
        step, (m0, l0, acc0, k, v), jnp.arange(n)
    )
    return (acc / jnp.maximum(l, 1e-37)).astype(q.dtype)


def sequence_parallel_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    causal: bool = True,
    axis_name: str = "seq",
    batch_axes: tuple[str, ...] = ("data", "fsdp"),
    head_axis: str | None = "tensor",
) -> jax.Array:
    """Global entry: (B, H, S, D) arrays -> ring attention over ``mesh``.

    Batch shards over the data axes, heads over tensor, sequence around the
    ring — composing context parallelism with DP/TP in one shard_map.
    """
    spec = P(batch_axes, head_axis, axis_name, None)
    ring = functools.partial(ring_attention, axis_name=axis_name, causal=causal)
    return jax.shard_map(
        ring,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)
