"""Ring attention: sequence/context parallelism over the mesh ``seq`` axis.

Long-context scaling the TPU-native way: Q, K, V are sharded along the
sequence dimension across devices; each device keeps its query shard
resident while K/V shards rotate around the ring via ``lax.ppermute`` (one
ICI hop per step).  Partial attention results merge with the same online
softmax used by the flash kernel, so the full S×S score matrix never exists
on any one chip and per-device memory is O(S/n · S/n) per step.

Run inside ``shard_map`` over a mesh with a ``seq`` axis — see
``sequence_parallel_attention`` for the wrapped entry point.  The loop is a
``lax.scan`` (not fori) so reverse-mode autodiff works for training.

The reference has no model/sequence scaling at all (SURVEY §5 "long-context
— ABSENT"); this module is a new capability mandated by the TPU north star.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.collectives import all_to_all, ring_permute
from .attention import _flash_backward, _flash_forward, flash_attention, on_tpu

_NEG_INF = -1e30


def _shard_indices(
    shard: jax.Array, n: int, seq_local: int, zigzag: bool
) -> jax.Array:
    """Global positions of ``shard``'s local rows ((seq_local,) int32)."""
    if zigzag:
        # Device i holds stripes i and 2n-1-i (each seq_local//2 long):
        # the mirror pairing balances causal work across the ring.
        stripe = seq_local // 2
        low = shard * stripe + jnp.arange(stripe, dtype=jnp.int32)
        high = (2 * n - 1 - shard) * stripe + jnp.arange(stripe, dtype=jnp.int32)
        return jnp.concatenate([low, high])
    return shard * seq_local + jnp.arange(seq_local, dtype=jnp.int32)


def _block_attend(q, k, v, q_idx, k_idx, scale, causal, window=None):
    """Score one (local-q, rotating-k) block pair; return (m, l, o) partials.

    Shapes: q (B,H,Sq,D), k/v (B,H,Sk,D); ``q_idx``/``k_idx`` are the
    GLOBAL sequence positions of each local row ((Sq,)/(Sk,) int32) — index
    vectors rather than offsets so non-contiguous (zigzag-striped) layouts
    mask correctly.  ``window`` adds the sliding-band upper edge (row sees
    column iff ``0 <= q - k < window``).  Matmul inputs stay in the input
    dtype (bf16 on TPU — the MXU's native path; casting to f32 first costs
    3-4x, same lesson as the flash kernel) with f32 accumulation; softmax
    statistics are f32.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        mask = q_idx[:, None] >= k_idx[None, :]
        if window is not None:
            mask &= q_idx[:, None] - k_idx[None, :] < window
        s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)  # (B,H,Sq,1)
    p = jnp.exp(s - m)
    if causal:
        p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return m, l, o


def _hop_needed(q_idx, k_idx, window):
    """Whether a (q-shard, k-shard) hop intersects the visible band.

    ``min(k) <= max(q)`` kills hops wholly in the future; with a window,
    ``max(k) > min(q) - window`` kills hops wholly behind the band — the
    shard-level analog of the kernels' ``_band_tile_needed``, exact for
    contiguous layouts and conservative-but-correct for striped ones.
    """
    needed = jnp.min(k_idx) <= jnp.max(q_idx)
    if window is not None:
        needed = jnp.logical_and(
            needed, jnp.max(k_idx) > jnp.min(q_idx) - window
        )
    return needed


def _ring_steps(n: int, seq_local: int, window, zigzag: bool) -> int:
    """Number of ring hops that can carry in-band work.

    Contiguous (non-zigzag) layout with a sliding window: device ``i``'s
    queries span ``[i*L, (i+1)*L)`` and their band reaches back at most
    ``window - 1`` keys, so only the own shard plus the previous
    ``ceil((window-1)/L)`` shards matter — the scan runs
    ``min(n, (window-2)//L + 2)`` steps instead of ``n``, a real
    wall-clock cut (the banded-ring hop saving, VERDICT r2 #3).  Striped
    (zigzag) shards interleave early and late stripes, so every hop may
    carry band work: full ``n`` steps.
    """
    if window is None or zigzag:
        return n
    return max(1, min(n, (window - 2) // seq_local + 2))


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "seq",
    causal: bool = True,
    scale: float | None = None,
    zigzag: bool = False,
    window: int | None = None,
) -> jax.Array:
    """Per-shard body: call under ``shard_map`` with seq-sharded (B,H,S/n,D).

    Step ``t`` holds the K/V shard that originated on device
    ``(my_index - t) mod n``; after scoring, the shard is passed to the next
    device in the ring.  With ``zigzag=True`` the local shard is assumed to
    be the striped layout produced by :func:`stripe_sequence` (device i owns
    stripes i and 2n-1-i), which load-balances causal masking across the
    ring — without it, early-ring devices idle while late ones attend.

    ``window`` masks to the sliding causal band; on the contiguous layout
    the ring then runs only the ``_ring_steps`` hops that can carry band
    work (the banded ring), and hops wholly outside any local band skip
    their matmuls.
    """
    n = lax.axis_size(axis_name)
    my_index = lax.axis_index(axis_name)
    seq_local = q.shape[2]
    head_dim = q.shape[3]
    scale = head_dim**-0.5 if scale is None else scale

    def shard_indices(shard: jax.Array) -> jax.Array:
        return _shard_indices(shard, n, seq_local, zigzag)

    q_idx = shard_indices(my_index)

    def step(carry, t):
        m_prev, l_prev, acc_prev, k_cur, v_cur = carry
        src = jnp.mod(my_index - t, n)
        k_idx = shard_indices(src)

        def attend(_):
            return _block_attend(
                q, k_cur, v_cur, q_idx, k_idx, scale, causal, window
            )

        if causal and (not zigzag or window is not None):
            # A fully-masked K/V shard (strictly future, or — windowed —
            # wholly behind the band): skip its matmuls.  The ring is
            # lockstep (every step ends at a ppermute), so this saves
            # FLOPs/energy on the skipping devices, not wall-clock —
            # latency stays bound by the device still attending.  Zigzag
            # striping is the wall-clock fix for unwindowed causal: every
            # (q-shard, k-shard) pair then carries ~equal causal work, so
            # no step has an idle device (and no pair is fully masked, so
            # no skip applies).  The windowed wall-clock fix is the
            # truncated scan below.
            def skip(_):
                stat_shape = q.shape[:3] + (1,)
                return (
                    jnp.full(stat_shape, _NEG_INF, jnp.float32),
                    jnp.zeros(stat_shape, jnp.float32),
                    jnp.zeros(q.shape, jnp.float32),
                )

            needed = _hop_needed(q_idx, k_idx, window)
            m_blk, l_blk, o_blk = lax.cond(needed, attend, skip, None)
        else:
            m_blk, l_blk, o_blk = attend(None)
        m_new = jnp.maximum(m_prev, m_blk)
        alpha_prev = jnp.exp(m_prev - m_new)
        alpha_blk = jnp.exp(m_blk - m_new)
        l_new = l_prev * alpha_prev + l_blk * alpha_blk
        acc_new = acc_prev * alpha_prev + o_blk * alpha_blk
        # Rotate K/V one hop around the ring (skipped result unused on the
        # last step but keeps the scan body uniform; XLA overlaps the
        # ppermute with the next step's einsum).
        k_next = ring_permute(k_cur, axis_name, shift=1)
        v_next = ring_permute(v_cur, axis_name, shift=1)
        return (m_new, l_new, acc_new, k_next, v_next), ()

    steps = _ring_steps(n, seq_local, window if causal else None, zigzag)
    shape = q.shape[:3] + (1,)
    m0 = jnp.full(shape, _NEG_INF, jnp.float32)
    l0 = jnp.zeros(shape, jnp.float32)
    acc0 = jnp.zeros(q.shape, jnp.float32)
    (m, l, acc, _, _), _ = lax.scan(
        step, (m0, l0, acc0, k, v), jnp.arange(steps)
    )
    return (acc / jnp.maximum(l, 1e-37)).astype(q.dtype)


# --------------------------------------------------------------------- #
# Ring flash: the Pallas kernels do each (q-shard, k-shard) block pair
# --------------------------------------------------------------------- #
#
# The einsum ring above materialises an (S/n x S/n) f32 score block per
# step — fine at moderate lengths, but the per-device memory still grows
# quadratically in the local shard.  The flash ring keeps the kernels'
# O(S/n * D) footprint: the forward merges per-block flash outputs with a
# log-sum-exp running merge, and the backward makes a second ring pass
# calling the FlashAttention-2 kernels per block with the GLOBAL softmax
# statistics (lse, delta) — dk/dv partials rotate around the ring with
# their k/v shards.  Position vectors (attention.py) make the causal mask
# correct for striped/rotated layouts where block offsets mean nothing.


def _ring_flash_fwd_pass(q, k, v, axis_name, causal, zigzag, interpret,
                         window=None):
    n = lax.axis_size(axis_name)
    my_index = lax.axis_index(axis_name)
    seq_local = q.shape[2]
    q_idx = _shard_indices(my_index, n, seq_local, zigzag)
    stat_shape = q.shape[:3] + (1,)

    def step(carry, t):
        o_run, lse_run, k_cur, v_cur = carry
        src = jnp.mod(my_index - t, n)
        k_idx = _shard_indices(src, n, seq_local, zigzag)

        def attend(_):
            # f32 block outputs: the merge below sums one partial per hop
            # and must not pay a bf16 rounding at each one.
            return _flash_forward(
                q, k_cur, v_cur, q_idx, k_idx, causal, None, None, interpret,
                out_dtype=jnp.float32, window=window,
            )

        if causal and (not zigzag or window is not None):
            # A fully-masked K/V shard (strictly future, or wholly behind
            # the band): skip its kernels (the lockstep ring still waits
            # on the ppermute either way).
            def skip(_):
                return (
                    jnp.zeros(q.shape, jnp.float32),
                    jnp.full(stat_shape, _NEG_INF, jnp.float32),
                )

            needed = _hop_needed(q_idx, k_idx, window)
            o_blk, lse_blk = lax.cond(needed, attend, skip, None)
        else:
            o_blk, lse_blk = attend(None)

        # Merge the normalised block output into the running output:
        # out = sum_blk exp(lse_blk - lse_global) * o_blk.  All statistics
        # are finite (_NEG_INF, not -inf), so no NaN guards are needed.
        lse_new = jnp.logaddexp(lse_run, lse_blk)
        w_run = jnp.exp(lse_run - lse_new)
        w_blk = jnp.exp(lse_blk - lse_new)
        o_new = o_run * w_run + o_blk * w_blk
        k_next = ring_permute(k_cur, axis_name, shift=1)
        v_next = ring_permute(v_cur, axis_name, shift=1)
        return (o_new, lse_new, k_next, v_next), ()

    steps = _ring_steps(n, seq_local, window if causal else None, zigzag)
    o0 = jnp.zeros(q.shape, jnp.float32)
    lse0 = jnp.full(stat_shape, _NEG_INF, jnp.float32)
    (o, lse, _, _), _ = lax.scan(step, (o0, lse0, k, v), jnp.arange(steps))
    return o.astype(q.dtype), lse


def _ring_flash_bwd_pass(q, k, v, out, lse, g, axis_name, causal, zigzag,
                         interpret, window=None):
    n = lax.axis_size(axis_name)
    my_index = lax.axis_index(axis_name)
    seq_local = q.shape[2]
    q_idx = _shard_indices(my_index, n, seq_local, zigzag)
    # delta = rowsum(dO * O) is loop-invariant: compute once, not per hop.
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1, keepdims=True
    )

    def step(carry, t):
        dq_acc, k_cur, v_cur, dk_cur, dv_cur = carry
        src = jnp.mod(my_index - t, n)
        k_idx = _shard_indices(src, n, seq_local, zigzag)

        def attend(_):
            # f32 per-hop gradient partials (grad_dtype): n bf16 roundings
            # per accumulator would otherwise stack up around the ring.
            return _flash_backward(
                q, k_cur, v_cur, out, lse, g, q_idx, k_idx, causal, interpret,
                delta=delta, grad_dtype=jnp.float32, window=window,
            )

        if causal and (not zigzag or window is not None):
            def skip(_):
                return (
                    jnp.zeros(q.shape, jnp.float32),
                    jnp.zeros(k.shape, jnp.float32),
                    jnp.zeros(v.shape, jnp.float32),
                )

            needed = _hop_needed(q_idx, k_idx, window)
            dq_blk, dk_blk, dv_blk = lax.cond(needed, attend, skip, None)
        else:
            dq_blk, dk_blk, dv_blk = attend(None)

        dq_acc = dq_acc + dq_blk
        dk_cur = dk_cur + dk_blk
        dv_cur = dv_cur + dv_blk
        # dk/dv partials ride the ring WITH their k/v shards; after n
        # rotations each shard (and its accumulated gradient) is home.
        k_next = ring_permute(k_cur, axis_name, shift=1)
        v_next = ring_permute(v_cur, axis_name, shift=1)
        dk_next = ring_permute(dk_cur, axis_name, shift=1)
        dv_next = ring_permute(dv_cur, axis_name, shift=1)
        return (dq_acc, k_next, v_next, dk_next, dv_next), ()

    steps = _ring_steps(n, seq_local, window if causal else None, zigzag)
    dq0 = jnp.zeros(q.shape, jnp.float32)
    dk0 = jnp.zeros(k.shape, jnp.float32)
    dv0 = jnp.zeros(v.shape, jnp.float32)
    (dq, _, _, dk, dv), _ = lax.scan(
        step, (dq0, k, v, dk0, dv0), jnp.arange(steps)
    )
    if steps < n:
        # The truncated scan leaves each dk/dv partial ``steps`` hops past
        # its home device; one ppermute (a single collective, whatever the
        # shift) re-homes them — still far cheaper than the n - steps
        # skipped kernel hops.
        dk = ring_permute(dk, axis_name, shift=n - steps)
        dv = ring_permute(dv, axis_name, shift=n - steps)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ring_flash(q, k, v, axis_name, causal, zigzag, interpret, window):
    out, _ = _ring_flash_fwd_pass(
        q, k, v, axis_name, causal, zigzag, interpret, window
    )
    return out


def _ring_flash_vjp_fwd(q, k, v, axis_name, causal, zigzag, interpret, window):
    out, lse = _ring_flash_fwd_pass(
        q, k, v, axis_name, causal, zigzag, interpret, window
    )
    return out, (q, k, v, out, lse)


def _ring_flash_vjp_bwd(axis_name, causal, zigzag, interpret, window,
                        residuals, g):
    q, k, v, out, lse = residuals
    return _ring_flash_bwd_pass(
        q, k, v, out, lse, g, axis_name, causal, zigzag, interpret, window
    )


_ring_flash.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)


def ring_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "seq",
    causal: bool = True,
    zigzag: bool = False,
    interpret: bool | None = None,
    window: int | None = None,
) -> jax.Array:
    """Per-shard ring attention through the Pallas flash kernels.

    Same contract as :func:`ring_attention` (call under ``shard_map`` with
    seq-sharded (B, H, S/n, D)), but each (q-shard, k-shard) pair runs the
    flash kernel instead of a dense einsum, so per-device memory stays
    O(S/n · D) at any length, forward AND backward (a second ring pass
    recomputes per-block gradients from the global softmax statistics).
    ``window`` masks to the sliding band and (contiguous layout) truncates
    the ring to the hops that can carry band work.
    """
    if interpret is None:
        interpret = not on_tpu()
    return _ring_flash(q, k, v, axis_name, causal, zigzag, interpret, window)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "seq",
    causal: bool = True,
    window: int | None = None,
    sinks: int = 0,
    interpret: bool | None = None,
) -> jax.Array:
    """Per-shard Ulysses (all-to-all) sequence parallelism.

    Call under ``shard_map`` with seq-sharded (B, H, S/n, D).  Two
    all-to-alls swap shard ownership sequence<->heads: each device runs
    the flash kernel over the FULL sequence for H/n of the heads, then
    swaps back.  Communication is 2 all-to-alls of O(B·H·S·D/n) per
    device (vs the ring's n ppermute hops); because the local attention
    sees the whole sequence with contiguous positions, the banded
    windowed grids AND attention sinks compose unchanged — this is the
    sinks × sequence-parallelism path the rotating ring cannot offer.

    Head divisibility: local heads (H after any tensor sharding) must be
    divisible by the axis size.  GQA kv tensors with fewer heads are
    repeated up to H first — acceptable at Ulysses' communication scale,
    where kv bytes already cross the interconnect.
    """
    n = lax.axis_size(axis_name)
    if n == 1:
        return flash_attention(
            q, k, v, causal=causal, window=window, sinks=sinks,
            interpret=interpret,
        )
    h_q, h_kv = q.shape[1], k.shape[1]
    if h_q % n:
        raise ValueError(
            f"ulysses needs local heads ({h_q}) divisible by the "
            f"'{axis_name}' axis ({n})"
        )
    if h_kv != h_q:
        group = h_q // h_kv
        k = jnp.repeat(k, group, axis=1)
        v = jnp.repeat(v, group, axis=1)
    # (B, H, S/n, D) -> (B, H/n, S, D): heads scatter, sequence gathers.
    q = all_to_all(q, axis_name, split_axis=1, concat_axis=2)
    k = all_to_all(k, axis_name, split_axis=1, concat_axis=2)
    v = all_to_all(v, axis_name, split_axis=1, concat_axis=2)
    out = flash_attention(
        q, k, v, causal=causal, window=window, sinks=sinks,
        interpret=interpret,
    )
    return all_to_all(out, axis_name, split_axis=2, concat_axis=1)


def _stripe_permutation(seq_len: int, n: int) -> jax.Array:
    """Index vector mapping natural order -> zigzag-striped order.

    The sequence splits into 2n stripes; device i's contiguous shard under
    ``P(..., axis_name, ...)`` becomes [stripe i ; stripe 2n-1-i], pairing
    a cheap (early) stripe with an expensive (late) one on every device.
    """
    import numpy as np

    if seq_len % (2 * n):
        raise ValueError(
            f"zigzag striping needs seq_len divisible by 2*n ({2 * n}); "
            f"got {seq_len} — pad the sequence or pass zigzag=False"
        )
    stripe = seq_len // (2 * n)
    order = []
    for device in range(n):
        order.extend(range(device * stripe, (device + 1) * stripe))
        order.extend(range((2 * n - 1 - device) * stripe, (2 * n - device) * stripe))
    return jnp.asarray(np.asarray(order, dtype=np.int32))


def stripe_sequence(x: jax.Array, n: int, axis: int = 2) -> jax.Array:
    """Permute ``axis`` into the zigzag layout for an ``n``-device ring."""
    return jnp.take(x, _stripe_permutation(x.shape[axis], n), axis=axis)


def unstripe_sequence(x: jax.Array, n: int, axis: int = 2) -> jax.Array:
    """Inverse of :func:`stripe_sequence`."""
    perm = _stripe_permutation(x.shape[axis], n)
    return jnp.take(x, jnp.argsort(perm), axis=axis)


def sequence_parallel_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    causal: bool = True,
    axis_name: str = "seq",
    batch_axes: tuple[str, ...] = ("data", "fsdp"),
    head_axis: str | None = "tensor",
    zigzag: bool | None = None,
    impl: str | None = None,
    window: int | None = None,
    sinks: int = 0,
) -> jax.Array:
    """Global entry: (B, H, S, D) arrays -> ring attention over ``mesh``.

    Batch shards over the data axes, heads over tensor, sequence around the
    ring — composing context parallelism with DP/TP in one shard_map.

    ``zigzag`` (default: on for unwindowed causal) permutes the sequence
    into the striped layout before sharding and back after, so causal work
    balances across the ring instead of serialising on the last device; XLA
    lowers the permutes to collective data movement alongside the resharding
    it already performs for ``P(..., seq, ...)``.

    ``window`` masks to the sliding causal band (long-context × sequence
    parallelism — the banded ring).  The default layout is then contiguous,
    NOT zigzag: a band of width ``w`` gives every query the same work
    regardless of position (no causal imbalance to stripe away), and the
    contiguous layout lets the ring truncate to
    ``min(n, ceil((w-1)/(S/n)) + 1)`` hops instead of ``n``
    (``_ring_steps``).  Explicit ``zigzag=True`` still composes with the
    window (full ``n`` hops, positions mask exactly).

    ``impl``: ``"flash"`` runs each (q-shard, k-shard) block pair through
    the Pallas kernels (O(S/n·D) per-device memory, fwd and bwd),
    ``"einsum"`` uses the fused dense block path, ``"ulysses"`` swaps
    shard ownership sequence<->heads with two all-to-alls and runs the
    full-sequence flash kernel on H/n local heads (needs head
    divisibility; the only impl that composes with ``sinks``); default
    auto-selects flash on TPU.
    """
    if window is not None and not causal:
        raise ValueError("window (sliding-window attention) requires causal")
    n = mesh.shape[axis_name]
    if impl is None:
        impl = "flash" if on_tpu() else "einsum"
    if impl not in ("flash", "einsum", "ulysses"):
        raise ValueError(
            f"impl must be 'flash', 'einsum', or 'ulysses', got {impl!r}"
        )
    if sinks and impl != "ulysses":
        raise ValueError(
            "sinks require impl='ulysses' (the rotating ring would need "
            "shard 0's sink slab resident on every hop)"
        )
    spec = P(batch_axes, head_axis, axis_name, None)
    if impl == "ulysses":
        # Ulysses keeps the contiguous layout (full sequence local after
        # the swap): zigzag striping has nothing to balance.
        body = functools.partial(
            ulysses_attention, axis_name=axis_name, causal=causal,
            window=window, sinks=sinks,
        )
        return jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )(q, k, v)
    if zigzag is None:
        zigzag = (
            causal and n > 1 and q.shape[2] % (2 * n) == 0 and window is None
        )
    if zigzag:
        q = stripe_sequence(q, n)
        k = stripe_sequence(k, n)
        v = stripe_sequence(v, n)
    body = ring_flash_attention if impl == "flash" else ring_attention
    ring = functools.partial(
        body, axis_name=axis_name, causal=causal, zigzag=zigzag, window=window
    )
    out = jax.shard_map(
        ring,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)
    if zigzag:
        out = unstripe_sequence(out, n)
    return out
