"""Attention: XLA reference path + Pallas TPU flash-attention kernel.

``mha_reference`` is the semantics oracle — plain einsum attention with a
float32 softmax, fully fused by XLA, O(S^2) memory.  ``flash_attention`` is
the memory-efficient Pallas kernel: query blocks stream over key/value
blocks with an online softmax, so the S×S score matrix never materialises
in HBM (activations stay in VMEM, scores live only as a (BQ, BK) tile).

Kernel layout (per pallas_guide.md):
  grid = (batch, heads, S // BQ); each program owns one query tile and
  fori-loops over key tiles, carrying (running max, running sum, output
  accumulator) in f32.  Causal masking prunes the loop bound so the kernel
  does ~half the work of the dense path.

The backward pass is likewise Pallas (FlashAttention-2 style): the forward
saves only the per-row log-sum-exp (B, H, S, 1) — not the S×S probabilities
— and two backward kernels recompute each probability tile from (q, k, lse)
on the fly: one accumulates dk/dv sweeping query tiles, one accumulates dq
sweeping key tiles.  Backward HBM stays O(S·D), the same as forward, where
the dense path's backward would materialise O(S²) probabilities.

On non-TPU backends the same kernel runs in interpreter mode, which is what
the CPU test tier exercises.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: Finite stand-in for -inf in masked scores (finite so downstream
#: exp/logaddexp arithmetic can never produce NaN).  Public: the model's
#: decode path masks with the same constant.
NEG_INF = -1e30
_NEG_INF = NEG_INF

# MXU-sweep winners on v5e at S=4096 (see flash_attention docstring).
_DEFAULT_BLOCK_Q = 512
_DEFAULT_BLOCK_K = 1024


def _pick_windowed_blocks(seq_len_q: int, seq_len_k: int,
                          window: int) -> tuple[int, int]:
    """Forward-tile winners for the BANDED (windowed) grids, from the v5e
    r4 hardware sweep (benchmarks/WINDOW_SWEEP.md).

    The band run is quantised to whole key tiles, so tile choice trades
    band tightness (smaller BK wastes fewer out-of-band columns) against
    MXU/overhead efficiency (larger tiles amortise better).  On-device
    chained timing (dispatch-noise-free; see WINDOW_SWEEP.md's method
    note) shows (512, 512) winning for w <= 512 and (1024, 1024) for
    wider bands, consistently across S = 4k..16k; the full-attention
    default (512, 1024) gives up 4-15% on banded shapes.  Explicit
    ``block_q``/``block_k`` args always override.
    """
    if window <= 512:
        return 512, 512
    return 1024, 1024


def _gqa_group(q: jax.Array, k: jax.Array) -> int:
    """Query-heads-per-kv-head ratio; validates the GQA head contract."""
    h_q, h_kv = q.shape[1], k.shape[1]
    if h_kv == 0 or h_q % h_kv:
        raise ValueError(
            f"GQA needs query heads ({h_q}) divisible by kv heads ({h_kv})"
        )
    return h_q // h_kv


def on_tpu() -> bool:
    try:
        device = jax.devices()[0]
    except Exception:
        return False
    return "tpu" in (device.platform + " " + getattr(device, "device_kind", "")).lower()


def _band_visible(qpos, kpos, window: int | None, sinks: int = 0):
    """Causal(-band) visibility on broadcastable position grids: row sees
    column iff ``q >= k`` and (windowed) ``q - k < window``, OR — with
    ``sinks`` (StreamingLLM attention sinks) — ``k < sinks`` and
    ``q >= k``.  The ONE definition of the band, shared by every kernel
    and the dense oracle."""
    causal_ok = qpos >= kpos
    if window is None:
        return causal_ok
    in_band = qpos - kpos < window
    if sinks:
        in_band = jnp.logical_or(in_band, kpos < sinks)
    return jnp.logical_and(causal_ok, in_band)


def _band_tile_needed(qpos_tile, kpos_tile, causal: bool, window: int | None,
                      sinks: int = 0):
    """Whether a (query tile, key tile) pair intersects the visible band.

    ``min(k) <= max(q)`` kills tiles wholly in the future; with a window,
    ``max(k) > min(q) - window`` kills tiles wholly behind the band —
    unless the tile holds sink columns (``min(k) < sinks``), which stay
    visible at any distance.  The same bounds serve all three sweeps (for
    dk/dv the roles read swapped but the inequalities are algebraically
    identical).
    """
    needed = True if not causal else (
        jnp.min(kpos_tile) <= jnp.max(qpos_tile)
    )
    if window is not None:
        behind_ok = jnp.max(kpos_tile) > jnp.min(qpos_tile) - window
        if sinks:
            behind_ok = jnp.logical_or(behind_ok, jnp.min(kpos_tile) < sinks)
        needed = jnp.logical_and(needed, behind_ok)
    return needed


def _check_window(window, causal, sinks: int = 0) -> None:
    if sinks:
        if sinks < 0:
            raise ValueError(f"sinks must be >= 0, got {sinks}")
        if window is None:
            raise ValueError("sinks (attention sinks) require a window")
    if window is None:
        return
    if not causal:
        raise ValueError("window (sliding-window attention) requires causal")
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")


# --- Band-only grid (windowed attention, contiguous positions) -----------
#
# With a sliding window the visible band covers only ~S·w of the S² score
# matrix.  The `@pl.when` tile-skip alone saves the MXU work but the grid
# still *visits* (and DMAs) every K/V tile — at S=16k/w=1k that is ~8× of
# wasted HBM traffic (measured: the windowed win saturated near 2× of a
# ~16× opportunity, BENCH_r02).  When positions are the default contiguous
# arange, the tiles a query tile needs are statically a contiguous run of
# ~⌈(BQ+w)/BK⌉+1 key tiles, so the sweep dimension can be shrunk to that
# run with a q-tile-relative index_map.  The index_map clamps to the last
# real tile; the kernel decides liveness from grid ids + static block
# sizes (NOT the clamped DMA index), so a clamped duplicate tile is never
# double-counted.  Striped/ring position vectors fall back to the full
# grid with the @pl.when skip.


def _sink_tiles(sinks: int, block_k: int) -> int:
    """Number of leading key tiles holding sink columns."""
    return -(-sinks // block_k) if sinks else 0


def _banded_n_inner_kt(seq_q: int, seq_k: int, block_q: int, block_k: int,
                       window: int, sinks: int = 0) -> int | None:
    """Static length of the inner key-tile sweep for the banded forward/dq
    grids: a leading run of sink tiles plus the max number of key tiles
    any query tile's band touches (the band run starts after the sink
    run — overlapping tiles are visited once, by the sink run).
    Returns None when that covers the full sweep anyway (no gain)."""
    kt_full = seq_k // block_k
    nst = _sink_tiles(sinks, block_k)
    worst = 0
    for i in range(seq_q // block_q):
        lo = max(nst, (i * block_q - (window - 1)) // block_k)
        hi = min(kt_full - 1, ((i + 1) * block_q - 1) // block_k)
        if hi >= lo:
            worst = max(worst, hi - lo + 1)
    n_inner = nst + worst
    return n_inner if 0 < n_inner < kt_full else None


def _banded_n_inner_qt(seq_q: int, seq_k: int, block_q: int, block_k: int,
                       window: int) -> int | None:
    """Static length of the inner query-tile sweep for the banded dk/dv
    grid: the max number of query tiles any key tile's band touches."""
    qt_full = seq_q // block_q
    worst = 0
    for jk in range(seq_k // block_k):
        lo = (jk * block_k) // block_q
        hi = min(qt_full - 1, ((jk + 1) * block_k - 1 + window - 1) // block_q)
        if hi >= lo:
            worst = max(worst, hi - lo + 1)
    return worst if 0 < worst < qt_full else None


def _band_kt_lo(i, block_q: int, block_k: int, window: int, sinks: int = 0):
    """Traced first key tile of query tile ``i``'s band RUN (contiguous
    pos): with sinks the run starts after the sink tiles, which the
    leading sweep steps already visit."""
    lo = jnp.maximum(i * block_q - (window - 1), 0) // block_k
    nst = _sink_tiles(sinks, block_k)
    return jnp.maximum(lo, nst) if nst else lo


def _band_kt_live(i, jj, block_q: int, block_k: int, window: int,
                  kt_full: int, sinks: int = 0):
    """Whether inner step ``jj`` of query tile ``i`` is a live tile (vs.
    a clamped duplicate past the causal edge).  Steps below the sink-run
    length always map to their own (unclamped) tile, so the position
    check alone is exact for them."""
    nst = _sink_tiles(sinks, block_k)
    hi = jnp.minimum(((i + 1) * block_q - 1) // block_k, kt_full - 1)
    in_band = _band_kt_lo(i, block_q, block_k, window, sinks) + (jj - nst) <= hi
    if not nst:
        return in_band
    return jnp.where(jj < nst, True, in_band)


def _band_qt_lo(jk, block_q: int, block_k: int):
    """Traced first query tile of key tile ``jk``'s band (causal bound)."""
    return (jk * block_k) // block_q


def _band_kt_global(i, jj, block_q: int, block_k: int, window: int,
                    kt_full: int, sinks: int = 0):
    """Global key-tile index of inner step ``jj`` of query tile ``i`` —
    THE geometry both the sweep's index map and the interior test use, so
    a clamp/sink-run change cannot desync them."""
    nst = _sink_tiles(sinks, block_k)
    band_j = jnp.minimum(
        _band_kt_lo(i, block_q, block_k, window, sinks) + (jj - nst),
        kt_full - 1,
    )
    return jnp.where(jj < nst, jj, band_j) if nst else band_j


def _band_qt_global(jk, qq, block_q: int, block_k: int, qt_full: int):
    """Global query-tile index of inner step ``qq`` of key tile ``jk``."""
    return jnp.minimum(_band_qt_lo(jk, block_q, block_k) + qq, qt_full - 1)


def _kt_interior(i, jj, block_q: int, block_k: int, window: int,
                 kt_full: int, sinks: int = 0):
    """Inner step ``jj`` of query tile ``i`` is an INTERIOR tile: every
    (q, k) pair it holds is visible, so the kernel may skip the band mask
    entirely (round-5 per-tile-overhead cut, WINDOW_SWEEP.md: at w=1k the
    measured multiple sat on the 1024-tile geometry ceiling; tighter
    tiles only win if the per-tile VPU work shrinks — interior tiles are
    the dominant per-tile VPU cost once DMA is banded).  Exact only for
    contiguous positions, which is the precondition of the banded grid
    this is used with.  A tile is interior iff it is fully causal
    (``max_k <= min_q``) and fully inside the band (``min_k > max_q -
    window``) or fully inside the sink columns (``max_k < sinks``)."""
    kt_g = _band_kt_global(i, jj, block_q, block_k, window, kt_full, sinks)
    causal_full = (kt_g + 1) * block_k - 1 <= i * block_q
    window_full = kt_g * block_k > (i + 1) * block_q - 1 - window
    if sinks:
        window_full = jnp.logical_or(
            window_full, (kt_g + 1) * block_k <= sinks
        )
    return jnp.logical_and(causal_full, window_full)


def _qt_interior(jk, qq, block_q: int, block_k: int, window: int,
                 qt_full: int):
    """Interior test for the dk/dv sweep (roles swapped: key tile ``jk``
    fixed, inner step ``qq`` walks query tiles).  The banded dk/dv call
    never covers sink columns (the sinks split handles those in a
    separate full sweep), so only the causal and band bounds apply."""
    qt_g = _band_qt_global(jk, qq, block_q, block_k, qt_full)
    causal_full = qt_g * block_q >= (jk + 1) * block_k - 1
    window_full = (qt_g + 1) * block_q - 1 - jk * block_k < window
    return jnp.logical_and(causal_full, window_full)


def _banded_sweep_kt(seq_q: int, seq_k: int, block_q: int, block_k: int,
                     window, enabled: bool, sinks: int = 0):
    """(steps, tile_index_fn, band) for a key-tile inner sweep.

    Banded (a sink-tile run + the band's q-tile-relative clamped run)
    when it helps; otherwise the full sweep with identity indexing and
    ``band=None``.  The ONE constructor for the forward and dq grids, so
    clamp-bound or geometry changes happen in a single place.
    """
    kt_full = seq_k // block_k
    n_inner = (
        _banded_n_inner_kt(seq_q, seq_k, block_q, block_k, window, sinks)
        if enabled else None
    )
    if n_inner is None:
        return kt_full, (lambda i, jj: jj), None
    def tile(i, jj):
        return _band_kt_global(i, jj, block_q, block_k, window, kt_full,
                               sinks)

    return n_inner, tile, (block_q, block_k, kt_full)


def _banded_sweep_qt(seq_q: int, seq_k: int, block_q: int, block_k: int,
                     window, enabled: bool):
    """(steps, tile_index_fn, band) for the dk/dv query-tile inner sweep."""
    qt_full = seq_q // block_q
    n_inner = (
        _banded_n_inner_qt(seq_q, seq_k, block_q, block_k, window)
        if enabled else None
    )
    if n_inner is None:
        return qt_full, (lambda jk, qq: qq), None

    def tile(jk, qq):
        return _band_qt_global(jk, qq, block_q, block_k, qt_full)

    return n_inner, tile, (block_q, block_k, qt_full)


def _band_qt_live(jk, qq, block_q: int, block_k: int, window: int,
                  qt_full: int):
    hi = jnp.minimum(
        ((jk + 1) * block_k - 1 + window - 1) // block_q, qt_full - 1
    )
    return _band_qt_lo(jk, block_q, block_k) + qq <= hi


def mha_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    scale: float | None = None,
    window: int | None = None,
    sinks: int = 0,
) -> jax.Array:
    """Dense multi-head attention oracle.  Shapes: (B, H, S, D).

    Grouped-query attention: ``k``/``v`` may carry fewer heads than ``q``
    (``H_q % H_kv == 0``); each kv head serves a contiguous group of query
    heads, matching the flash kernel's convention.  ``window=w`` masks to
    the sliding causal band: row ``i`` sees columns ``(i-w, i]``;
    ``sinks=k`` (StreamingLLM) keeps the first ``k`` columns visible to
    every row alongside the band.
    """
    _check_window(window, causal, sinks)
    if k.shape[1] != q.shape[1]:
        group = _gqa_group(q, k)
        k = jnp.repeat(k, group, axis=1)
        v = jnp.repeat(v, group, axis=1)
    d = q.shape[-1]
    scale = d**-0.5 if scale is None else scale
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        s_q, s_k = q.shape[2], k.shape[2]
        qi = jax.lax.broadcasted_iota(jnp.int32, (s_q, s_k), 0)
        ki = jax.lax.broadcasted_iota(jnp.int32, (s_q, s_k), 1)
        scores = jnp.where(_band_visible(qi, ki, window, sinks), scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bhqk,bhkd->bhqd", probs.astype(v.dtype), v, preferred_element_type=jnp.float32
    )
    return out.astype(q.dtype)


def _flash_kernel(q_ref, k_ref, v_ref, qpos_ref, kpos_ref, o_ref, lse_ref,
                  m_ref, l_ref, acc_ref,
                  *, causal: bool, scale: float, window: int | None = None,
                  sinks: int = 0, band: tuple[int, int, int] | None = None):
    """One (query tile, key tile) grid cell.

    The key-tile index is the *innermost* grid dimension, so for a fixed
    query tile the kernel runs over key tiles sequentially while the online
    softmax state (running max ``m``, normaliser ``l``, accumulator ``acc``)
    persists in VMEM scratch — only one (BQ, BK) score tile and one K/V tile
    are ever resident, which is what lets sequence length scale far past
    VMEM.  Pallas double-buffers the K/V tile DMAs across grid steps.
    """
    kt = pl.program_id(3)
    num_k_tiles = pl.num_programs(3)

    @pl.when(kt == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # A tile whose every key position is in the future of every query
    # position contributes nothing under causal masking: skip its MXU work.
    # The bound check reads the POSITION tiles, so it is exact for the
    # default contiguous layout (reproducing the classic above-diagonal
    # skip, ~2x fewer ops) and conservative-but-correct for arbitrary
    # ring/striped position vectors.
    needed = _band_tile_needed(
        qpos_ref[:, :], kpos_ref[:, :], causal, window, sinks
    )
    if band is not None:
        # Banded grid: the inner sweep visits only the band's tile run; a
        # step past the causal edge DMA'd a clamped duplicate whose
        # position tile would wrongly read "needed" — liveness must come
        # from grid ids + static geometry, never the DMA'd positions.
        block_q, block_k, kt_full = band
        needed = jnp.logical_and(
            needed,
            _band_kt_live(pl.program_id(2), kt, block_q, block_k, window,
                          kt_full, sinks),
        )

    def _tile_body(masked: bool):
        # Matmul inputs stay in the INPUT dtype (bf16 on TPU) with f32
        # accumulation — casting to f32 first would push the hot matmuls
        # off the MXU's native bf16 path (measured 3-4x slower end to end).
        q = q_ref[0, 0, :, :]
        k_tile = k_ref[0, 0, :, :]
        v_tile = v_ref[0, 0, :, :]

        s = jax.lax.dot_general(
            q, k_tile,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (BQ, BK) f32

        if causal and masked:
            # Masking reads GLOBAL positions — (BQ,1) against (1,BK) —
            # so striped/rotated layouts (ring attention) mask correctly;
            # contiguous arange positions reproduce the classic diagonal.
            mask = _band_visible(qpos_ref[:, :], kpos_ref[:, :], window, sinks)
            s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_ref[:]
        l_prev = l_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        if causal and masked:
            p = jnp.where(mask, p, 0.0)
        m_ref[:] = m_new
        l_ref[:] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v_tile.dtype), v_tile,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * alpha + pv

    if band is None:
        pl.when(needed)(lambda: _tile_body(True))
    else:
        # Two-branch banded cell: INTERIOR tiles (statically fully
        # visible — exact for the contiguous positions the banded grid
        # requires) skip the mask compute and both (BQ, BK) selects; only
        # band-edge tiles pay the masked path.
        interior = jnp.logical_and(needed, _kt_interior(
            pl.program_id(2), kt, block_q, block_k, window, kt_full, sinks
        ))
        pl.when(interior)(lambda: _tile_body(False))
        pl.when(jnp.logical_and(needed, jnp.logical_not(interior)))(
            lambda: _tile_body(True)
        )

    @pl.when(kt == num_k_tiles - 1)
    def _finalise():
        o_ref[0, 0, :, :] = (
            acc_ref[:] / jnp.maximum(l_ref[:], 1e-37)
        ).astype(o_ref.dtype)
        # Per-row log-sum-exp — the only softmax statistic the backward
        # kernels need to recompute any probability tile.  Kept in the
        # (BQ, 1) sublane layout the scratch already uses.
        lse_ref[0, 0, :, :] = m_ref[:] + jnp.log(jnp.maximum(l_ref[:], 1e-37))


def _fit_block(requested: int, seq_len: int) -> int:
    """Largest power-of-two shrink of ``requested`` that divides seq_len.

    Floors at 128 (or the whole sequence when shorter): a length with a
    large odd factor (4098 = 2·3·683) would otherwise silently degrade to
    2-wide tiles — pathologically slow or rejected by Mosaic — where the
    pre-adaptive behavior was a clear "pad the sequence" error.
    """
    block = min(requested, seq_len)
    while block > 1 and seq_len % block:
        block //= 2
    floor = min(128, seq_len)
    if block < floor:
        raise ValueError(
            f"seq_len {seq_len} has no usable tile size (>= {floor}); "
            "pad the sequence to a multiple of 128"
        )
    return block


def _positions_2d(q_positions, k_positions, seq_len_q: int, seq_len_k: int):
    """Normalise optional (S,) position vectors to the kernels' layouts:
    query positions (S,1) — sublanes; key positions (1,S) — lanes."""
    if q_positions is None:
        q_positions = jnp.arange(seq_len_q, dtype=jnp.int32)
    if k_positions is None:
        k_positions = jnp.arange(seq_len_k, dtype=jnp.int32)
    qpos = jnp.asarray(q_positions, jnp.int32).reshape(seq_len_q, 1)
    kpos = jnp.asarray(k_positions, jnp.int32).reshape(1, seq_len_k)
    return qpos, kpos


def _flash_forward(
    q, k, v, q_positions, k_positions, causal: bool,
    block_q: int | None, block_k: int | None, interpret: bool,
    out_dtype=None, window: int | None = None, sinks: int = 0,
):
    batch, heads, seq_len, head_dim = q.shape
    seq_len_k = k.shape[2]
    scale = head_dim**-0.5
    # Default (None) blocks adapt to the sequence: the tuned sweep winners
    # shrink by halving until they divide seq_len, so any even-ish length
    # works out of the box.  EXPLICIT blocks stay strict — a user-chosen
    # tile that doesn't divide is an error, not a silent re-tile.
    # Windowed (banded-grid) calls get their own per-shape winners: the
    # full-attention tiles are measurably wrong for a band (see
    # _pick_windowed_blocks).
    if window is not None and causal:
        win_bq, win_bk = _pick_windowed_blocks(seq_len, seq_len_k, window)
    else:
        win_bq, win_bk = _DEFAULT_BLOCK_Q, _DEFAULT_BLOCK_K
    if block_q is None:
        block_q = _fit_block(win_bq, seq_len)
    else:
        block_q = min(block_q, seq_len)
    if block_k is None:
        block_k = _fit_block(win_bk, seq_len_k)
    else:
        block_k = min(block_k, seq_len_k)
    if seq_len % block_q or seq_len_k % block_k:
        raise ValueError(
            f"seq lengths ({seq_len}, {seq_len_k}) must be divisible by "
            f"block sizes ({block_q}, {block_k}); pad the sequence"
        )

    group = _gqa_group(q, k)
    qpos, kpos = _positions_2d(q_positions, k_positions, seq_len, seq_len_k)
    contiguous = q_positions is None and k_positions is None
    # With sinks the inner sweep is a sink-tile run + the band run (two
    # contiguous runs, visited once each — overlaps fold into the sink run).
    steps, _kj, band = _banded_sweep_kt(
        seq_len, seq_len_k, block_q, block_k, window,
        window is not None and causal and contiguous, sinks,
    )
    grid = (batch, heads, seq_len // block_q, steps)
    qo_spec = pl.BlockSpec(
        (1, 1, block_q, head_dim), lambda b, h, i, j: (b, h, i, 0)
    )
    qpos_spec = pl.BlockSpec((block_q, 1), lambda b, h, i, j: (i, 0))
    lse_spec = pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0))
    # GQA: each query head reads its group's shared kv head (h // group).
    kv_spec = pl.BlockSpec(
        (1, 1, block_k, head_dim),
        lambda b, h, i, j: (b, h // group, _kj(i, j), 0),
    )
    kpos_spec = pl.BlockSpec((1, block_k), lambda b, h, i, j: (0, _kj(i, j)))
    kernel = functools.partial(
        _flash_kernel, causal=causal, scale=scale, window=window,
        sinks=sinks, band=band,
    )
    flops_factor = 0.5 if causal else 1.0
    if window is not None:
        # The band covers ~S*(w+sinks) of the S^2 score matrix; feeding
        # the causal half-estimate to the compiler's cost model would
        # overstate a w<<S kernel by ~S/(2w) and skew latency-hiding.
        flops_factor = min(
            flops_factor, (window + sinks) / max(seq_len_k, 1)
        )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[qo_spec, kv_spec, kv_spec, qpos_spec, kpos_spec],
        out_specs=[qo_spec, lse_spec],
        out_shape=[
            # out_dtype=f32 lets ring callers merge unrounded block partials
            jax.ShapeDtypeStruct(q.shape, out_dtype or q.dtype),
            jax.ShapeDtypeStruct((batch, heads, seq_len, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),        # running max
            pltpu.VMEM((block_q, 1), jnp.float32),        # running sum
            pltpu.VMEM((block_q, head_dim), jnp.float32),  # output accumulator
        ],
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=int(4 * batch * heads * seq_len * seq_len_k * head_dim * flops_factor),
            bytes_accessed=int(4 * batch * heads * seq_len * head_dim * q.dtype.itemsize),
            transcendentals=int(batch * heads * seq_len * seq_len_k * flops_factor),
        ),
    )(q, k, v, qpos, kpos)
    return out, lse


# Backward tile edge (v5e sweep, 2026-07): 1024 beat 512/256 at every
# (S, head_dim) probed — S=2048/4096/8192, d=64/128; see benchmarks/.
# _fit_block halves it to divide shorter or odd sequences.
_DEFAULT_BWD_BLOCK = 1024


def _flash_bwd_dkdv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qpos_ref, kpos_ref,
    dk_ref, dv_ref, dk_acc, dv_acc,
    *, causal: bool, scale: float, window: int | None = None,
    sinks: int = 0, band: tuple[int, int, int] | None = None,
    kt_offset: int = 0
):
    """One (kv head, key tile, group member, query tile) cell of the dk/dv
    sweep, grid (B, H_kv, KT, G, QT).

    The two innermost grid dimensions — query-head-group member and query
    tile — share one (kv head, key tile) output block, so the accumulators
    persist in VMEM scratch across the whole sweep and dk/dv sum over the
    query heads a GQA kv head serves (G = 1 degenerates to plain MHA).  The
    probability tile is recomputed from (q, k, lse) — never read from HBM.
    """
    gi = pl.program_id(3)
    qt = pl.program_id(4)
    num_q_tiles = pl.num_programs(4)
    last_group = pl.num_programs(3) - 1

    @pl.when(jnp.logical_and(gi == 0, qt == 0))
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    # A query tile entirely in the past of this key tile contributes no
    # gradient under causal masking; the position-tile bound check is exact
    # for contiguous layouts and conservative for striped ones.
    needed = _band_tile_needed(
        qpos_ref[:, :], kpos_ref[:, :], causal, window, sinks
    )
    if band is not None:
        # Banded grid: liveness from grid ids + static geometry (clamped
        # duplicate tiles must not double-count) — see forward kernel.
        # kt_offset maps this call's local key-tile ids to global ones
        # (the sinks split runs the banded call on the post-sink tiles).
        block_q, block_k, qt_full = band
        needed = jnp.logical_and(
            needed,
            _band_qt_live(pl.program_id(2) + kt_offset, qt, block_q,
                          block_k, window, qt_full),
        )

    def _tile_body(masked: bool):
        q = q_ref[0, 0, :, :]
        k_tile = k_ref[0, 0, :, :]
        v_tile = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :]
        lse = lse_ref[0, 0, :, :]      # (BQ, 1) f32
        delta = delta_ref[0, 0, :, :]  # (BQ, 1) f32

        s = jax.lax.dot_general(
            q, k_tile,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (BQ, BK) f32
        p = jnp.exp(s - lse)  # exactly the forward's normalised probabilities
        if causal and masked:
            p = jnp.where(
                _band_visible(qpos_ref[:, :], kpos_ref[:, :], window, sinks),
                p, 0.0,
            )

        # dV += P^T dO ; dP = dO V^T ; dS = P*(dP - delta)*scale ; dK += dS^T Q
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v_tile,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * scale
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if band is None:
        pl.when(needed)(lambda: _tile_body(True))
    else:
        # Interior tiles skip the band mask (see forward kernel); the
        # banded dk/dv call never covers sink columns (the sinks split
        # runs those separately), so _qt_interior needs no sinks case.
        interior = jnp.logical_and(needed, _qt_interior(
            pl.program_id(2) + kt_offset, qt, block_q, block_k, window,
            qt_full,
        ))
        pl.when(interior)(lambda: _tile_body(False))
        pl.when(jnp.logical_and(needed, jnp.logical_not(interior)))(
            lambda: _tile_body(True)
        )

    @pl.when(jnp.logical_and(gi == last_group, qt == num_q_tiles - 1))
    def _finalise():
        dk_ref[0, 0, :, :] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qpos_ref, kpos_ref,
    dq_ref, dq_acc,
    *, causal: bool, scale: float, window: int | None = None,
    sinks: int = 0, band: tuple[int, int, int] | None = None
):
    """One (query tile, key tile) cell of the dq sweep (key tiles innermost)."""
    kt = pl.program_id(3)
    num_k_tiles = pl.num_programs(3)

    @pl.when(kt == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    needed = _band_tile_needed(
        qpos_ref[:, :], kpos_ref[:, :], causal, window, sinks
    )
    if band is not None:
        block_q, block_k, kt_full = band
        needed = jnp.logical_and(
            needed,
            _band_kt_live(pl.program_id(2), kt, block_q, block_k, window,
                          kt_full, sinks),
        )

    def _tile_body(masked: bool):
        q = q_ref[0, 0, :, :]
        k_tile = k_ref[0, 0, :, :]
        v_tile = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :]
        lse = lse_ref[0, 0, :, :]
        delta = delta_ref[0, 0, :, :]

        s = jax.lax.dot_general(
            q, k_tile,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        p = jnp.exp(s - lse)
        if causal and masked:
            p = jnp.where(
                _band_visible(qpos_ref[:, :], kpos_ref[:, :], window, sinks),
                p, 0.0,
            )

        dp = jax.lax.dot_general(
            do, v_tile,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * scale
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k_tile.dtype), k_tile,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if band is None:
        pl.when(needed)(lambda: _tile_body(True))
    else:
        # Interior tiles skip the band mask (see forward kernel).
        interior = jnp.logical_and(needed, _kt_interior(
            pl.program_id(2), kt, block_q, block_k, window, kt_full, sinks
        ))
        pl.when(interior)(lambda: _tile_body(False))
        pl.when(jnp.logical_and(needed, jnp.logical_not(interior)))(
            lambda: _tile_body(True)
        )

    @pl.when(kt == num_k_tiles - 1)
    def _finalise():
        dq_ref[0, 0, :, :] = dq_acc[:].astype(dq_ref.dtype)


def _flash_backward(
    q, k, v, out, lse, g, q_positions, k_positions, causal: bool,
    interpret: bool, delta=None, grad_dtype=None, window: int | None = None,
    sinks: int = 0,
):
    """FlashAttention-2 backward: two Pallas sweeps, O(S·D) HBM."""
    batch, heads, seq_len, head_dim = q.shape
    kv_heads = k.shape[1]
    seq_len_k = k.shape[2]
    group = _gqa_group(q, k)
    scale = head_dim**-0.5
    block_q = _fit_block(_DEFAULT_BWD_BLOCK, seq_len)
    block_k = _fit_block(_DEFAULT_BWD_BLOCK, seq_len_k)
    qpos, kpos = _positions_2d(q_positions, k_positions, seq_len, seq_len_k)

    # delta_i = rowsum(dO_i * O_i) — a cheap elementwise reduce XLA fuses;
    # kept (B, H, S, 1) to match the kernels' sublane layout.  Ring callers
    # precompute it once per training step (it is loop-invariant there).
    if delta is None:
        delta = jnp.sum(
            g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1,
            keepdims=True,
        )

    flops_factor = 0.5 if causal else 1.0
    if window is not None:
        # The band covers ~S*(w+sinks) of the S^2 score matrix; feeding
        # the causal half-estimate to the compiler's cost model would
        # overstate a w<<S kernel by ~S/(2w) and skew latency-hiding.
        flops_factor = min(flops_factor, (window + sinks) / max(seq_len_k, 1))
    cost = pl.CostEstimate(
        flops=int(10 * batch * heads * seq_len * seq_len_k * head_dim * flops_factor),
        bytes_accessed=int(8 * batch * heads * seq_len * head_dim * q.dtype.itemsize),
        transcendentals=int(batch * heads * seq_len * seq_len_k * flops_factor),
    )

    contiguous = q_positions is None and k_positions is None
    banded = window is not None and causal and contiguous
    qt_full = seq_len // block_q
    kt_full = seq_len_k // block_k

    # dk/dv sweep — grid (B, H_kv, KT, G, QT): group member + query tile are
    # innermost so one (kv head, key tile) output block accumulates across
    # every query head in its group (see kernel docstring).  With a window
    # the QT sweep shrinks to the band's query-tile run (see forward).
    # With sinks the sweep SPLITS: a sink KEY tile is read by every later
    # query tile (no band run exists for it), so the leading sink tiles
    # get their own full-QT-sweep call and the remaining tiles run the
    # banded grid with a key-tile offset — both calls write disjoint
    # dk/dv slabs that concatenate back to (B, H_kv, S, D).
    def run_dkdv(kt_offset, kt_n, qi, band, n_inner):
        """One dk/dv pallas_call over key tiles [kt_offset, kt_offset+kt_n)."""
        qo_spec_q = pl.BlockSpec(
            (1, 1, block_q, head_dim),
            lambda b, h, i, gi, j: (b, h * group + gi, qi(i, j), 0),
        )
        kv_spec_in = pl.BlockSpec(
            (1, 1, block_k, head_dim),
            lambda b, h, i, gi, j: (b, h, i + kt_offset, 0),
        )
        kv_spec_out = pl.BlockSpec(
            (1, 1, block_k, head_dim), lambda b, h, i, gi, j: (b, h, i, 0)
        )
        stat_spec_q = pl.BlockSpec(
            (1, 1, block_q, 1),
            lambda b, h, i, gi, j: (b, h * group + gi, qi(i, j), 0),
        )
        qpos_spec_q = pl.BlockSpec(
            (block_q, 1), lambda b, h, i, gi, j: (qi(i, j), 0)
        )
        kpos_spec_k = pl.BlockSpec(
            (1, block_k), lambda b, h, i, gi, j: (0, i + kt_offset)
        )
        return pl.pallas_call(
            functools.partial(
                _flash_bwd_dkdv_kernel, causal=causal, scale=scale,
                window=window, sinks=sinks, band=band, kt_offset=kt_offset,
            ),
            grid=(batch, kv_heads, kt_n, group, n_inner),
            in_specs=[qo_spec_q, kv_spec_in, kv_spec_in, qo_spec_q,
                      stat_spec_q, stat_spec_q, qpos_spec_q, kpos_spec_k],
            out_specs=[kv_spec_out, kv_spec_out],
            out_shape=[
                # grad_dtype=f32: ring callers sum one partial per hop and
                # must not pay a bf16 rounding at every hop
                jax.ShapeDtypeStruct(
                    (batch, kv_heads, kt_n * block_k, head_dim),
                    grad_dtype or k.dtype,
                ),
                jax.ShapeDtypeStruct(
                    (batch, kv_heads, kt_n * block_k, head_dim),
                    grad_dtype or v.dtype,
                ),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_k, head_dim), jnp.float32),  # dk acc
                pltpu.VMEM((block_k, head_dim), jnp.float32),  # dv acc
            ],
            interpret=interpret,
            cost_estimate=cost,
        )(q, k, v, g, lse, delta, qpos, kpos)

    nst_bwd = _sink_tiles(sinks, block_k) if (banded and sinks) else 0
    n_inner_rem = (
        _banded_n_inner_qt(seq_len, seq_len_k, block_q, block_k, window)
        if 0 < nst_bwd < kt_full else None
    )
    if n_inner_rem is not None:
        # Sinks split: full sweep over the few sink tiles, banded sweep
        # (global geometry via kt_offset) over everything after them.
        def qi_rem(i, j):
            return jnp.minimum(
                _band_qt_lo(i + nst_bwd, block_q, block_k) + j, qt_full - 1
            )

        dk_s, dv_s = run_dkdv(0, nst_bwd, lambda i, j: j, None, qt_full)
        dk_r, dv_r = run_dkdv(
            nst_bwd, kt_full - nst_bwd, qi_rem,
            (block_q, block_k, qt_full), n_inner_rem,
        )
        dk = jnp.concatenate([dk_s, dk_r], axis=2)
        dv = jnp.concatenate([dv_s, dv_r], axis=2)
    else:
        n_inner_qt, _qi, band_kv = _banded_sweep_qt(
            seq_len, seq_len_k, block_q, block_k, window,
            banded and not sinks,
        )
        dk, dv = run_dkdv(0, kt_full, _qi, band_kv, n_inner_qt)

    # dq sweep — banded exactly like the forward (key tiles innermost).
    n_inner_kt, _kj, band_q = _banded_sweep_kt(
        seq_len, seq_len_k, block_q, block_k, window, banded, sinks
    )

    qo_spec_i = pl.BlockSpec(
        (1, 1, block_q, head_dim), lambda b, h, i, j: (b, h, i, 0)
    )
    kv_spec_j = pl.BlockSpec(
        (1, 1, block_k, head_dim),
        lambda b, h, i, j: (b, h // group, _kj(i, j), 0),
    )
    stat_spec_i = pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0))
    qpos_spec_i = pl.BlockSpec((block_q, 1), lambda b, h, i, j: (i, 0))
    kpos_spec_j = pl.BlockSpec((1, block_k), lambda b, h, i, j: (0, _kj(i, j)))
    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, causal=causal, scale=scale, window=window,
            sinks=sinks, band=band_q,
        ),
        grid=(batch, heads, qt_full, n_inner_kt),
        in_specs=[qo_spec_i, kv_spec_j, kv_spec_j, qo_spec_i, stat_spec_i,
                  stat_spec_i, qpos_spec_i, kpos_spec_j],
        out_specs=qo_spec_i,
        out_shape=jax.ShapeDtypeStruct(q.shape, grad_dtype or q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, head_dim), jnp.float32),  # dq accumulator
        ],
        interpret=interpret,
        cost_estimate=cost,
    )(q, k, v, g, lse, delta, qpos, kpos)
    return dq, dk, dv


def _pos_zero(positions):
    """float0 cotangent for an (integer) position argument, or None."""
    if positions is None:
        return None
    return jnp.zeros(jnp.shape(positions), dtype=jax.dtypes.float0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash(q, k, v, q_positions, k_positions, causal, block_q, block_k,
           interpret, window, sinks):
    out, _ = _flash_forward(
        q, k, v, q_positions, k_positions, causal, block_q, block_k, interpret,
        window=window, sinks=sinks,
    )
    return out


def _flash_fwd(q, k, v, q_positions, k_positions, causal, block_q, block_k,
               interpret, window, sinks):
    out, lse = _flash_forward(
        q, k, v, q_positions, k_positions, causal, block_q, block_k, interpret,
        window=window, sinks=sinks,
    )
    return out, (q, k, v, out, lse, q_positions, k_positions)


def _flash_bwd(causal, block_q, block_k, interpret, window, sinks,
               residuals, g):
    q, k, v, out, lse, q_positions, k_positions = residuals
    dq, dk, dv = _flash_backward(
        q, k, v, out, lse, g, q_positions, k_positions, causal, interpret,
        window=window, sinks=sinks,
    )
    return dq, dk, dv, _pos_zero(q_positions), _pos_zero(k_positions)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    *,
    q_positions: jax.Array | None = None,
    k_positions: jax.Array | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
    window: int | None = None,
    sinks: int = 0,
) -> jax.Array:
    """Flash attention over (B, H, S, D) inputs.

    Grouped-query attention: ``k``/``v`` may have fewer heads than ``q``
    (``H_q % H_kv == 0``); kv head ``i`` serves query heads
    ``[i*G, (i+1)*G)``.  Gradients flow to the true kv shapes (dk/dv sum
    over each group) — no materialised ``repeat``.

    ``q_positions``/``k_positions`` ((S,) int32) override the causal mask's
    notion of position: row ``i`` attends column ``j`` iff
    ``q_positions[i] >= k_positions[j]``.  This is what lets ring attention
    run striped (zigzag) sequence layouts through the same kernels; the
    static above-diagonal tile skip applies only to the default contiguous
    positions.  ``k`` may also have a different sequence length than ``q``
    (ring K/V shards).

    ``interpret=None`` auto-selects: compiled Mosaic kernel on TPU,
    interpreter elsewhere (the CPU-mesh test tier).  Default (None) blocks
    are the MXU-sweep winners on v5e (fwd 512×1024: 16.9× over the fused
    XLA path at S=4096; bwd 1024²: 5.6× at S=4096, 15.7× at S=8192 — see
    benchmarks/ATTENTION_SWEEP.md), auto-shrunk by halving to divide any
    sequence length; explicitly passed blocks must divide the sequence
    exactly.

    ``window=w`` (sliding-window / Mistral-style local attention,
    requires ``causal``) restricts each query to the ``w`` most recent
    positions; with default contiguous positions the grids visit ONLY the
    band's tiles (compute and DMA scale O(S·w) instead of O(S²)).
    ``sinks=k`` (StreamingLLM attention sinks) keeps columns ``< k``
    visible to every row alongside the band; the forward and dq sweeps
    band as a sink-tile run + band run, and the dk/dv sweep splits into
    a full-sweep call over the sink key tiles plus a banded call over
    the rest, so all three sweeps stay O(S·w) with sinks on.
    """
    _check_window(window, causal, sinks)
    if interpret is None:
        interpret = not on_tpu()
    return _flash(
        q, k, v, q_positions, k_positions, causal, block_q, block_k,
        interpret, window, sinks,
    )


def flash_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh,
    causal: bool = True,
    *,
    batch_axes=("data", "fsdp"),
    head_axis: str = "tensor",
    **kwargs,
) -> jax.Array:
    """Flash attention as a shard_map over (batch, heads) mesh axes.

    A ``pallas_call`` is opaque to XLA's sharding propagation: under a
    sharded ``jit`` the bare kernel forces all-gathers of Q/K/V to every
    device (measured 27 gathers in the compiled HLO of one call on a
    2×4 mesh).  Attention is embarrassingly parallel over batch and query
    heads, so this wrapper runs the kernel on each shard's local block
    instead — zero collectives in the forward pass.

    GQA under tensor parallelism: when the head axis divides ``H_kv`` the
    kv tensors shard right along with q (contiguous groups keep the
    q↔kv correspondence); when the head axis is *larger* than ``H_kv``
    (``tp % H_kv == 0``) kv arrives replicated and each shard slices the
    single kv head its query slab attends to.  The shard_map transpose
    rule psums the sliced-kv cotangents automatically in the backward.
    """
    from jax.sharding import PartitionSpec as P

    batch_axes = tuple(a for a in batch_axes if a in mesh.shape)
    if head_axis not in mesh.shape:
        # No head axis on this mesh (e.g. a hand-built data-only Mesh):
        # shard over batch only, heads stay whole on every shard.
        head_axis = None
    tp = mesh.shape[head_axis] if head_axis else 1
    h_q, h_kv = q.shape[1], k.shape[1]
    group = _gqa_group(q, k)
    if h_q % tp:
        raise ValueError(f"query heads {h_q} not divisible by {head_axis}={tp}")
    local_q_heads = h_q // tp

    q_spec = P(batch_axes, head_axis, None, None)
    if h_kv % tp == 0:
        kv_spec = P(batch_axes, head_axis, None, None)
        slice_kv = False
    elif tp % h_kv == 0:
        # More shards than kv heads: replicate kv, slice per shard.
        kv_spec = P(batch_axes, None, None, None)
        slice_kv = True
    else:
        raise ValueError(
            f"kv heads {h_kv} and {head_axis} axis {tp} must divide one way"
        )

    def local_fn(q_l, k_l, v_l):
        if slice_kv:
            shard = jax.lax.axis_index(head_axis)
            kv_head = (shard * local_q_heads) // group
            k_l = jax.lax.dynamic_slice_in_dim(k_l, kv_head, 1, axis=1)
            v_l = jax.lax.dynamic_slice_in_dim(v_l, kv_head, 1, axis=1)
        return flash_attention(q_l, k_l, v_l, causal=causal, **kwargs)

    return jax.shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(q_spec, kv_spec, kv_spec),
        out_specs=q_spec,
        check_vma=False,  # pallas_call defeats the replication checker
    )(q, k, v)
