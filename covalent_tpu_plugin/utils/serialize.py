"""Task/result serialization.

The wire protocol matches the reference: the dispatcher cloudpickles the
``(fn, args, kwargs)`` triple into a function file
(``covalent_ssh_plugin/ssh.py:147-150``) and the remote harness writes a
``(result, exception)`` pickle back (``covalent_ssh_plugin/exec.py:45-46``).
The TPU additions are device-aware: results are materialised to host memory
(``block_until_ready`` + ``device_get``) before pickling, because
``jax.Array`` handles referencing TPU buffers do not survive a pickle
round-trip to another machine.
"""

from __future__ import annotations

import pickle
from pathlib import Path
from typing import Any, Callable

import cloudpickle


def dump_task(
    fn: Callable, args: tuple, kwargs: dict, path: str | Path
) -> None:
    """Stage ``(fn, args, kwargs)`` to ``path`` (reference: ssh.py:147-150)."""
    with open(path, "wb") as f:
        cloudpickle.dump((fn, args, kwargs), f)


def load_result(path: str | Path) -> tuple[Any, BaseException | None]:
    """Unpickle a fetched result file (reference: ssh.py:455-458)."""
    with open(path, "rb") as f:
        return pickle.load(f)
