"""Per-stage wall-clock timing — backward-compatible shim over obs.trace.

``StageTimer`` predates the observability subsystem: it recorded a flat
``{stage: seconds}`` dict per ``TPUExecutor.run()`` that died with the
executor instance.  The span tracer (``covalent_tpu_plugin/obs/trace.py``)
subsumes it — trace/span/parent ids, status, event-stream export, and
per-stage histograms in the metrics registry — so this class is kept only
for existing callers of the old API: each ``stage()`` opens a real span
(named ``timer.<stage>``), and ``summary()``/``total()``/``overhead()``
read back the identical numbers the old implementation produced.
"""

from __future__ import annotations

from contextlib import contextmanager

from ..obs.trace import Span

__all__ = ["StageTimer"]


class StageTimer:
    """Accumulates named stage durations for one executor run.

    Deprecated in favour of :mod:`covalent_tpu_plugin.obs.trace`; each
    timed stage is now a real span so existing callers feed the metrics
    registry and event stream without code changes.
    """

    def __init__(self, root_name: str = "timer") -> None:
        self._root_name = root_name
        # The root is entered immediately (matching the old perf_counter
        # capture in __init__) and closed implicitly by summary()/total()
        # reads — the old API had no explicit end, so the root must not
        # capture the ambient span context (activate=False).
        self._root = Span(root_name, emit=False, activate=False)
        self._root.__enter__()

    @property
    def stages(self) -> dict[str, float]:
        return self._root.stage_durations

    @contextmanager
    def stage(self, name: str):
        with Span(f"{self._root_name}.{name}", parent=self._root):
            yield

    def total(self) -> float:
        return self._root.total()

    def overhead(self, exclude: tuple[str, ...] = ("execute",)) -> float:
        """Dispatch overhead = everything except the task's own runtime."""
        return self._root.overhead(exclude)

    def summary(self) -> dict[str, float]:
        return self._root.summary()
