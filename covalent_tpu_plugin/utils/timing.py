"""Per-stage wall-clock timing for dispatch-overhead accounting.

The reference has no timing capture at all (SURVEY §5: only ``app_log.debug``
breadcrumbs at ``covalent_ssh_plugin/ssh.py:158,382,424,...``).  The TPU
build's north star is <2 s dispatch overhead per electron, so every
``TPUExecutor.run()`` records how long each lifecycle stage took; the bench
harness and tests read these numbers back.
"""

from __future__ import annotations

import time
from contextlib import contextmanager


class StageTimer:
    """Accumulates named stage durations for one executor run."""

    def __init__(self) -> None:
        self.stages: dict[str, float] = {}
        self._t0 = time.perf_counter()

    @contextmanager
    def stage(self, name: str):
        start = time.perf_counter()
        try:
            yield
        finally:
            self.stages[name] = self.stages.get(name, 0.0) + (
                time.perf_counter() - start
            )

    def total(self) -> float:
        return time.perf_counter() - self._t0

    def overhead(self, exclude: tuple[str, ...] = ("execute",)) -> float:
        """Dispatch overhead = everything except the task's own runtime."""
        return sum(v for k, v in self.stages.items() if k not in exclude)

    def summary(self) -> dict[str, float]:
        out = dict(self.stages)
        out["total"] = self.total()
        out["overhead"] = self.overhead()
        return out
