"""Cross-cutting utilities: config, logging, serialization, stage timing.

The reference keeps these as loose globals inside ``ssh.py`` (config at
``covalent_ssh_plugin/ssh.py:31,39-50``, logging at ``ssh.py:36-37``,
serialization at ``ssh.py:28``).  Here they are a proper subpackage so the
transport, executor, and harness layers share one implementation.
"""

from .checkpoint import (
    checkpoint_dir,
    latest_step,
    prune_checkpoints,
    register_snapshot,
    reshard_tree,
    restore_checkpoint,
    resume_state,
    save_checkpoint,
    unregister_snapshot,
)
from .config import get_config, set_config, update_config
from .log import app_log
from .serialize import dump_task, load_result
from .timing import StageTimer

__all__ = [
    "checkpoint_dir",
    "latest_step",
    "prune_checkpoints",
    "register_snapshot",
    "reshard_tree",
    "restore_checkpoint",
    "resume_state",
    "save_checkpoint",
    "unregister_snapshot",
    "get_config",
    "set_config",
    "update_config",
    "app_log",
    "dump_task",
    "load_result",
    "StageTimer",
]
