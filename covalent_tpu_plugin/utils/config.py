"""Config resolution for the TPU executor.

The reference resolves every constructor field through a three-level chain —
explicit argument -> ``get_config("executors.ssh.<key>")`` -> hardcoded
default (``covalent_ssh_plugin/ssh.py:94-124``) — where ``get_config`` reads
Covalent's TOML config.  This module supplies the same ``get_config`` surface:

* if the ``covalent`` package is installed, delegate to its config manager so
  the plugin shares the server's ``[executors.tpu]`` section;
* otherwise read/write a standalone TOML file at
  ``$COVALENT_TPU_CONFIG`` (default ``~/.config/covalent_tpu/config.toml``),
  so the executor behaves identically without a Covalent install.

Keys are dotted paths, e.g. ``get_config("executors.tpu.remote_workdir")``.
"""

from __future__ import annotations

import os
import threading
from pathlib import Path
from typing import Any

try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11
    try:
        import tomli as tomllib  # type: ignore[no-redef]
    except ModuleNotFoundError:  # no TOML parser at all: reads degrade
        tomllib = None  # type: ignore[assignment]

try:  # covered by the stub-covalent interop tier when importable
    from covalent._shared_files.config import get_config as _ct_get_config
    from covalent._shared_files.config import set_config as _ct_set_config

    _HAVE_COVALENT = True
except Exception:
    _HAVE_COVALENT = False

_lock = threading.Lock()
_cache: dict[str, Any] | None = None


def _config_path() -> Path:
    return Path(
        os.environ.get(
            "COVALENT_TPU_CONFIG",
            os.path.join(
                os.environ.get("XDG_CONFIG_HOME", os.path.expanduser("~/.config")),
                "covalent_tpu",
                "config.toml",
            ),
        )
    )


def _load() -> dict[str, Any]:
    global _cache
    if _cache is None:
        path = _config_path()
        if path.is_file() and tomllib is not None:
            with open(path, "rb") as f:
                _cache = tomllib.load(f)
        else:
            if path.is_file():
                import warnings

                warnings.warn(
                    f"no TOML parser available (python < 3.11 without tomli); "
                    f"ignoring config file {path}",
                    RuntimeWarning,
                    stacklevel=2,
                )
            _cache = {}
    return _cache


def _toml_value(value: Any) -> str:
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, (int, float)):
        return repr(value)
    if isinstance(value, (list, tuple)):
        return "[" + ", ".join(_toml_value(v) for v in value) + "]"
    text = str(value).replace("\\", "\\\\").replace('"', '\\"')
    return f'"{text}"'


def _dump_toml(data: dict[str, Any]) -> str:
    """Minimal TOML writer: emits dotted ``[section]`` headers with scalar keys."""
    out: list[str] = []

    def walk(node: dict[str, Any], path: str) -> None:
        scalars = {k: v for k, v in node.items() if not isinstance(v, dict)}
        tables = {k: v for k, v in node.items() if isinstance(v, dict)}
        if scalars:
            if path:
                out.append(f"[{path}]")
            for key, value in scalars.items():
                out.append(f"{key} = {_toml_value(value)}")
            out.append("")
        for key, sub in tables.items():
            walk(sub, f"{path}.{key}" if path else key)

    walk(data, "")
    return "\n".join(out) + ("\n" if out else "")


def _write(data: dict[str, Any]) -> None:
    path = _config_path()
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(_dump_toml(data))


def get_config(key: str, default: Any = None) -> Any:
    """Look up a dotted config key; return ``default`` when unset.

    Mirrors the lookup at ``covalent_ssh_plugin/ssh.py:100-104`` but never
    raises on a missing key — the executor constructor supplies the default.
    """
    if _HAVE_COVALENT:
        try:
            return _ct_get_config(key)
        except Exception:
            return default
    with _lock:
        node: Any = _load()
        for part in key.split("."):
            if not isinstance(node, dict) or part not in node:
                return default
            node = node[part]
        return node


def set_config(key: str, value: Any) -> None:
    """Set a single dotted key and persist it."""
    if _HAVE_COVALENT:
        _ct_set_config({key: value})
        return
    with _lock:
        data = _load()
        node = data
        parts = key.split(".")
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = value
        _write(data)


def update_config(defaults: dict[str, Any], section: str = "executors.tpu") -> None:
    """Merge plugin defaults under ``section`` without clobbering user values.

    This is what Covalent's plugin loader does with
    ``_EXECUTOR_PLUGIN_DEFAULTS`` (``covalent_ssh_plugin/ssh.py:39-50``); the
    standalone path replicates it so a bare install self-registers.
    """
    if _HAVE_COVALENT:
        # Merge into the server's config manager so `executor="tpu"` resolves
        # defaults there; only keys the user hasn't set already.
        updates = {}
        for key, value in defaults.items():
            full_key = f"{section}.{key}"
            try:
                _ct_get_config(full_key)
            except Exception:
                updates[full_key] = value
        if updates:
            _ct_set_config(updates)
        return
    with _lock:
        data = _load()
        node = data
        for part in section.split("."):
            node = node.setdefault(part, {})
        changed = False
        for key, value in defaults.items():
            if key not in node:
                node[key] = value
                changed = True
        # Persist only when a config file already exists (or the user pointed
        # COVALENT_TPU_CONFIG somewhere) — a bare import must not scribble
        # files into the home directory.  The in-memory merge above is what
        # get_config() reads either way.
        if changed and not _HAVE_COVALENT and _config_path().is_file():
            _write(data)


def _reset_cache_for_tests() -> None:
    global _cache
    with _lock:
        _cache = None
