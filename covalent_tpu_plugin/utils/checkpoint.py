"""In-electron checkpoint/resume helpers (SURVEY §5 checkpoint subsystem).

The reference persists nothing mid-task — its only artifact is the final
result pickle (``covalent_ssh_plugin/exec.py:45-46``) — and delegates
anything more to the user.  This module keeps that division of labor but
gives electron bodies a first-class, TPU-correct implementation to call:

* ``checkpoint_dir()`` — the durable per-task location, honoring the
  harness workdir contract (``create_unique_workdir``, reference
  ssh.py:486-491): electrons restarted with the same dispatch/node ids see
  the same directory and can resume.
* ``save_checkpoint`` / ``restore_checkpoint`` / ``latest_step`` — orbax
  when available (the JAX-native, multi-host-safe checkpointer), otherwise
  a pickle fallback so the API works on any worker.  Device arrays are
  materialised to host before the fallback writes, and writes are atomic
  (temp + rename) so a killed task never leaves a torn checkpoint.

Elastic-gang additions (ROADMAP item 1):

* ``register_snapshot`` — a training electron registers a zero-arg hook
  returning ``(train_state_tree, step)``; the *harness* (which never
  imports this package — it finds the module through ``sys.modules``)
  calls :func:`take_snapshot` on its checkpoint interval and on the
  SIGTERM preemption notice, publishing sha256-named bundles into the
  worker's remote CAS.
* ``resume_state`` — the replacement gang's side of the contract: when
  the dispatcher shipped a resume bundle with the retry attempt
  (``COVALENT_TPU_RESUME_CHECKPOINT``), returns ``(step, tree)`` after
  digest verification, optionally resharded onto a new mesh.
* ``reshard_tree`` — maps host arrays saved under an N-worker mesh onto
  an M-worker replacement mesh (elastic re-meshing): ``jax.device_put``
  against the new mesh's shardings, replicated by default.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import re
import sys
import uuid
from pathlib import Path
from typing import Any, Callable

_STEP_RE = re.compile(r"^step_(\d+)$")
_ORBAX: Any = None  # resolved on first use; see _orbax()

#: Environment contract for dispatcher-shipped resume bundles (set by the
#: harness from the retry attempt's task spec).
RESUME_PATH_ENV = "COVALENT_TPU_RESUME_CHECKPOINT"
RESUME_STEP_ENV = "COVALENT_TPU_RESUME_STEP"
RESUME_DIGEST_ENV = "COVALENT_TPU_RESUME_DIGEST"


def _orbax():
    """Lazy orbax resolution: importing it pulls in jax/tensorstore (seconds),
    which the dispatcher's control plane must not pay at package import."""
    global _ORBAX
    if _ORBAX is None:
        try:
            import orbax.checkpoint as ocp

            _ORBAX = ocp
        except Exception:  # pragma: no cover - exercised via fallback tests
            _ORBAX = False
    return _ORBAX or None


def checkpoint_dir(base: str | os.PathLike | None = None) -> Path:
    """The task's durable checkpoint directory (created on first call).

    Defaults to ``<cwd>/checkpoints`` — under the harness workdir contract
    the cwd is the per-task workdir, so a re-dispatched electron with
    ``create_unique_workdir`` resumes from its own prior state.
    """
    path = Path(base) if base is not None else Path.cwd() / "checkpoints"
    path.mkdir(parents=True, exist_ok=True)
    return path


def _to_host(tree: Any) -> Any:
    try:
        import jax

        return jax.tree_util.tree_map(
            lambda x: jax.device_get(x) if hasattr(x, "devices") else x, tree
        )
    except Exception:
        return tree


def _process_index() -> int:
    """jax.process_index() when the data plane is up, else 0 (single host).

    Avoids importing jax (seconds) in tasks that never touched it.
    """
    if "jax" not in sys.modules:
        return 0
    try:
        import jax

        return jax.process_index()
    except Exception:  # pragma: no cover - uninitialised backends
        return 0


def save_checkpoint(
    tree: Any,
    step: int,
    base: str | os.PathLike | None = None,
    *,
    per_process: bool = False,
    keep_n: int | None = None,
) -> Path:
    """Persist ``tree`` for ``step``; returns the checkpoint path.

    Assumes a *replicated* tree in multi-process electrons: process 0 is the
    single writer (matching the harness's result-write contract); other
    processes return immediately.  For genuinely per-process state pass
    ``per_process=True`` with a per-process ``base`` path — every process
    then writes its own checkpoint.
    """
    root = checkpoint_dir(base)
    target = root / f"step_{step}"
    ocp = _orbax()
    # A step saved by one stack (orbax = directory, fallback = file) must
    # not be silently clobbered-or-crashed by the other: availability can
    # differ between save and restore environments.
    if ocp is not None and target.is_file():
        raise RuntimeError(
            f"{target} holds a pickle-format checkpoint but orbax is active; "
            "delete it or restore with the stack that wrote it"
        )
    if ocp is None and target.is_dir():
        raise RuntimeError(
            f"{target} holds an orbax (directory) checkpoint but orbax is "
            "unavailable; install orbax or delete the old step"
        )
    if not per_process and _process_index() != 0:
        return target
    if ocp is not None:
        checkpointer = ocp.PyTreeCheckpointer()
        checkpointer.save(target.resolve(), _to_host(tree), force=True)
        if keep_n:
            prune_checkpoints(base, keep_n)
        return target
    # Unique temp per writer: concurrent savers of the same step (replicated
    # multi-process electrons on a shared filesystem) must never interleave
    # bytes into one file before the atomic rename.
    tmp = root / f".tmp_step_{step}.{os.getpid()}.{uuid.uuid4().hex[:8]}"
    with open(tmp, "wb") as f:
        pickle.dump(_to_host(tree), f)
    os.replace(tmp, target)
    if keep_n:
        prune_checkpoints(base, keep_n)
    return target


def prune_checkpoints(
    base: str | os.PathLike | None = None, keep_n: int = 1
) -> list[int]:
    """Drop all but the newest ``keep_n`` saved steps; returns the steps
    removed.  Interrupted saves (``.tmp_*`` files) never match the step
    pattern, so they are invisible to :func:`latest_step` by construction —
    this bounds the *completed* history so checkpoint dirs stop growing
    unbounded under interval checkpointing."""
    root = checkpoint_dir(base)
    keep_n = max(1, int(keep_n))
    steps = sorted(
        (
            (int(m.group(1)), p)
            for p in root.iterdir()
            if (m := _STEP_RE.match(p.name))
        ),
        reverse=True,
    )
    removed: list[int] = []
    for step, path in steps[keep_n:]:
        try:
            if path.is_dir():
                import shutil

                shutil.rmtree(path)
            else:
                path.unlink()
            removed.append(step)
        except OSError:  # pragma: no cover - concurrent pruner/reader race
            continue
    return removed


def latest_step(base: str | os.PathLike | None = None) -> int | None:
    """Highest step with a saved checkpoint, or None."""
    root = checkpoint_dir(base)
    steps = [
        int(m.group(1))
        for p in root.iterdir()
        if (m := _STEP_RE.match(p.name))
    ]
    return max(steps) if steps else None


def restore_checkpoint(
    step: int | None = None,
    base: str | os.PathLike | None = None,
    template: Any = None,
) -> Any:
    """Load the checkpoint for ``step`` (default: latest).

    ``template`` (an abstract pytree, e.g. from ``jax.eval_shape``) lets
    orbax restore with correct shardings/dtypes; ignored by the fallback.
    Raises FileNotFoundError when nothing has been saved.
    """
    if step is None:
        step = latest_step(base)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {checkpoint_dir(base)}")
    target = checkpoint_dir(base) / f"step_{step}"
    if not target.exists():
        raise FileNotFoundError(f"no checkpoint at {target}")
    ocp = _orbax()
    if ocp is not None and target.is_dir():
        checkpointer = ocp.PyTreeCheckpointer()
        if template is not None:
            return checkpointer.restore(target.resolve(), item=template)
        return checkpointer.restore(target.resolve())
    if target.is_dir():
        raise RuntimeError(
            f"{target} is an orbax (directory) checkpoint but orbax is "
            "unavailable in this environment; install orbax to restore it"
        )
    with open(target, "rb") as f:
        return pickle.load(f)


# --------------------------------------------------------------------------
# Cooperative checkpointing (elastic gangs): the electron side.
#
# The harness process (stdlib-only, never imports this package) reaches the
# registered hook through ``sys.modules["covalent_tpu_plugin.utils.
# checkpoint"]`` — the same rendezvous trick ``_process_index`` uses for
# jax.  An electron that never imports this module simply has no hook, and
# the harness's checkpointer thread idles.
# --------------------------------------------------------------------------

_SNAPSHOT: dict[str, Any] = {"hook": None}


def register_snapshot(hook: Callable[[], tuple[Any, int] | None]) -> None:
    """Register the training electron's train-state snapshot hook.

    ``hook()`` must return ``(tree, step)`` — the current train state (host
    or device arrays; the harness materialises to host) and the step it
    corresponds to — or ``None`` when there is nothing to save yet.  It is
    called from the harness's checkpointer thread on the configured
    interval AND from the SIGTERM preemption handler, concurrently with
    the training loop: return a consistent reference (e.g. the state
    object swapped in whole at each step), not a structure mutated in
    place mid-step.
    """
    if not callable(hook):
        raise TypeError(f"snapshot hook must be callable, got {hook!r}")
    _SNAPSHOT["hook"] = hook


def unregister_snapshot() -> None:
    _SNAPSHOT["hook"] = None


def take_snapshot() -> tuple[Any, int] | None:
    """``(tree, step)`` from the registered hook, or None.  Called by the
    harness checkpointer (via sys.modules); exceptions propagate so the
    harness can count them without this module importing its event sink."""
    hook = _SNAPSHOT["hook"]
    if hook is None:
        return None
    snap = hook()
    if snap is None:
        return None
    tree, step = snap
    return tree, int(step)


def verify_bundle_file(path: str | os.PathLike, digest: str) -> bool:
    """Whether ``path``'s bytes match the sha256 ``digest`` (torn-bundle
    guard shared by the dispatcher's resume discovery and tests)."""
    sha = hashlib.sha256()
    try:
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                sha.update(chunk)
    except OSError:
        return False
    return sha.hexdigest() == digest


def resume_state(
    mesh: Any = None, shardings: Any = None
) -> tuple[int, Any] | None:
    """The dispatcher-shipped resume checkpoint, or None (cold start).

    When the retry driver found a complete checkpoint for this electron's
    lineage, the harness exposes it via ``COVALENT_TPU_RESUME_CHECKPOINT``
    (+ step/digest).  Returns ``(step, tree)`` after verifying the bundle
    bytes against the shipped digest — a torn artifact returns None so the
    electron recomputes instead of restoring garbage.  ``mesh`` (with
    optional ``shardings``) reshards the host tree onto the *current* gang
    via :func:`reshard_tree`, so a checkpoint saved at N workers restores
    on an M-worker replacement.
    """
    path = os.environ.get(RESUME_PATH_ENV, "")
    if not path or not os.path.exists(path):
        return None
    expected = os.environ.get(RESUME_DIGEST_ENV, "")
    if expected and not verify_bundle_file(path, expected):
        print(
            f"resume checkpoint {path} failed digest verification; "
            "recomputing from scratch",
            file=sys.stderr,
        )
        return None
    try:
        import cloudpickle as pickler
    except ImportError:  # pragma: no cover - cloudpickle ships with workers
        pickler = pickle
    with open(path, "rb") as f:
        bundle = pickler.load(f)
    tree = bundle["tree"]
    step = int(bundle["step"])
    if mesh is not None:
        tree = reshard_tree(tree, mesh, shardings=shardings)
    return step, tree


def host_tree(tree: Any) -> Any:
    """Every leaf gathered to host memory (full arrays, any mesh size)."""
    return _to_host(tree)


def reshard_tree(tree: Any, mesh: Any, shardings: Any = None) -> Any:
    """Place a host-array tree onto ``mesh`` (elastic re-meshing).

    A checkpoint bundle holds *full host arrays* (the snapshot path
    gathers before pickling), so restoring onto a replacement gang with a
    different worker/device count is one ``jax.device_put`` per leaf:

    * ``shardings=None`` — replicate every leaf (the train-state default:
      data-parallel replicas all hold full params/opt state).
    * ``shardings`` — a matching pytree of ``PartitionSpec`` (placed on
      ``mesh``) or concrete ``Sharding`` objects per leaf, for sharded
      state; XLA scatters each full host array onto the new mesh.

    Non-array leaves (ints, strings, None) pass through untouched, so a
    mixed train-state dict reshards without ceremony.
    """
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec

    host = _to_host(tree)

    def place(leaf: Any, sharding: Any) -> Any:
        if not isinstance(leaf, (np.ndarray, np.generic)) and not hasattr(
            leaf, "shape"
        ):
            return leaf
        if sharding is None:
            sharding = PartitionSpec()
        if not isinstance(sharding, jax.sharding.Sharding):
            sharding = NamedSharding(mesh, sharding)
        return jax.device_put(leaf, sharding)

    # flatten_up_to (the pjit in_shardings pattern), not tree_map over
    # both trees: PartitionSpec is a tuple subclass, so a naive two-tree
    # map would flatten INTO the spec instead of treating it as a leaf.
    leaves, treedef = jax.tree_util.tree_flatten(host)
    if shardings is None:
        shard_leaves: list[Any] = [None] * len(leaves)
    else:
        shard_leaves = treedef.flatten_up_to(shardings)
    return jax.tree_util.tree_unflatten(
        treedef, [place(l, s) for l, s in zip(leaves, shard_leaves)]
    )
