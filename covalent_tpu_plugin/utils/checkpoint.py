"""In-electron checkpoint/resume helpers (SURVEY §5 checkpoint subsystem).

The reference persists nothing mid-task — its only artifact is the final
result pickle (``covalent_ssh_plugin/exec.py:45-46``) — and delegates
anything more to the user.  This module keeps that division of labor but
gives electron bodies a first-class, TPU-correct implementation to call:

* ``checkpoint_dir()`` — the durable per-task location, honoring the
  harness workdir contract (``create_unique_workdir``, reference
  ssh.py:486-491): electrons restarted with the same dispatch/node ids see
  the same directory and can resume.
* ``save_checkpoint`` / ``restore_checkpoint`` / ``latest_step`` — orbax
  when available (the JAX-native, multi-host-safe checkpointer), otherwise
  a pickle fallback so the API works on any worker.  Device arrays are
  materialised to host before the fallback writes, and writes are atomic
  (temp + rename) so a killed task never leaves a torn checkpoint.
"""

from __future__ import annotations

import os
import pickle
import re
import sys
import uuid
from pathlib import Path
from typing import Any

_STEP_RE = re.compile(r"^step_(\d+)$")
_ORBAX: Any = None  # resolved on first use; see _orbax()


def _orbax():
    """Lazy orbax resolution: importing it pulls in jax/tensorstore (seconds),
    which the dispatcher's control plane must not pay at package import."""
    global _ORBAX
    if _ORBAX is None:
        try:
            import orbax.checkpoint as ocp

            _ORBAX = ocp
        except Exception:  # pragma: no cover - exercised via fallback tests
            _ORBAX = False
    return _ORBAX or None


def checkpoint_dir(base: str | os.PathLike | None = None) -> Path:
    """The task's durable checkpoint directory (created on first call).

    Defaults to ``<cwd>/checkpoints`` — under the harness workdir contract
    the cwd is the per-task workdir, so a re-dispatched electron with
    ``create_unique_workdir`` resumes from its own prior state.
    """
    path = Path(base) if base is not None else Path.cwd() / "checkpoints"
    path.mkdir(parents=True, exist_ok=True)
    return path


def _to_host(tree: Any) -> Any:
    try:
        import jax

        return jax.tree_util.tree_map(
            lambda x: jax.device_get(x) if hasattr(x, "devices") else x, tree
        )
    except Exception:
        return tree


def _process_index() -> int:
    """jax.process_index() when the data plane is up, else 0 (single host).

    Avoids importing jax (seconds) in tasks that never touched it.
    """
    if "jax" not in sys.modules:
        return 0
    try:
        import jax

        return jax.process_index()
    except Exception:  # pragma: no cover - uninitialised backends
        return 0


def save_checkpoint(
    tree: Any,
    step: int,
    base: str | os.PathLike | None = None,
    *,
    per_process: bool = False,
) -> Path:
    """Persist ``tree`` for ``step``; returns the checkpoint path.

    Assumes a *replicated* tree in multi-process electrons: process 0 is the
    single writer (matching the harness's result-write contract); other
    processes return immediately.  For genuinely per-process state pass
    ``per_process=True`` with a per-process ``base`` path — every process
    then writes its own checkpoint.
    """
    root = checkpoint_dir(base)
    target = root / f"step_{step}"
    ocp = _orbax()
    # A step saved by one stack (orbax = directory, fallback = file) must
    # not be silently clobbered-or-crashed by the other: availability can
    # differ between save and restore environments.
    if ocp is not None and target.is_file():
        raise RuntimeError(
            f"{target} holds a pickle-format checkpoint but orbax is active; "
            "delete it or restore with the stack that wrote it"
        )
    if ocp is None and target.is_dir():
        raise RuntimeError(
            f"{target} holds an orbax (directory) checkpoint but orbax is "
            "unavailable; install orbax or delete the old step"
        )
    if not per_process and _process_index() != 0:
        return target
    if ocp is not None:
        checkpointer = ocp.PyTreeCheckpointer()
        checkpointer.save(target.resolve(), _to_host(tree), force=True)
        return target
    # Unique temp per writer: concurrent savers of the same step (replicated
    # multi-process electrons on a shared filesystem) must never interleave
    # bytes into one file before the atomic rename.
    tmp = root / f".tmp_step_{step}.{os.getpid()}.{uuid.uuid4().hex[:8]}"
    with open(tmp, "wb") as f:
        pickle.dump(_to_host(tree), f)
    os.replace(tmp, target)
    return target


def latest_step(base: str | os.PathLike | None = None) -> int | None:
    """Highest step with a saved checkpoint, or None."""
    root = checkpoint_dir(base)
    steps = [
        int(m.group(1))
        for p in root.iterdir()
        if (m := _STEP_RE.match(p.name))
    ]
    return max(steps) if steps else None


def restore_checkpoint(
    step: int | None = None,
    base: str | os.PathLike | None = None,
    template: Any = None,
) -> Any:
    """Load the checkpoint for ``step`` (default: latest).

    ``template`` (an abstract pytree, e.g. from ``jax.eval_shape``) lets
    orbax restore with correct shardings/dtypes; ignored by the fallback.
    Raises FileNotFoundError when nothing has been saved.
    """
    if step is None:
        step = latest_step(base)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {checkpoint_dir(base)}")
    target = checkpoint_dir(base) / f"step_{step}"
    if not target.exists():
        raise FileNotFoundError(f"no checkpoint at {target}")
    ocp = _orbax()
    if ocp is not None and target.is_dir():
        checkpointer = ocp.PyTreeCheckpointer()
        if template is not None:
            return checkpointer.restore(target.resolve(), item=template)
        return checkpointer.restore(target.resolve())
    if target.is_dir():
        raise RuntimeError(
            f"{target} is an orbax (directory) checkpoint but orbax is "
            "unavailable in this environment; install orbax to restore it"
        )
    with open(target, "rb") as f:
        return pickle.load(f)
