"""Framework logger.

The reference borrows Covalent's shared logger
(``covalent_ssh_plugin/ssh.py:30,36-37``).  When the ``covalent`` package is
installed we do the same so log records land in the server's debug log;
otherwise a standard-library logger configured from the environment is used,
keeping the plugin importable standalone.
"""

from __future__ import annotations

import logging
import os

try:  # pragma: no cover - exercised only when covalent is installed
    from covalent._shared_files import logger as _ct_logger

    app_log = _ct_logger.app_log
except Exception:
    app_log = logging.getLogger("covalent_tpu_plugin")
    if not app_log.handlers:
        _handler = logging.StreamHandler()
        _handler.setFormatter(
            logging.Formatter("[%(asctime)s] [%(levelname)s] %(name)s: %(message)s")
        )
        app_log.addHandler(_handler)
    # Validate before setLevel: an invalid value would raise ValueError at
    # import time and take down every `import covalent_tpu_plugin` with it.
    _raw = os.environ.get("COVALENT_TPU_LOG_LEVEL", "WARNING").strip().upper()
    _level = int(_raw) if _raw.isdigit() else logging.getLevelName(_raw)
    if not isinstance(_level, int):
        app_log.setLevel(logging.WARNING)
        app_log.warning(
            "invalid COVALENT_TPU_LOG_LEVEL %r; falling back to WARNING", _raw
        )
    else:
        app_log.setLevel(_level)

__all__ = ["app_log"]
