"""TPUExecutor — dispatch Covalent electrons to Cloud TPU VMs and pod slices.

TPU-native rebuild of the reference ``SSHExecutor``
(``covalent_ssh_plugin/ssh.py:53``).  The lifecycle contract is the same —
validate -> connect -> stage -> upload -> submit -> poll -> fetch -> cleanup
(``ssh.py:466-591``) — but the design diverges where TPU hardware and the
<2 s-overhead target demand it:

* **Multi-worker fan-out.**  A pod slice is N TPU-VM workers that must all
  run one process each (JAX multi-host convention).  Staging/upload/submit
  fan out to every worker concurrently; the harness on each worker calls
  ``jax.distributed.initialize`` so XLA collectives ride ICI/DCN (SURVEY
  §2.4).  Launch is all-or-nothing: if any worker fails to start, the rest
  are killed.
* **Asynchronous submit + real cancel.**  The reference blocks inside
  ``conn.run`` (``ssh.py:383``) and stubs ``cancel``
  (``ssh.py:460-464``); here submit detaches the harness and returns its
  PID, the poller watches for the result file / process death, and
  ``cancel`` kills the remote process group on every worker.
* **Batched pre-flight.**  One compound command replaces the reference's 3
  sequential round-trips (conda check, python check, mkdir —
  ``ssh.py:508-532``).
* **Connection reuse.**  Transports are pooled across electrons instead of a
  fresh handshake per ``run()`` (``ssh.py:497``), and are closed in a
  ``finally`` so the reference's leak on the exception path
  (``ssh.py:581-587``) cannot recur.
* **Robust status probe.**  ``test -f`` exit status instead of the
  reference's string-comparison of ``ls`` output (``ssh.py:402-406``).
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import json
import os
import pickle
import re
import shlex
import time
import weakref
from enum import Enum
from pathlib import Path
from typing import Any, Callable, Sequence

import cloudpickle

from . import harness as _harness_module
from .agent import (
    AGENT_RESTARTS_TOTAL,
    AgentClient,
    AgentError,
    attach_pool_server,
    ensure_agent_binary,
    read_orphan_rendezvous,
    start_pool_server,
)
from .cache import (
    CAS_EVICTIONS_TOTAL,
    RESULT_CACHE_TOTAL,
    CASIndex,
    FnRegistry,
    ResultCache,
    bytes_digest,
    cas_bytes_prune_command,
    cas_path,
    file_digest,
    harness_digest,
)
from .executor_base import RemoteExecutor
from .fleet import journal as journal_mod
from .fleet.health import HEALTH
from .fleet.lease import GangLease
from .obs import events as obs_events
from .obs.flightrec import FLIGHT_RECORDER, ensure_flight_recorder
from .obs.heartbeat import MONITOR, STALLS_TOTAL
from .obs.metrics import REGISTRY
from .obs.opsserver import (
    ensure_ops_server,
    register_profile_provider,
    register_status_provider,
    unregister_profile_provider,
    unregister_status_provider,
)
from .obs.trace import Span, context_of
from .parallel.distributed import coordinator_spec
from .serving.metrics import SERVE_WORKER_SLOTS
from .resilience import (
    TASK_RETRIES_TOTAL,
    CircuitBreakerRegistry,
    Deadline,
    FaultClass,
    RetryPolicy,
    WorkerPreemptedError,
    WorkerStalledError,
    classify_error,
)
from .transport import (
    ChaosPlan,
    ChaosTransport,
    LocalTransport,
    SSHTransport,
    Transport,
    TransportError,
    TransportPool,
    connect_with_retries,
)
from .transport import codec as codec_mod
from .transport.chaos import plan_from_spec
from .utils.config import get_config, update_config
from .utils.log import app_log
from .utils.serialize import dump_task, load_result

# Plugin identity — the hook Covalent's loader keys on (pattern: ssh.py:34).
EXECUTOR_PLUGIN_NAME = "TPUExecutor"

# Defaults merged into the config under [executors.tpu]
# (pattern: _EXECUTOR_PLUGIN_DEFAULTS, ssh.py:39-50).
_EXECUTOR_PLUGIN_DEFAULTS = {
    "username": "",
    "hostname": "",
    "workers": [],
    "tpu_name": "",
    "zone": "",
    "project": "",
    "use_internal_ips": False,
    "ssh_key_file": os.path.join("~", ".ssh", "id_rsa"),
    # "ssh" auto-picks an SSH backend (asyncssh > OpenSSH binaries >
    # vendored minissh); "minissh" pins the vendored pure-python stack
    # (transport/minissh.py); "local" runs workers in-place.
    "transport": "ssh",
    # minissh-backend host-key pin: path to the server's public key for
    # strict checking (asyncssh/openssh pin via ~/.ssh/known_hosts; the
    # transport refuses the combination rather than silently ignoring an
    # explicit pin).
    "known_host_key_file": "",
    "cache_dir": os.path.join("~", ".cache", "covalent-tpu"),
    "python_path": "python3",
    "conda_env": "",
    "remote_cache": ".cache/covalent-tpu",
    "remote_workdir": "covalent_tpu_workdir",
    "create_unique_workdir": False,
    "run_local_on_dispatch_fail": False,
    "poll_freq": 0.5,
    "max_connection_attempts": 5,
    "retry_wait_time": 5.0,
    "do_cleanup": True,
    # Run cleanup as a background task after the result is returned: saves
    # the rm round-trips (~3 ms/electron on the local transport, one SSH
    # round-trip on pods) from the electron's critical path.  Off by
    # default so run() returning implies the workdir contract is settled.
    "defer_cleanup": False,
    "strict_host_keys": True,
    "coordinator_port": 8476,
    "task_timeout": 0.0,
    "task_env": {},
    "use_agent": True,
    # RPC dispatch (ROADMAP item 3): "launch" runs every electron through
    # the process-launch path (harness process per electron); "auto"
    # executes eligible electrons (single-worker, no pip deps/profiling,
    # no chaos plan) by digest on the warm resident runtime instead — ship
    # the cloudpickled function once per connection via the CAS, invoke by
    # digest over the agent channel, stream the result back without
    # touching remote disk; "rpc" pins RPC mode even under a chaos plan
    # (still falling back to launch when no resident runtime exists).
    # COVALENT_TPU_DISPATCH_MODE overrides per process; electron metadata
    # ("dispatch_mode") overrides per electron.
    "dispatch_mode": "launch",
    # Args at or below this many pickled bytes travel inline on the RPC
    # channel; larger args are staged through the CAS (digest-verified
    # remotely) instead.  COVALENT_TPU_RPC_INLINE_MAX overrides.
    "rpc_inline_args_max": 64 * 1024,
    # Level-2 cache (cache.py): memoize completed electron results locally,
    # keyed by (function digest, args digest, executor env fingerprint).
    # Only sound for side-effect-free electrons, hence opt-in; the env var
    # COVALENT_TPU_RESULT_CACHE=1 flips it on process-wide.
    "cache_results": False,
    "result_cache_max_entries": 512,
    "result_cache_max_bytes": 256 * 1024 * 1024,
    # Age bound on remote_cache/cas/ contents, pruned once per connection
    # during pre-flight: dedupable artifacts (harness, repeated fn pickles)
    # stay hot, while one-off payloads from long-gone electrons cannot fill
    # the worker disk.  0 disables pruning.
    "cas_ttl_hours": 168.0,
    # Byte budget for the same CAS dir, enforced oldest-access-first during
    # the per-electron maintenance round trip (the touch keeps hot
    # artifacts at the LRU tail).  The TTL bounds staleness; this bounds
    # SIZE — KV bundles (disaggregated serving) are orders of magnitude
    # larger than fn pickles and can fill a disk well inside the TTL.
    # 0 disables; COVALENT_TPU_CAS_MAX_BYTES overrides per process.
    "cas_max_bytes": 0,
    # NOT jax by default: forking a parent that already imported jax (PJRT
    # plugins register at import) measurably slows TPU backend init in the
    # children; interpreter+sitecustomize startup is the big win anyway.
    "pool_preload": "cloudpickle",
    # Binary agent-channel frames (transport/frames.py): negotiated on the
    # ready-banner handshake; RPC args/results and streamed serve tokens
    # then ride length-prefixed raw-pickle frames (no base64, optional
    # zlib body codec) with invoke micro-batching and token coalescing.
    # Either side declining — COVALENT_TPU_AGENT_FRAMES=0 here, the same
    # kill switch in the worker env, or an old runtime — degrades to the
    # byte-equal JSONL fallback.
    "agent_frames": True,
    # Wire codec (transport/codec.py): "auto" negotiates the best codec
    # both ends support (zstd > zlib > raw) during pre-flight and applies
    # it to staged uploads — same round-trip count, fewer bytes; "zlib"/
    # "zstd" pin one AND additionally compress result downloads (which
    # cost one extra round trip, so they're opt-in); "off" ships raw.
    # COVALENT_TPU_COMPRESS overrides per process.
    "compress": "auto",
    # Bundled staging: pack a worker's missing artifacts (function pickle,
    # harness, spec) into ONE tar shipped with a single put + unpack exec
    # instead of put+publish pairs per artifact.
    "bundle": True,
    # DAG-driven connection prewarm: the workflow runner pre-dials this
    # executor's pooled transports (and starts its agents) while a node's
    # upstream dependencies are still running, so dial latency overlaps
    # upstream compute.  Breaker-gated; disabled automatically under a
    # chaos plan so fault budgets are spent only by real dispatch ops.
    "prewarm": True,
    "profile_dir": "",
    # Resilience layer (resilience.py).  max_task_retries counts full-gang
    # re-submissions after a *transient* failure (channel death, connect/
    # preflight failure, worker death without a result, timeout); user-code
    # exceptions and cancellations are never retried.  0 preserves the
    # single-shot behavior; COVALENT_TPU_TASK_RETRIES overrides per process.
    "max_task_retries": 0,
    "retry_base_delay": 0.25,
    "retry_max_delay": 10.0,
    # Elapsed wall clock after which no NEW attempt starts (sleeps are
    # capped to it; an in-flight attempt finishes); 0 = none.
    "retry_wall_budget": 0.0,
    # Per-worker circuit breaker: open after N consecutive dial/preflight
    # failures, half-open probe after the cooldown.
    "circuit_threshold": 3,
    "circuit_cooldown": 30.0,
    # Fault-injection spec (transport/chaos.py); also COVALENT_TPU_CHAOS.
    # Empty = no chaos wrapper (the production default).
    "chaos": "",
    # Cooperative checkpointing (elastic gangs, ROADMAP item 1): when > 0,
    # training electrons that registered a snapshot hook
    # (utils.checkpoint.register_snapshot) have their train state published
    # every N seconds — and on the SIGTERM spot-preemption notice — as
    # sha256-named bundles in the worker's remote CAS; the retry driver
    # then resumes the replacement gang from the newest complete
    # checkpoint instead of recomputing from step 0.  0 disables;
    # COVALENT_TPU_CHECKPOINT_INTERVAL_S overrides per process.
    "checkpoint_interval_s": 0.0,
    # Complete checkpoint steps retained per lineage (older bundles are
    # garbage-collected by the worker); COVALENT_TPU_CHECKPOINT_KEEP
    # overrides per process.
    "checkpoint_keep_n": 3,
    # Worker heartbeat cadence (obs/heartbeat.py): each harness process
    # beats every N seconds — step counter, RSS, device-memory stats —
    # into the telemetry side-band the dispatcher streams back (agent
    # channel) or reads piggybacked on its status probe (poll path).
    # 0 disables; COVALENT_TPU_HEARTBEAT_S overrides per process.
    "heartbeat_interval": 5.0,
    # Silence after which a worker that WAS heartbeating is declared
    # stalled (classified `worker_stalled` transient, gang retried before
    # the hard task_timeout).  0 = 3x the heartbeat interval;
    # COVALENT_TPU_STALL_S overrides per process.
    "stall_threshold": 0.0,
}


# Process-wide series every executor instance records to (obs/metrics.py).
# Per-stage latency distributions ride the span histogram
# (covalent_tpu_span_duration_seconds{span="executor.<stage>"}) emitted by
# obs.trace automatically; these three are the executor-level aggregates.
_TASKS_TOTAL = REGISTRY.counter(
    "covalent_tpu_tasks_total",
    "Electron outcomes by terminal state",
    ("outcome",),
)
_ACTIVE_ELECTRONS = REGISTRY.gauge(
    "covalent_tpu_active_electrons",
    "Electrons currently inside TPUExecutor.run()",
)
_OVERHEAD_HIST = REGISTRY.histogram(
    "covalent_tpu_dispatch_overhead_seconds",
    "Per-electron dispatch overhead (lifecycle stages minus execute)",
)
_PREWARM_TOTAL = REGISTRY.counter(
    "covalent_tpu_prewarm_total",
    "DAG-driven connection prewarm attempts by result",
    ("result",),
)
#: Measured cold-start: how long a full gang prewarm (connect +
#: pre-flight + agent warm-up) takes, labelled by fleet pool ("" for
#: pool-less executors).  The autoscale controller sizes its predictive
#: lead time from this — capacity must start warming this many seconds
#: before the trend says demand arrives, measured, not guessed.
_PREWARM_SECONDS = REGISTRY.histogram(
    "covalent_tpu_prewarm_seconds",
    "Gang prewarm (cold-start) duration per fleet pool",
    ("pool",),
)
_WALL_OVERHEAD_HIST = REGISTRY.histogram(
    "covalent_tpu_wall_overhead_seconds",
    "Per-electron wall-clock dispatch overhead (elapsed minus execute)",
)
CHECKPOINT_SAVES_TOTAL = REGISTRY.counter(
    "covalent_tpu_checkpoint_saves_total",
    "Cooperative train-state checkpoint bundles published by workers",
    ("trigger",),
)
CHECKPOINT_RESTORES_TOTAL = REGISTRY.counter(
    "covalent_tpu_checkpoint_restores_total",
    "Retry attempts dispatched with a verified resume checkpoint reference",
)
_CHECKPOINT_RESUMED_STEP = REGISTRY.gauge(
    "covalent_tpu_checkpoint_resumed_step",
    "Step of the most recent checkpoint shipped as a resume reference",
)


def _sanitize_lineage(lineage: str) -> str:
    """Filesystem-safe lineage token (must match harness._sanitize_lineage:
    the worker writes the manifest this name resolves)."""
    return re.sub(r"[^A-Za-z0-9._-]", "_", str(lineage))


def _ckpt_manifest_remote(remote_cache: str, lineage: str) -> str:
    """Remote path of one lineage's checkpoint manifest (CAS dir)."""
    return cas_path(remote_cache, f"ckpt_{_sanitize_lineage(lineage)}", ".json")


def _split_host_port(hostport: str) -> tuple[str, int | None]:
    """Split a ``host[:port]`` worker address.

    Only a single all-digit suffix counts as a port — IPv6 literals and
    other colon-bearing names pass through whole as the hostname.
    """
    host, sep, port = hostport.rpartition(":")
    if sep and port.isdigit() and ":" not in host:
        return host, int(port)
    return hostport, None


class TaskStatus(str, Enum):
    """Remote task state from one combined status round-trip."""

    READY = "READY"          # result file exists
    RUNNING = "RUNNING"      # process alive, no result yet
    STARTING = "STARTING"    # no result, no pid file yet (launch window)
    DEAD = "DEAD"            # process gone and no result -> failure
    TIMEOUT = "TIMEOUT"      # task_timeout expired with processes RUNNING
    STALLED = "STALLED"      # a heartbeating worker went silent past its
    #                          stall threshold while its process looks alive


class StagedTask:
    """Paths produced by staging one task for one worker set.

    Extends the reference's 5-tuple of staged paths (``ssh.py:173-179``) with
    per-worker spec files and the shared harness script.  Immutable staged
    payloads (harness, function pickle, specs) are *content-addressed*:
    their remote paths are ``{remote_cache}/cas/{sha256}{ext}``, which is
    what lets the CAS layer (cache.py) skip re-uploads of bytes a worker
    already holds.  Mutable per-operation files (result, log, pid) keep
    their operation-scoped names.
    """

    def __init__(self, operation_id: str, cache_dir: Path, remote_cache: str):
        self.operation_id = operation_id
        self.function_file = str(cache_dir / f"function_{operation_id}.pkl")
        self.local_result_file = str(cache_dir / f"result_{operation_id}.pkl")
        self.local_spec_files: list[str] = []
        self.remote_cache = remote_cache
        #: content digests, assigned during staging (_write_function_files)
        self.function_digest: str = ""
        self.harness_digest: str = ""
        self.spec_digests: list[str] = []
        self.remote_result_file = f"{remote_cache}/result_{operation_id}.pkl"
        self.remote_log_file = f"{remote_cache}/log_{operation_id}.txt"
        self.remote_pid_file = f"{remote_cache}/pid_{operation_id}"
        #: resume checkpoint shipped to every worker under an OP-SCOPED
        #: remote name, outside the content-addressed staging road:
        #: (local, remote, digest).  Deliberately not a cas/ artifact —
        #: the worker-side checkpointer's keep_n GC owns digest-named
        #: ``.ckpt`` files there, and a not-yet-dead old gang's racing
        #: save must never unlink the bundle the replacement attempt is
        #: about to restore from.
        self.resume_artifact: tuple[str, str, str] | None = None

    def remote_telemetry_file(self, process_id: int) -> str:
        """Worker-local JSONL side-band (heartbeats + worker events) the
        agent channel tails back to the dispatcher."""
        return (
            f"{self.remote_cache}/telemetry_{self.operation_id}"
            f".{process_id}.jsonl"
        )

    def remote_hb_file(self, process_id: int) -> str:
        """Atomic latest-heartbeat snapshot the status probe piggybacks."""
        return f"{self.remote_pid_file}.{process_id}.hb"

    @property
    def remote_function_file(self) -> str:
        return cas_path(self.remote_cache, self.function_digest, ".pkl")

    @property
    def remote_harness_file(self) -> str:
        return cas_path(self.remote_cache, self.harness_digest, ".py")

    def remote_spec_file(self, process_id: int) -> str:
        return cas_path(
            self.remote_cache, self.spec_digests[process_id], ".json"
        )

    def artifacts(self, process_id: int) -> list[tuple[str, str, str]]:
        """``(local_path, remote_path, digest)`` per staged file for one
        worker — the unit the CAS upload path works in."""
        return [
            (self.function_file, self.remote_function_file,
             self.function_digest),
            (_harness_module.__file__, self.remote_harness_file,
             self.harness_digest),
            (self.local_spec_files[process_id],
             self.remote_spec_file(process_id),
             self.spec_digests[process_id]),
        ]


class _StageUploadFailed(Exception):
    """Internal tag: a per-worker pipeline failed in its *upload* leg.

    The pipelined dispatch (upload -> launch per worker, no global
    barrier) needs to preserve the pre-pipeline failure routing: upload
    faults take the channel path (discard + redial + retry, no local
    fallback) while launch faults take the launch path (fallback
    allowed).  ``__cause__`` carries the real error.
    """


class _RpcUnavailable(Exception):
    """Internal control flow: this gang cannot host an RPC invocation
    (no resident pool runtime on the worker).  Caught by the retry driver,
    which re-runs the SAME attempt through the launch path — the ISSUE's
    "automatic fallback on missing agent"."""


class _RetryDispatch(Exception):
    """Internal control flow: this attempt failed transiently and the retry
    budget allows another.  Raised by ``_run_attempt``'s failure sites and
    caught only by the ``run()`` driver — never escapes the executor."""

    def __init__(
        self, reason: str, message: str, redial: bool, conns=None
    ) -> None:
        super().__init__(message)
        self.reason = reason
        self.message = message
        #: drop pooled transports before the next attempt (degradation
        #: order: retry -> redial/alternate connection -> local fallback).
        self.redial = redial
        #: the failed attempt's channels — the ONLY ones a redial may
        #: discard (a concurrent electron's fresh channel must survive).
        self.conns = list(conns or ())


class TPUExecutor(RemoteExecutor):
    """Executor plugin: ``@ct.electron(executor="tpu")``.

    Constructor fields resolve explicit argument -> config
    ``executors.tpu.<key>`` -> default, exactly like the reference chain at
    ``ssh.py:94-124``.
    """

    SHORT_NAME = "tpu"

    def __init__(
        self,
        username: str | None = None,
        hostname: str | None = None,
        workers: Sequence[str] | None = None,
        tpu_name: str | None = None,
        zone: str | None = None,
        project: str | None = None,
        use_internal_ips: bool | None = None,
        ssh_key_file: str | None = None,
        transport: str | None = None,
        known_host_key_file: str | None = None,
        cache_dir: str | None = None,
        python_path: str | None = None,
        conda_env: str | None = None,
        remote_cache: str | None = None,
        remote_workdir: str | None = None,
        create_unique_workdir: bool | None = None,
        run_local_on_dispatch_fail: bool | None = None,
        run_local_on_ssh_fail: bool | None = None,  # reference-compat alias
        poll_freq: float | None = None,
        max_connection_attempts: int | None = None,
        retry_wait_time: float | None = None,
        do_cleanup: bool | None = None,
        defer_cleanup: bool | None = None,
        strict_host_keys: bool | None = None,
        coordinator_port: int | None = None,
        task_timeout: float | None = None,
        task_env: dict[str, str] | None = None,
        use_agent: bool | str | None = None,
        dispatch_mode: str | None = None,
        rpc_inline_args_max: int | None = None,
        pool_preload: str | None = None,
        agent_frames: bool | None = None,
        compress: str | None = None,
        bundle: bool | None = None,
        prewarm: bool | None = None,
        profile_dir: str | None = None,
        cache_results: bool | None = None,
        result_cache_max_entries: int | None = None,
        result_cache_max_bytes: int | None = None,
        cas_ttl_hours: float | None = None,
        cas_max_bytes: int | None = None,
        max_task_retries: int | None = None,
        retry_base_delay: float | None = None,
        retry_max_delay: float | None = None,
        retry_wall_budget: float | None = None,
        circuit_threshold: int | None = None,
        circuit_cooldown: float | None = None,
        chaos: "str | ChaosPlan | None" = None,
        heartbeat_interval: float | None = None,
        stall_threshold: float | None = None,
        checkpoint_interval_s: float | None = None,
        checkpoint_keep_n: int | None = None,
        pool: TransportPool | None = None,
    ) -> None:
        def resolve(value, key):
            if value is not None:
                return value
            got = get_config(f"executors.tpu.{key}", _EXECUTOR_PLUGIN_DEFAULTS[key])
            return got

        self.username = resolve(username, "username")
        self.hostname = resolve(hostname, "hostname")
        self.workers = list(resolve(workers, "workers") or [])
        self.tpu_name = resolve(tpu_name, "tpu_name")
        self.zone = resolve(zone, "zone")
        self.project = resolve(project, "project")
        #: dial workers on VPC-internal IPs (dispatcher inside the project).
        self.use_internal_ips = bool(resolve(use_internal_ips, "use_internal_ips"))
        #: discovery cache: [(external_ip, internal_ip)] per worker.
        self._discovered_endpoints: list[tuple[str, str]] | None = None
        self.transport_kind = resolve(transport, "transport")
        if self.transport_kind not in ("local", "ssh", "minissh"):
            raise ValueError(
                f'transport must be "local", "ssh" or "minissh", '
                f"got {self.transport_kind!r}"
            )
        self.known_host_key_file = str(
            resolve(known_host_key_file, "known_host_key_file") or ""
        )
        self.ssh_key_file = str(
            Path(resolve(ssh_key_file, "ssh_key_file")).expanduser().resolve()
        )
        self.cache_dir = str(Path(resolve(cache_dir, "cache_dir")).expanduser().resolve())
        self.python_path = resolve(python_path, "python_path")
        self.conda_env = resolve(conda_env, "conda_env")
        self.remote_workdir = resolve(remote_workdir, "remote_workdir")
        self.create_unique_workdir = bool(
            resolve(create_unique_workdir, "create_unique_workdir")
        )
        if run_local_on_dispatch_fail is None and run_local_on_ssh_fail is not None:
            run_local_on_dispatch_fail = run_local_on_ssh_fail
        self.run_local_on_dispatch_fail = bool(
            resolve(run_local_on_dispatch_fail, "run_local_on_dispatch_fail")
        )
        self.max_connection_attempts = int(
            resolve(max_connection_attempts, "max_connection_attempts")
        )
        self.retry_wait_time = float(resolve(retry_wait_time, "retry_wait_time"))
        self.do_cleanup = bool(resolve(do_cleanup, "do_cleanup"))
        self.defer_cleanup = bool(resolve(defer_cleanup, "defer_cleanup"))
        self._cleanup_tasks: set[asyncio.Task] = set()
        self._closing = False
        self.strict_host_keys = bool(resolve(strict_host_keys, "strict_host_keys"))
        self.coordinator_port = int(resolve(coordinator_port, "coordinator_port"))
        self.task_timeout = float(resolve(task_timeout, "task_timeout"))
        #: extra environment for the remote harness process (e.g.
        #: LIBTPU_INIT_ARGS, JAX_PLATFORMS) — travels in the task spec.
        self.task_env = dict(resolve(task_env, "task_env") or {})
        #: remote dir for jax.profiler traces; empty disables (SURVEY §5 —
        #: the reference has no tracing subsystem at all).
        self.profile_dir = str(resolve(profile_dir, "profile_dir") or "")
        #: resident worker runtime: push-based completion over one channel
        #: instead of status-probe round-trips.  True/"auto" prefers the
        #: harness forkserver pool (pre-warmed imports, fork per task) and
        #: falls back to the native C++ agent, then to nohup+poll; "pool" or
        #: "native" pins one; False disables both.
        self.use_agent = resolve(use_agent, "use_agent")
        if self.use_agent not in (True, False, "auto", "pool", "native", "off"):
            raise ValueError(
                f"use_agent must be True/False/'auto'/'pool'/'native'/'off', "
                f"got {self.use_agent!r}"
            )
        if self.use_agent == "off":
            self.use_agent = False
        #: RPC dispatch mode: explicit arg > COVALENT_TPU_DISPATCH_MODE >
        #: config; per-electron metadata ("dispatch_mode") overrides again.
        env_mode = os.environ.get("COVALENT_TPU_DISPATCH_MODE")
        if dispatch_mode is None and env_mode is not None:
            dispatch_mode = env_mode.strip().lower() or None
        self.dispatch_mode = str(
            resolve(dispatch_mode, "dispatch_mode")
        ).lower()
        if self.dispatch_mode not in ("launch", "auto", "rpc"):
            raise ValueError(
                f'dispatch_mode must be "launch", "auto" or "rpc", '
                f"got {self.dispatch_mode!r}"
            )
        env_inline = os.environ.get("COVALENT_TPU_RPC_INLINE_MAX")
        if rpc_inline_args_max is None and env_inline is not None:
            try:
                rpc_inline_args_max = int(env_inline)
            except ValueError:
                app_log.warning(
                    "ignoring non-integer COVALENT_TPU_RPC_INLINE_MAX=%r",
                    env_inline,
                )
        self.rpc_inline_args_max = max(
            0, int(resolve(rpc_inline_args_max, "rpc_inline_args_max"))
        )
        #: dispatch mode the most recent attempt actually used
        #: ("rpc"/"launch"); bench and tests assert the fast path engaged.
        self.last_dispatch_mode = ""
        #: comma-separated modules the pool server imports once at start.
        self.pool_preload = str(resolve(pool_preload, "pool_preload"))
        #: binary agent-channel frames: explicit arg >
        #: COVALENT_TPU_AGENT_FRAMES > config.  The kill switch only stops
        #: THIS side from negotiating — the runtime keeps advertising, and
        #: either side declining leaves the channel on the JSONL fallback.
        env_frames = os.environ.get("COVALENT_TPU_AGENT_FRAMES")
        if agent_frames is None and env_frames is not None:
            agent_frames = env_frames.strip().lower() not in (
                "0", "off", "false", "no"
            )
        self.agent_frames = bool(resolve(agent_frames, "agent_frames"))
        #: wire codec policy: explicit arg > COVALENT_TPU_COMPRESS > config.
        env_compress = os.environ.get("COVALENT_TPU_COMPRESS")
        if compress is None and env_compress is not None:
            compress = env_compress.strip().lower() or None
        self.compress = str(resolve(compress, "compress")).lower()
        if self.compress in ("0", "false", "no", "none", "raw"):
            self.compress = "off"
        elif self.compress in ("1", "true", "yes", "on"):
            self.compress = "auto"
        if self.compress not in ("auto", "off", "zlib", "zstd"):
            raise ValueError(
                f'compress must be "auto"/"off"/"zlib"/"zstd", '
                f"got {self.compress!r}"
            )
        #: bundled staging (one tar per worker instead of per-file pairs).
        self.bundle = bool(resolve(bundle, "bundle"))
        #: whether the workflow runner may pre-dial this executor.
        self.prewarm_enabled = bool(resolve(prewarm, "prewarm"))
        #: pool key -> codec names the worker advertised at pre-flight.
        self._wire_codecs: dict[str, list[str]] = {}
        #: a prewarm already warmed this loop's pool (reset on discard).
        self._prewarmed = False
        #: result memoization (cache.py level 2): explicit arg > env var >
        #: config > default-off.  Env is the workflow-layer switch — each
        #: dispatch resolves a fresh alias executor, and the disk-backed
        #: store under cache_dir is what repeat dispatches share.
        env_cache = os.environ.get("COVALENT_TPU_RESULT_CACHE")
        if cache_results is None and env_cache is not None:
            cache_results = env_cache.strip().lower() not in (
                "", "0", "false", "no", "off"
            )
        self.cache_results = bool(resolve(cache_results, "cache_results"))
        self.cas_ttl_hours = float(resolve(cas_ttl_hours, "cas_ttl_hours"))
        #: byte budget for remote_cache/cas/ (and the local KV mirror):
        #: oldest-access-first LRU eviction once the dir outgrows it.
        #: The TTL prune bounds staleness; this bounds SIZE — KV bundles
        #: are orders of magnitude larger than fn pickles.  0 = off.
        env_cas_bytes = os.environ.get("COVALENT_TPU_CAS_MAX_BYTES")
        if cas_max_bytes is None and env_cas_bytes is not None:
            try:
                cas_max_bytes = int(env_cas_bytes)
            except ValueError:
                app_log.warning(
                    "ignoring non-integer COVALENT_TPU_CAS_MAX_BYTES=%r",
                    env_cas_bytes,
                )
        self.cas_max_bytes = max(
            0, int(resolve(cas_max_bytes, "cas_max_bytes"))
        )

        #: gang-level retry budget (resilience.py): explicit arg > env >
        #: config > default-off, the same chain as cache_results — the env
        #: var is the workflow-layer switch for a whole dispatch.
        env_retries = os.environ.get("COVALENT_TPU_TASK_RETRIES")
        if max_task_retries is None and env_retries is not None:
            try:
                max_task_retries = int(env_retries)
            except ValueError:
                app_log.warning(
                    "ignoring non-integer COVALENT_TPU_TASK_RETRIES=%r",
                    env_retries,
                )
        self.max_task_retries = max(
            0, int(resolve(max_task_retries, "max_task_retries"))
        )
        self._retry_policy = RetryPolicy(
            max_retries=self.max_task_retries,
            base_delay=float(resolve(retry_base_delay, "retry_base_delay")),
            max_delay=float(resolve(retry_max_delay, "retry_max_delay")),
            wall_budget=float(
                resolve(retry_wall_budget, "retry_wall_budget")
            ),
        )
        #: per-worker-address quarantine, consulted before every fresh dial.
        self._breakers = CircuitBreakerRegistry(
            failure_threshold=int(
                resolve(circuit_threshold, "circuit_threshold")
            ),
            cooldown=float(resolve(circuit_cooldown, "circuit_cooldown")),
        )
        #: fault-injection plan shared by every transport this executor
        #: dials (None = no chaos wrapper).  A ChaosPlan instance wins so
        #: tests/bench can script faults and read injection counts back.
        if isinstance(chaos, ChaosPlan):
            self._chaos: ChaosPlan | None = chaos
        else:
            if chaos is None:
                chaos = os.environ.get("COVALENT_TPU_CHAOS")
            self._chaos = plan_from_spec(str(resolve(chaos, "chaos") or ""))
        #: worker liveness: heartbeat cadence shipped in the task spec and
        #: the silence past which a beating worker counts as stalled.  Env
        #: is the workflow-layer switch, same chain as the retry budget.
        def resolve_float_env(value, env_name, key):
            env_value = os.environ.get(env_name)
            if value is None and env_value is not None:
                try:
                    value = float(env_value)
                except ValueError:
                    app_log.warning(
                        "ignoring non-numeric %s=%r", env_name, env_value
                    )
            return max(0.0, float(resolve(value, key)))

        self.heartbeat_interval = resolve_float_env(
            heartbeat_interval, "COVALENT_TPU_HEARTBEAT_S",
            "heartbeat_interval",
        )
        self.stall_threshold = resolve_float_env(
            stall_threshold, "COVALENT_TPU_STALL_S", "stall_threshold"
        )
        #: cooperative checkpointing cadence (elastic gangs): shipped in
        #: the task spec; the harness snapshots the electron's registered
        #: train state on this interval and on SIGTERM.
        self.checkpoint_interval_s = resolve_float_env(
            checkpoint_interval_s, "COVALENT_TPU_CHECKPOINT_INTERVAL_S",
            "checkpoint_interval_s",
        )
        env_keep = os.environ.get("COVALENT_TPU_CHECKPOINT_KEEP")
        if checkpoint_keep_n is None and env_keep is not None:
            try:
                checkpoint_keep_n = int(env_keep)
            except ValueError:
                app_log.warning(
                    "ignoring non-integer COVALENT_TPU_CHECKPOINT_KEEP=%r",
                    env_keep,
                )
        self.checkpoint_keep_n = max(
            1, int(resolve(checkpoint_keep_n, "checkpoint_keep_n"))
        )
        #: lineage (base operation id) -> newest-first checkpoint records
        #: {"step","digest","file","local"?} learned from worker
        #: checkpoint_saved events and resume discovery.
        self._ckpt_records: dict[str, list[dict[str, Any]]] = {}
        #: lineage -> resume reference the next retry attempt ships
        #: ({"step","digest","local"}), produced by _discover_resume.
        self._resume_plans: dict[str, dict[str, Any]] = {}
        #: attempt operation ids whose worker announced a preemption
        #: notice (worker.preempt_notice): relabels the coming death.
        self._preempt_notices: set[str] = set()
        #: (lineage, step, digest) triples already counted/mirrored — the
        #: telemetry side-band re-tails from offset 0 after reconnects.
        self._ckpt_seen: set[tuple[str, int, str]] = set()
        #: operation id -> this attempt's gang transports (mirror fetches).
        self._op_conns: dict[str, list[Transport]] = {}
        #: live per-operation view served by the ops /status endpoint:
        #: operation_id -> {"stage", "attempt", "trace_id", "since"}.
        self._op_status: dict[str, dict[str, Any]] = {}
        #: attempts consumed by the most recent run() (1 = no retries).
        self.last_attempts = 0
        #: base operation id -> attempts consumed; read (and popped) by the
        #: workflow runner via attempts_of() so node events attribute
        #: retries to the right node even under concurrent fan-out.
        self._op_attempts: dict[str, int] = {}

        resolved_poll_freq = float(resolve(poll_freq, "poll_freq"))
        resolved_remote_cache = resolve(remote_cache, "remote_cache")
        super().__init__(
            poll_freq=resolved_poll_freq,
            remote_cache=resolved_remote_cache,
        )

        os.makedirs(self.cache_dir, exist_ok=True)
        self._pool = pool or TransportPool()
        self._owns_pool = pool is None
        #: hosts (by pool key) that already passed pre-flight — one check
        #: per host per executor lifetime, not per electron (overhead
        #: budget).  Keyed by pool key, NOT id(conn): a GC'd transport's id
        #: can be reused by a fresh connection, which would falsely skip
        #: pre-flight; _discard_workers evicts per-key entries instead.
        self._preflighted: set[str] = set()
        #: level-1 cache: per-connection CAS digest sets (cache.py).
        self._cas = CASIndex()
        #: RPC function registry: per-connection registered-digest sets
        #: mirroring the CAS index (evicted with the channel; self-resets
        #: when a restarted agent loses its in-process registry).
        self._fn_registry = FnRegistry()
        #: level-2 cache: opt-in electron result memoization.
        self._result_cache: ResultCache | None = (
            ResultCache(
                os.path.join(self.cache_dir, "results"),
                max_entries=int(
                    resolve(result_cache_max_entries,
                            "result_cache_max_entries")
                ),
                max_bytes=int(
                    resolve(result_cache_max_bytes, "result_cache_max_bytes")
                ),
            )
            if self.cache_results
            else None
        )
        #: operation_id -> {worker address -> pid}; backs cancel().
        self._active: dict[str, dict[str, int]] = {}
        #: operations killed by cancel(): their DEAD status must surface as
        #: cancellation, never as a failure that re-runs the body locally.
        self._cancelled_ops: set[str] = set()
        #: worker address -> AgentClient | None (None = worker can't run the
        #: agent; don't retry the compile every electron).
        self._agents: dict[str, Any] = {}
        #: operation_id -> per-worker AgentClient used at launch (None slots
        #: mean that worker went through the nohup fallback).
        self._op_agents: dict[str, list] = {}
        #: per-address locks making agent creation single-flight.
        self._agent_locks: dict[str, asyncio.Lock] = {}
        #: sid -> live serving ServeHandle opened on this executor's gang
        #: (serving.open_session registers/deregisters; /status and the
        #: fleet pool view read it).
        self._serve_handles: dict[str, Any] = {}
        #: executor-scoped CAS adapter registry (name -> packed LoRA
        #: bundle record): sessions attach from it, the journal points
        #: recovery's re-attach at its files, and the fleet scheduler's
        #: adapter-digest affinity consults the staged CAS keys.  Lazily
        #: built on first use (serving.registry import stays off the
        #: electron-only hot path); SessionSupervisor._adapter_registry
        #: creates it through this same attribute.
        self._adapter_registry: Any = None
        #: fleet pool name this executor backs ("" standalone) — set by
        #: fleet.pools.Pool so per-pool metrics (prewarm cold-start
        #: durations) key on the pool operators actually scale.
        self.pool_label = ""
        self.last_timings: dict[str, Any] = {}
        #: operation id -> fetched, digest-verified local profile artifact
        #: (merged into ``last_timings["profile_trace"]`` by the epilogue).
        self._profile_artifacts: dict[str, str] = {}

        # Fleet ops plane: start the (env-gated) status endpoint and expose
        # this executor's live view on it.  The provider holds only a
        # weakref — a dropped executor answers None and the server prunes
        # the registration instead of keeping the instance alive.  The
        # flight recorder rides along: executors are where task lifecycles
        # happen, so the black-box rings must be fed before the first one.
        ensure_ops_server()
        ensure_flight_recorder()
        self._ops_provider_name = f"executor:{id(self):x}"
        provider_name = self._ops_provider_name
        self_ref = weakref.ref(
            self,
            lambda _ref: (
                unregister_status_provider(provider_name),
                unregister_profile_provider(provider_name),
            ),
        )

        def _ops_provider():
            executor = self_ref()
            return (
                executor._status_snapshot() if executor is not None else None
            )

        register_status_provider(provider_name, _ops_provider)

        def _profile_provider(params: dict):
            executor = self_ref()
            if executor is None:
                return None
            return executor._capture_profile_blocking(params)

        register_profile_provider(provider_name, _profile_provider)

    def _stall_after(self) -> float:
        """Seconds of heartbeat silence that declare a worker stalled."""
        if self.heartbeat_interval <= 0:
            return 0.0
        if self.stall_threshold > 0:
            return self.stall_threshold
        return 3.0 * self.heartbeat_interval

    def _status_snapshot(self) -> dict[str, Any]:
        """This executor's contribution to the ops ``/status`` payload."""
        try:
            addresses = self._worker_addresses()
        except Exception:  # noqa: BLE001 - topology may be unresolvable
            addresses = []
        in_flight = {}
        for op, state in list(self._op_status.items()):
            in_flight[op] = {
                **state,
                "age_s": round(time.time() - state.get("since", 0.0), 3),
                "pids": dict(self._active.get(op, {})),
                "heartbeats": MONITOR.last(op),
            }
        return {
            "transport": self.transport_kind,
            "workers": addresses,
            "heartbeat_interval_s": self.heartbeat_interval,
            "stall_after_s": self._stall_after(),
            "dispatch_mode": self.dispatch_mode,
            "rpc_registered": self._fn_registry.counts(),
            "serving": self.serve_sessions(),
            "in_flight": in_flight,
            "circuit_breakers": self._breakers.states(),
            "health": HEALTH.snapshot(),
            "agents": {
                address: (client.mode if client is not None else None)
                for address, client in self._agents.items()
            },
        }

    def _set_stage(self, operation_id: str, stage: str) -> None:
        """Move one in-flight op's stage: the live ``/status`` view plus
        the flight recorder's history (stage transitions are state, not
        events — the recorder is where they become browsable later)."""
        state = self._op_status.get(operation_id)
        if state is not None:
            state["stage"] = stage
        FLIGHT_RECORDER.record_stage(
            operation_id, stage,
            trace_id=(state or {}).get("trace_id"),
        )

    # -- RPC registry views (fleet placement + ops /status) ----------------

    def holds_fn_digest(self, digest: str) -> bool:
        """Whether any live connection's resident runtime registered this
        function digest — the fleet scheduler's placement-affinity probe
        (a holding gang skips the register round trip entirely)."""
        return bool(digest) and self._fn_registry.holds(digest)

    def rpc_digest_count(self) -> int:
        """Distinct function digests registered across this executor's
        connections (the fleet ``/status`` per-pool counter)."""
        return len(self._fn_registry.digests())

    def adapter_registry(self):
        """The executor-scoped LoRA adapter registry (lazily built —
        keeps ``serving.registry`` off the electron-only import path).
        Register bundles here (``put``) and sessions opened on this
        executor attach them by name."""
        if self._adapter_registry is None:
            from .serving.registry import AdapterRegistry

            self._adapter_registry = AdapterRegistry(self.cache_dir)
        return self._adapter_registry

    def holds_serve_digest(self, digest: str) -> bool:
        """Whether this executor's gang already staged the given CAS
        artifact (a serving factory payload) — replica warm-up affinity:
        a holding gang re-opens a session of that factory with zero
        staging round trips, the serving analog of fn-digest affinity."""
        return self._cas.holds(digest)

    def in_flight_modes(self) -> dict[str, str]:
        """operation id -> dispatch mode for every in-flight electron."""
        return {
            op: str(state.get("mode", "launch"))
            for op, state in list(self._op_status.items())
        }

    def serve_sessions(self) -> dict[str, dict[str, Any]]:
        """sid -> live serving-session view (state, slots, queue depth,
        tokens/s) for ``/status`` and the fleet pool status."""
        views: dict[str, dict[str, Any]] = {}
        for sid, handle in list(self._serve_handles.items()):
            try:
                views[sid] = handle.status()
            except Exception:  # noqa: BLE001 - status must not crash a view
                pass
        return views

    # ------------------------------------------------------------------ #
    # Worker topology                                                    #
    # ------------------------------------------------------------------ #

    def _worker_addresses(self) -> list[str]:
        """The control-plane address of every pod worker.

        Explicit ``workers`` list wins; otherwise the single ``hostname``
        (the reference's only topology, ``ssh.py:77``); local transport
        needs no address at all.
        """
        if self.workers:
            if len(set(self.workers)) != len(self.workers):
                # PIDs/pool keys are keyed by address; duplicates would alias.
                raise ValueError(f"duplicate worker addresses: {self.workers}")
            return list(self.workers)
        if self.tpu_name:
            endpoints = self._discover_endpoints()
            if self.use_internal_ips:
                return [internal or external for external, internal in endpoints]
            return [external or internal for external, internal in endpoints]
        if self.hostname:
            return [self.hostname]
        if self.transport_kind == "local":
            return ["localhost"]
        raise ValueError(
            "TPUExecutor needs `tpu_name`, `hostname`, or `workers` "
            "(or transport='local')"
        )

    def _num_processes(self) -> int:
        return len(self._worker_addresses())

    def _discover_endpoints(self) -> list[tuple[str, str]]:
        """Cached ``(external, internal)`` endpoints for ``tpu_name``."""
        if self._discovered_endpoints is None:
            from .discovery import discover_tpu_endpoints

            self._discovered_endpoints = discover_tpu_endpoints(
                self.tpu_name, zone=self.zone, project=self.project
            )
            app_log.info(
                "TPU %s: discovered %d worker(s)",
                self.tpu_name, len(self._discovered_endpoints),
            )
        return self._discovered_endpoints

    def seed_endpoints(
        self, endpoints: Sequence[tuple[str, str]]
    ) -> None:
        """Pre-fill the ``tpu_name`` discovery cache from an external
        resolution — fleet registration already ran gcloud once, so the
        first dispatch must not pay (or race) a second subprocess.  Gang
        teardown still clears the cache, keeping the re-discovery path
        for re-created TPUs."""
        pairs = [
            (str(external), str(internal)) for external, internal in endpoints
        ]
        if pairs:
            self._discovered_endpoints = pairs

    async def _ensure_workers(self) -> None:
        """Warm the discovery cache off the event loop (gcloud can be slow)."""
        if self.tpu_name and self._discovered_endpoints is None:
            await asyncio.to_thread(self._discover_endpoints)

    def _coordinator_address(self) -> str:
        if self.transport_kind == "local":
            # Local-transport "workers" are processes on this machine; their
            # labels are bookkeeping names, not resolvable hosts.
            return f"127.0.0.1:{self.coordinator_port}"
        if self.tpu_name:
            # Data plane stays on the VPC: workers dial worker 0's INTERNAL
            # IP — default GCP firewalls block arbitrary ports on external
            # IPs, which would hang every jax.distributed.initialize.
            external, internal = self._discover_endpoints()[0]
            return f"{internal or external}:{self.coordinator_port}"
        host = self._worker_addresses()[0]
        # Strip user@ and any :ssh-port — the data plane dials its own port.
        host, _ = _split_host_port(host.split("@", 1)[-1])
        return f"{host}:{self.coordinator_port}"

    # ------------------------------------------------------------------ #
    # Credentials / connect / fallback                                   #
    # ------------------------------------------------------------------ #

    async def _validate_credentials(self) -> bool:
        """Reference: ``_validate_credentials`` (ssh.py:317-335)."""
        if self.transport_kind == "local":
            return True
        if not Path(self.ssh_key_file).is_file():
            raise RuntimeError(
                f"no SSH key file found at {self.ssh_key_file}; "
                "set `ssh_key_file` or [executors.tpu].ssh_key_file"
            )
        return True

    def _make_transport(self, address: str) -> Transport:
        if self.transport_kind == "local":
            return LocalTransport()
        username = address.split("@", 1)[0] if "@" in address else self.username
        host, port = _split_host_port(address.split("@", 1)[-1])
        return SSHTransport(
            hostname=host,
            username=username,
            ssh_key_file=self.ssh_key_file,
            port=port or 22,
            strict_host_keys=self.strict_host_keys,
            backend="minissh" if self.transport_kind == "minissh" else "auto",
            known_host_key=self.known_host_key_file or None,
        )

    async def _client_connect(self, address: str) -> Transport:
        """Open (or reuse) the control-plane channel to one worker.

        Reference: ``_client_connect``/``_attempt_client_connect``
        (ssh.py:210-282); retry classification lives in
        :func:`covalent_tpu_plugin.transport.connect_with_retries`.
        """

        async def factory() -> Transport:
            transport = self._make_transport(address)
            if self._chaos is not None:
                # Chaos wraps UNDER the connect-retry envelope so injected
                # connect faults exercise the same classified-retry path a
                # real refused dial does.
                transport = ChaosTransport(transport, self._chaos)
            return await connect_with_retries(
                transport,
                max_attempts=self.max_connection_attempts,
                retry_wait_time=self.retry_wait_time,
            )

        # The breaker gate makes a quarantined host fail fast instead of
        # burning the full connect-retry envelope on every electron.
        return await self._pool.acquire(
            self._pool_key(address), factory, gate=self._breakers.get(address)
        )

    def _pool_key(self, address: str) -> str:
        return f"{self.transport_kind}:{address}"

    async def _drain_cleanup_tasks(self, until_empty: bool = False) -> None:
        """Await pending deferred-cleanup tasks bound to this loop.

        ``until_empty`` keeps re-collecting tasks scheduled while draining
        — only sound when ``_closing`` stops new ones (close()); a
        mid-run drain (``_discard_workers``) snapshots once instead, or
        concurrent electrons could starve it indefinitely.
        """
        loop = asyncio.get_running_loop()
        while True:
            current = [
                t for t in self._cleanup_tasks
                if not t.done() and t.get_loop() is loop
            ]
            if not current:
                return
            await asyncio.gather(*current, return_exceptions=True)
            if not until_empty:
                return

    async def _discard_workers(
        self, conns: list[Transport] | None = None
    ) -> None:
        """Drop pooled transports after a mid-run control-plane error so the
        next electron redials instead of reusing a dead channel.

        ``conns`` scopes the discard to the channels this caller actually
        saw fail: a concurrent electron may already have redialed a FRESH
        transport under the same pool key, and closing that one would turn
        a single fault into a cascade of spurious launch failures across
        the whole fan-out.  ``None`` (e.g. loop-guard teardown) discards
        unconditionally.
        """
        obs_events.emit(
            "pool.workers_discarded",
            addresses=self._worker_addresses(),
            transport=self.transport_kind,
        )
        # Deferred-cleanup tasks from earlier electrons hold these same
        # pooled transports; closing the channels mid-rm would fail their
        # cleanup and leak the staged files — let them finish first.
        await self._drain_cleanup_tasks()
        any_discarded = False
        for address in self._worker_addresses():
            key = self._pool_key(address)
            discarded = await self._pool.discard(key, only=conns)
            if not discarded and conns is not None and self._pool.has(key):
                # A DIFFERENT (fresh) transport owns this key now — a
                # concurrent electron already discarded the failed channel
                # and redialed.  Its preflight/CAS/agent state is valid;
                # leave it alone.
                continue
            any_discarded = any_discarded or discarded
            client = self._agents.pop(address, None)
            if client is not None:
                await client.close()
            # Per-key eviction (not clear()): other hosts' pre-flight and
            # CAS knowledge stays valid; only the discarded channels must
            # re-prove their environment and re-probe their artifact cache
            # (the worker may have been recreated with an empty disk).
            self._preflighted.discard(key)
            self._wire_codecs.pop(key, None)
            self._cas.forget(key)
            # The resident runtime died with its channel: its in-process
            # function registry is gone, so the next RPC dispatch must
            # re-register (execute-by-digest self-heals like the CAS).
            self._fn_registry.forget(key)
        # A recreated worker must be re-dialed by the next prewarm too.
        self._prewarmed = False
        # A mid-run control-plane failure may mean the TPU itself was
        # preempted/recreated with new IPs: re-discover on the next electron
        # instead of dialing stale addresses forever.
        if any_discarded or conns is None:
            self._discovered_endpoints = None

    async def _connect_all(self) -> list[Transport]:
        """Open channels to every worker concurrently (all-or-nothing)."""
        await self._ensure_workers()  # blocking gcloud discovery off-loop
        addresses = self._worker_addresses()
        results = await asyncio.gather(
            *(self._client_connect(a) for a in addresses), return_exceptions=True
        )
        errors = [r for r in results if isinstance(r, BaseException)]
        if errors:
            raise TransportError(
                f"failed to connect to {len(errors)}/{len(addresses)} workers: {errors[0]}"
            ) from errors[0]
        return list(results)  # type: ignore[list-item]

    # ------------------------------------------------------------------ #
    # Gang ownership (the GangLease seam)                                #
    # ------------------------------------------------------------------ #

    async def lease_gang(
        self, dialed: "list[Transport] | None" = None
    ) -> GangLease:
        """Acquire a fully warmed gang behind the ownership seam.

        Connect to every worker (pooled, breaker-gated), run the batched
        pre-flight, and warm the resident agents — then hand the gang back
        as a :class:`~covalent_tpu_plugin.fleet.lease.GangLease` so the
        caller (the attempt state machine in :meth:`_run_attempt`, a
        prewarm, or the fleet scheduler bin-packing electrons onto warm
        gangs) holds ownership explicitly instead of reaching into the
        transport pool.  Raises exactly what the dial/pre-flight path
        raises (``TransportError``/``OSError``/``ValueError``), so every
        caller keeps its existing failure routing.

        ``dialed`` (when given) receives the connected channels as soon
        as the dial succeeds — BEFORE pre-flight can fail — so a caller
        whose retry policy discards the failed attempt's channels still
        holds them when pre-flight (not the dial) is what raised; without
        this, a redial retry would silently reuse the dead pooled
        transports pre-flight just proved broken.
        """
        with Span("executor.connect"):
            conns = await self._connect_all()
        if dialed is not None:
            dialed.extend(conns)
        addresses = self._worker_addresses()
        with Span("executor.preflight"):
            # Agent warm-up (upload + compile on first use) rides the same
            # gather as the env checks: independent round-trips, so the
            # first electron hides the one-time compile cost.
            await asyncio.gather(
                *(
                    self._preflight(c, key=self._pool_key(a))
                    for a, c in zip(addresses, conns)
                ),
                *(self._agent_for(c) for c in conns),
            )
        return GangLease(self, conns, addresses)

    async def recover(self, timeout_s: float = 120.0) -> dict:
        """Crash-recovery pass: re-adopt what survived the predecessor.

        Replays the journal's picture of the dead dispatcher's world,
        re-dials the fleet (adopting orphaned pool servers and fencing
        the channels with this incarnation's epoch on the way), and
        re-attaches surviving sessions and their in-flight streams.  A
        no-op returning ``recovered=False`` when journaling is off or
        the journal held nothing.  See :mod:`.fleet.recovery`.
        """
        from .fleet import recovery as recovery_mod

        return await recovery_mod.recover(self, timeout_s=timeout_s)

    @property
    def is_warm(self) -> bool:
        """Whether at least one pooled channel has passed pre-flight.

        The fleet placement engine prefers pools whose gangs are warm —
        a leased-and-preflighted channel means the next electron skips
        the dial + pre-flight round trips entirely.
        """
        return bool(self._preflighted)

    def gang_state(self) -> dict[str, Any]:
        """Placement-facing snapshot: warmth + per-address breaker states.

        The scheduler consults this instead of private executor state so
        placement can route around open breakers (no dial is even
        attempted against a quarantined host) and prefer warm gangs.
        Addresses never dialed report ``closed`` — an unknown host is
        placeable, and the breaker gate still protects the actual dial.

        Called synchronously from the scheduler pump on the dispatcher
        loop, so it must never block: a ``tpu_name`` whose endpoints are
        not yet discovered reports no addresses (falling back to every
        known breaker state) instead of running gcloud here —
        ``_ensure_workers`` fills that cache off-loop on first dispatch.
        """
        if self.tpu_name and self._discovered_endpoints is None:
            addresses = []
        else:
            try:
                addresses = self._worker_addresses()
            except Exception:  # noqa: BLE001 - topology may be unresolvable
                addresses = []
        states = self._breakers.states()
        return {
            "warm": self.is_warm,
            "workers": addresses,
            "breakers": (
                {a: states.get(a, "closed") for a in addresses}
                if addresses
                else dict(states)
            ),
        }

    async def prewarm(self) -> bool:
        """Best-effort pre-dial of this executor's control plane.

        The workflow runner calls this for a node whose upstream
        dependencies are still running, so the connect handshake,
        pre-flight round trip, codec negotiation, and agent warm-up all
        overlap upstream compute instead of sitting on the node's own
        critical path.  Everything it touches is the cached/idempotent
        fast path the real dispatch reuses (pool single-flight, breaker
        gate included); failures are swallowed — the dispatch itself will
        surface them with its full retry envelope.  No-op when disabled,
        already warm, or under a chaos plan (injected fault budgets must
        be spent by real dispatch ops, not warmup).
        """
        if not self.prewarm_enabled or self._chaos is not None:
            return False
        if self._prewarmed:
            return False
        self._guard_event_loop()
        self._prewarmed = True  # optimistic: concurrent callers skip
        started = time.monotonic()
        try:
            with Span("executor.prewarm", {"transport": self.transport_kind}):
                lease = await self.lease_gang()
        except asyncio.CancelledError:
            self._prewarmed = False
            raise
        except Exception as err:  # noqa: BLE001 - warmup is advisory
            self._prewarmed = False  # let a later node retry
            _PREWARM_TOTAL.labels(result="failed").inc()
            obs_events.emit(
                "executor.prewarm_failed",
                transport=self.transport_kind,
                error=repr(err),
            )
            app_log.debug("prewarm failed (dispatch will retry): %s", err)
            return False
        _PREWARM_TOTAL.labels(result="warmed").inc()
        # The measured cold-start: the autoscale controller reads this
        # histogram (per pool) to size its predictive lead time.
        _PREWARM_SECONDS.labels(pool=self.pool_label).observe(
            time.monotonic() - started
        )
        obs_events.emit(
            "executor.prewarm",
            transport=self.transport_kind,
            workers=len(lease),
        )
        return True

    async def teardown_gang(self) -> bool:
        """Scale-to-zero actuator: tear down this executor's warm gang.

        Closes the pooled transports, resident agents, and per-key
        pre-flight/CAS/registry state — the idle-capacity release the
        autoscale controller performs after a pool sits unused past its
        TTL.  Refuses (returns False) while electrons are in flight or
        serving sessions are live, and when there is nothing warm to
        drop.  The next dispatch — or :meth:`prewarm`, which the
        controller fires ahead of predicted demand — re-dials from cold
        through the ordinary path; nothing about the executor's
        configuration or retry envelope changes.
        """
        self._guard_event_loop()
        if self._op_status or self._serve_handles:
            return False
        if not self.is_warm:
            return False
        await self._discard_workers()
        obs_events.emit(
            "executor.gang_teardown",
            transport=self.transport_kind,
            **({"pool": self.pool_label} if self.pool_label else {}),
        )
        return True

    def _on_dispatch_fail(
        self, fn: Callable, args: tuple, kwargs: dict, message: str
    ) -> Any:
        """Degraded-mode policy (reference: ``_on_ssh_fail``, ssh.py:181-208).

        On a TPU deployment the dispatcher host has no accelerator, so the
        local fallback runs the electron on CPU-JAX.
        """
        if self.run_local_on_dispatch_fail:
            app_log.warning(
                "TPU dispatch failed (%s); running electron locally on the "
                "dispatcher host (CPU)", message
            )
            return fn(*args, **kwargs)
        app_log.error(message)
        raise RuntimeError(message)

    async def _on_dispatch_fail_async(
        self,
        fn: Callable,
        args: tuple,
        kwargs: dict,
        message: str,
        operation_id: str | None = None,
        log_tail: str = "",
    ) -> Any:
        """Async wrapper: the fallback body runs on a worker thread so a
        long CPU electron cannot stall the (shared) dispatcher event loop —
        every concurrent dispatch and agent channel lives there."""
        obs_events.emit(
            "task.dispatch_failed",
            operation_id=operation_id,
            message=message,
            fallback_local=self.run_local_on_dispatch_fail,
            **({"log_tail": log_tail} if log_tail else {}),
        )
        if self.run_local_on_dispatch_fail:
            app_log.warning(
                "TPU dispatch failed (%s); running electron locally on the "
                "dispatcher host (CPU)", message
            )
            return await asyncio.to_thread(fn, *args, **kwargs)
        app_log.error(message)
        raise RuntimeError(message)

    # ------------------------------------------------------------------ #
    # Staging / pre-flight / upload                                      #
    # ------------------------------------------------------------------ #

    def _write_function_files(
        self,
        operation_id: str,
        fn: Callable,
        args: tuple,
        kwargs: dict,
        current_remote_workdir: str,
        pip_deps: Sequence[str] = (),
        payload: bytes | None = None,
        trace: dict | None = None,
        lineage: str | None = None,
        resume: dict | None = None,
    ) -> StagedTask:
        """Stage the function pickle + per-worker task specs locally.

        Reference: ``_write_function_files`` (ssh.py:126-179).  Instead of
        ``.format()``-ing the harness per task (ssh.py:160-171), per-task
        parameters go into small JSON spec files — one per worker process so
        each gets its own ``process_id`` for ``jax.distributed``.
        ``payload`` carries pre-serialized ``(fn, args, kwargs)`` bytes when
        the result-cache lookup already pickled them, so a cold cached
        dispatch never serializes a large argument set twice.  ``trace``
        (obs.trace.context_of) stamps the dispatch trace/span ids + attempt
        into every spec so worker-side events join the dispatch trace.
        """
        staged = StagedTask(operation_id, Path(self.cache_dir), self.remote_cache)
        if payload is None:
            dump_task(fn, args, kwargs, staged.function_file)
            staged.function_digest = file_digest(staged.function_file)
        else:
            with open(staged.function_file, "wb") as f:
                f.write(payload)
            staged.function_digest = bytes_digest(payload)
        # Content addressing: remote artifact paths derive from the digests
        # above, which therefore must exist before the specs (embedding the
        # remote function path) are written.
        staged.harness_digest = harness_digest()

        num_processes = self._num_processes()
        dist_blocks = (
            coordinator_spec(
                coordinator_address=self._coordinator_address(),
                num_processes=num_processes,
            )
            if num_processes > 1
            else None
        )
        # Worker-side events join the dispatcher's JSONL only when the two
        # share a filesystem (local transport); remote workers honor their
        # own COVALENT_TPU_EVENTS_PATH instead of scribbling a dispatcher
        # path onto a foreign fs.
        events_file = (
            obs_events.get_sink().path
            if self.transport_kind == "local" and obs_events.get_sink().enabled
            else None
        )
        checkpoint_block: dict[str, Any] | None = None
        if self.checkpoint_interval_s > 0:
            checkpoint_block = {
                "dir": f"{self.remote_cache}/cas",
                "lineage": lineage or operation_id,
                "interval_s": self.checkpoint_interval_s,
                "keep_n": self.checkpoint_keep_n,
            }
        resume_block: dict[str, Any] | None = None
        if resume and resume.get("local") and resume.get("digest"):
            remote_bundle = f"{self.remote_cache}/resume_{operation_id}.ckpt"
            staged.resume_artifact = (
                resume["local"], remote_bundle, resume["digest"]
            )
            resume_block = {
                "file": remote_bundle,
                "step": int(resume.get("step", 0)),
                "digest": resume["digest"],
            }
        for process_id in range(num_processes):
            spec: dict[str, Any] = {
                "operation_id": operation_id,
                "function_file": staged.remote_function_file,
                # The harness verifies the CAS artifact against this before
                # unpickling: a torn/stale digest-addressed file fails loud.
                "function_digest": staged.function_digest,
                "result_file": staged.remote_result_file,
                "workdir": current_remote_workdir,
                "pid_file": f"{staged.remote_pid_file}.{process_id}",
            }
            if events_file:
                spec["events_file"] = events_file
            if trace:
                spec["trace"] = trace
            if self.heartbeat_interval > 0:
                # Liveness side-band: the harness beats into a worker-local
                # telemetry file (agent channel tails it back) and keeps an
                # atomic snapshot the status probe reads piggybacked.
                spec["heartbeat_s"] = self.heartbeat_interval
                spec["telemetry_file"] = staged.remote_telemetry_file(
                    process_id
                )
            if self.task_env:
                spec["env"] = self.task_env
            if self.profile_dir:
                # Per-task subdir so concurrent electrons' traces don't mix.
                spec["profile_dir"] = f"{self.profile_dir}/{operation_id}"
            if pip_deps:
                spec["pip_deps"] = list(pip_deps)
            if checkpoint_block is not None:
                spec["checkpoint"] = checkpoint_block
            if resume_block is not None:
                spec["resume"] = resume_block
            if dist_blocks is not None:
                spec["distributed"] = dist_blocks[process_id]
            local_spec = str(
                Path(self.cache_dir) / f"spec_{operation_id}_{process_id}.json"
            )
            with open(local_spec, "w") as f:
                json.dump(spec, f)
            staged.local_spec_files.append(local_spec)
            staged.spec_digests.append(file_digest(local_spec))
        return staged

    @staticmethod
    def _fn_code_digest(fn: Callable) -> str:
        """Digest of the electron's own bytecode, or "" when unavailable.

        cloudpickle serializes module-importable functions BY REFERENCE
        (module + qualname), so the staged payload bytes alone would not
        change when the user edits such a function's body — and the
        disk-persistent result cache would serve the stale result.  The
        marshalled code object closes that hole for the electron itself
        (edits to transitively imported helpers remain invisible — see the
        README's cache-hit semantics).
        """
        import marshal

        code = getattr(fn, "__code__", None)
        if code is None:
            code = getattr(getattr(fn, "__call__", None), "__code__", None)
        if code is None:
            return ""
        try:
            return bytes_digest(marshal.dumps(code))
        except (TypeError, ValueError):
            return ""

    def _result_cache_key(
        self,
        fn: Callable,
        args: tuple,
        kwargs: dict,
        task_metadata: dict,
        payload: bytes | None = None,
    ) -> str | None:
        """Memoization key for one electron, or None when uncacheable.

        (payload digest, function code digest, executor env fingerprint):
        the payload is the staged ``(fn, args, kwargs)`` pickle — passed in
        when run() already serialized it, so key computation and staging
        share ONE cloudpickle pass — the code digest covers by-reference
        pickled functions whose payload bytes don't change with their body,
        and the fingerprint covers everything that could change the remote
        computation's meaning: transport/interpreter/conda environment,
        task env, pip deps, and worker topology, so a config change never
        serves a stale result.  Unpicklable callables/arguments are simply
        uncacheable (counted, never fatal).
        """
        if payload is None:
            try:
                payload = cloudpickle.dumps(
                    (fn, tuple(args), dict(kwargs))
                )
            except Exception as err:  # noqa: BLE001 - arbitrary payloads
                RESULT_CACHE_TOTAL.labels(result="unpicklable").inc()
                app_log.debug(
                    "result cache: electron not picklable (%s)", err
                )
                return None
        fingerprint = json.dumps(
            {
                "transport": self.transport_kind,
                "python_path": self.python_path,
                "conda_env": self.conda_env,
                "task_env": self.task_env,
                "pip_deps": list(task_metadata.get("pip_deps", ()) or ()),
                "workers": self.workers
                or [self.tpu_name or self.hostname or "local"],
                "workdir": self.remote_workdir,
            },
            sort_keys=True,
            default=str,
        )
        return ResultCache.make_key(
            bytes_digest(payload),
            self._fn_code_digest(fn),
            bytes_digest(fingerprint.encode()),
        )

    def _preflight_command(self) -> str:
        """One compound pre-flight command.

        Folds the reference's three sequential round-trips — conda-env check
        (ssh.py:508-519), python3 check (ssh.py:521-524), cache mkdir
        (ssh.py:528-532) — into a single exec.
        """
        cas_dir = shlex.quote(cas_path(self.remote_cache, "").rstrip("/"))
        checks = [
            f"mkdir -p {shlex.quote(self.remote_cache)} {cas_dir}"
        ]
        prune = self._cas_prune_clause()
        if prune:
            # Connection-start CAS prune (pre-flight runs before the first
            # existence probe, so the present set can never reference a
            # pruned file): bounds worker-disk growth from one-off payloads
            # and sweeps .tmp orphans of crashed uploads.  Cleanup re-runs
            # the same clause per electron, so growth stays bounded on
            # long-lived connections too.
            checks.append(f"({prune} || true)")
        if self.conda_env:
            checks.append(
                f'eval "$(conda shell.bash hook)" && conda activate '
                f"{shlex.quote(self.conda_env)}"
            )
        # Codec negotiation rides the same compound command (zero extra
        # round trips): the clause prints COVALENT_TPU_CODECS=... and
        # always exits 0, so a probe failure means the raw fallback, never
        # a failed pre-flight.
        codec_probe = codec_mod.probe_clause(self.python_path, self.compress)
        if codec_probe:
            checks.append(codec_probe)
        # -E -S skips site/sitecustomize processing: the check only needs
        # the interpreter's existence + major version, and a site hook that
        # imports heavy ML runtimes (as TPU-VM images do) would turn a
        # ~30 ms probe into seconds of first-electron latency.  (-E -S and
        # not -I: python2 rejects -I, which would mask the dedicated
        # "not python3" diagnostic below with an option error.)
        checks.append(
            f"{self.python_path} -E -S -c 'import sys; print(sys.version_info[0])'"
        )
        return " && ".join(checks)

    def _codec_for(
        self, key: str, conn: Transport
    ) -> "codec_mod.Codec | None":
        """The negotiated wire codec for one connection (None = raw).

        Zero-wire transports (shared filesystem) always ship raw; a pinned
        codec the worker didn't advertise degrades to raw with a warning
        rather than failing dispatch.
        """
        if self.compress == "off" or getattr(conn, "zero_wire", False):
            return None
        remote = self._wire_codecs.get(key, ())
        if self.compress in ("zlib", "zstd"):
            if (
                self.compress in remote
                and self.compress in codec_mod.available_codecs()
            ):
                return codec_mod.get_codec(self.compress)
            app_log.warning(
                "compress=%r pinned but %s did not negotiate it; "
                "shipping raw", self.compress, conn.address,
            )
            return None
        return codec_mod.pick_codec(remote)

    def _cas_prune_clause(self) -> str | None:
        """Age-prune shell clause for the CAS dir; None when disabled."""
        if self.cas_ttl_hours <= 0:
            return None
        cas_dir = shlex.quote(cas_path(self.remote_cache, "").rstrip("/"))
        minutes = max(1, int(self.cas_ttl_hours * 60))
        return (
            f"find {cas_dir} -type f -mmin +{minutes} "
            "-exec rm -f {} + 2>/dev/null"
        )

    def _cas_maintenance_command(self, staged: StagedTask) -> str:
        """Per-electron CAS upkeep, one round-trip, run during cleanup.

        ``touch`` refreshes the dedupable artifacts' mtimes so the TTL
        prune treats in-use files as hot — without it, a sibling executor's
        prune could delete a week-old harness a live present set still
        references, making the next upload skip launch against a missing
        file.  The prune clause then ages out one-off payloads (unique-args
        function pickles) continuously, not just at connection start, so a
        long-lived connection cannot fill the worker disk.
        """
        hot = " ".join(
            shlex.quote(p)
            for p in (staged.remote_function_file, staged.remote_harness_file)
        )
        parts = [f"touch -c {hot} 2>/dev/null"]
        prune = self._cas_prune_clause()
        if prune:
            parts.append(prune)
        if self.cas_max_bytes > 0:
            # Byte-budget LRU AFTER the touch, so this electron's hot
            # artifacts sit at the LRU tail and one-off payloads (unique
            # args pickles, KV bundles) evict first.  The worker prints
            # CAS_EVICTED=<n> for the dispatcher's eviction counter.
            parts.append(cas_bytes_prune_command(
                self.python_path,
                cas_path(self.remote_cache, "").rstrip("/"),
                self.cas_max_bytes,
            ))
        return "; ".join(parts) + "; true"

    async def _preflight(self, conn: Transport, key: str | None = None) -> None:
        """Run the environment checks once per pooled connection.

        The reference re-validates the remote environment on every electron
        (3 round-trips each time, ssh.py:508-532); with pooled transports the
        environment cannot change under us, so the (already batched) check
        runs once per host and subsequent electrons skip straight to staging.
        Keyed by the pool key (the connection's durable identity), which
        _discard_workers evicts when the channel is dropped — an id(conn)
        key could be silently reused by a fresh connection after GC.
        """
        key = key or self._pool_key(conn.address)
        if key in self._preflighted:
            return
        # The breaker is keyed by the *configured* worker address (the pool
        # key's tail), the same identity _client_connect gates on.
        breaker = self._breakers.get(key.split(":", 1)[1])
        try:
            result = await conn.run(self._preflight_command())
            if result.exit_status != 0:
                raise TransportError(
                    f"pre-flight failed on {conn.address}: "
                    f"{result.stderr.strip()}"
                )
            if result.stdout.strip().splitlines()[-1] != "3":
                raise TransportError(
                    f"{self.python_path} on {conn.address} is not python3 "
                    f"(reported major version {result.stdout.strip()!r})"
                )
        except (TransportError, OSError):
            # A host that keeps failing preflight is as quarantine-worthy
            # as one that refuses to dial.
            breaker.record_failure()
            raise
        breaker.record_success()
        # Codec negotiation settled by the same round trip: remember what
        # the worker advertised (absent/garbled -> raw fallback).
        self._wire_codecs[key] = codec_mod.parse_probe(result.stdout)
        self._preflighted.add(key)

    async def _upload_task(
        self,
        conn: Transport,
        staged: StagedTask,
        process_id: int,
        key: str | None = None,
    ) -> None:
        """Ship the staged files to one worker (reference: ssh.py:337-361).

        Every artifact goes through the CAS layer: digests the worker is
        known to hold are skipped outright, unknown state is resolved by
        ONE batched existence probe per connection lifetime, and identical
        payloads racing from concurrent electrons upload single-flight.
        The harness (digest constant per package version) therefore ships
        once per connection, not once per electron × worker.  What DOES
        ship rides the fast path: ≥2 missing artifacts pack into one
        bundle (one put + one unpack exec), and payloads are compressed
        with the codec negotiated at pre-flight — the remote side always
        verifies CAS digests against the decompressed bytes.
        """
        key = key or self._pool_key(conn.address)
        artifacts = staged.artifacts(process_id)
        await self._cas.ensure_probed(
            key, conn, [(digest, remote) for _, remote, digest in artifacts]
        )
        codec = self._codec_for(key, conn)
        if self.bundle:
            await self._cas.ensure_bundle(
                key, conn,
                [(local, remote, digest) for local, remote, digest in artifacts],
                codec=codec, python_path=self.python_path,
            )
        else:
            for local, remote, digest in artifacts:
                await self._cas.ensure(
                    key, conn, digest, local, remote,
                    codec=codec, python_path=self.python_path,
                )
        if staged.resume_artifact is not None:
            # The resume bundle ships OUTSIDE the CAS road, under an
            # op-scoped name: the present-set/skip-if-held optimizations
            # are digest-keyed, and a digest-named copy in cas/ belongs
            # to the worker checkpointer's keep_n GC — a straggling old
            # gang's save could unlink it between this upload and the
            # harness reading it.  tmp + rename keeps the publish atomic;
            # the harness digest-verifies before restoring either way.
            local, remote, digest = staged.resume_artifact
            tmp = f"{remote}.tmp.{os.getpid()}.{process_id}"
            await conn.put(local, tmp)
            await conn.rename(tmp, remote)

    # ------------------------------------------------------------------ #
    # Submit / status / poll / fetch / cancel / cleanup                  #
    # ------------------------------------------------------------------ #

    def _task_command(self, staged: StagedTask, process_id: int) -> str:
        # `exec` makes the harness *replace* the wrapper shell, so the PID
        # captured at launch is the python process itself — kill/liveness
        # probes then act on the real task, conda or not.
        base = (
            f"exec {self.python_path} {shlex.quote(staged.remote_harness_file)} "
            f"{shlex.quote(staged.remote_spec_file(process_id))}"
        )
        if self.conda_env:
            # Conda wrapping per the reference (ssh.py:379-380).
            base = (
                f'eval "$(conda shell.bash hook)" && conda activate '
                f"{shlex.quote(self.conda_env)} && {base}"
            )
        return base

    async def submit_task(
        self, conn: Transport, staged: StagedTask, process_id: int
    ) -> int:
        """Launch the harness detached; return its PID.

        Deliberately asynchronous where the reference blocks
        (``ssh.py:383``): the PID makes :meth:`cancel` implementable (the
        reference stubs it, ssh.py:460-464) and lets N pod workers launch
        near-simultaneously for ``jax.distributed`` rendezvous.
        """
        launch = (
            f"nohup sh -c {shlex.quote(self._task_command(staged, process_id))} "
            f"> {shlex.quote(staged.remote_log_file)} 2>&1 & echo $!"
        )
        result = await conn.run(launch)
        if result.exit_status != 0:
            raise TransportError(
                f"submit failed on {conn.address}: {result.stderr.strip()}"
            )
        try:
            return int(result.stdout.strip().splitlines()[-1])
        except (ValueError, IndexError) as err:
            raise TransportError(
                f"submit on {conn.address} returned no PID: {result.stdout!r}"
            ) from err

    # ------------------------------------------------------------------ #
    # Resident agent fast path (native/agent.cc)                         #
    # ------------------------------------------------------------------ #

    async def _agent_for(self, conn: Transport) -> AgentClient | None:
        """A live agent channel for this worker, or None if unavailable.

        First use per worker uploads + compiles the agent (content-hash
        cached in ``remote_cache``); a worker that can't build or run it is
        remembered as agent-less so no electron pays the probe again.
        """
        if not self.use_agent:
            return None
        modes = (
            ["pool", "native"]
            if self.use_agent in (True, "auto")
            else [str(self.use_agent)]
        )
        # Single-flight per address: concurrent electrons must not each
        # compile/start an agent and orphan the loser's process.
        lock = self._agent_locks.setdefault(conn.address, asyncio.Lock())
        async with lock:
            if conn.address in self._agents:
                client = self._agents[conn.address]
                if client is None:
                    return None
                if client.alive:
                    try:
                        # One cheap RPC proves the cached channel end to
                        # end before a task is entrusted to it: a server
                        # that hung or lost its stdin looks `alive` from
                        # here but would fail (or time out) the submit.
                        await client.ping(self.AGENT_PING_TIMEOUT_S)
                        return client
                    except AgentError as err:
                        app_log.warning(
                            "worker %s: cached agent failed ping (%s); "
                            "restarting it", conn.address, err,
                        )
                        AGENT_RESTARTS_TOTAL.inc()
                        obs_events.emit(
                            "agent.restarted",
                            address=conn.address,
                            error=repr(err),
                        )
                await client.close()  # dead/stale channel; rebuild below
                self._agents.pop(conn.address, None)
            adopted = await self._try_adopt_orphan(conn)
            if adopted is not None:
                self._agents[conn.address] = adopted
                obs_events.emit(
                    "agent.adopted", address=conn.address, mode=adopted.mode
                )
                return adopted
            for mode in modes:
                try:
                    # Frame-body compression mirrors the staging codec's
                    # opt-in download leg: only a PINNED codec engages it
                    # (deflate time beats the b64+JSON tax only when the
                    # wire is the bottleneck); zlib is the one codec the
                    # stdlib-only worker side always has.
                    frames_codec = (
                        "zlib" if self.compress in ("zlib", "zstd") else ""
                    )
                    if mode == "pool":
                        client = await start_pool_server(
                            conn,
                            self.remote_cache,
                            self.python_path,
                            conda_env=self.conda_env,
                            preload=self.pool_preload,
                            frames_enabled=self.agent_frames,
                            frames_codec=frames_codec,
                        )
                    else:
                        binary = await ensure_agent_binary(conn, self.remote_cache)
                        client = await AgentClient.start(
                            conn, binary,
                            frames_enabled=self.agent_frames,
                            frames_codec=frames_codec,
                        )
                except (AgentError, TransportError) as err:
                    app_log.info(
                        "worker %s: no %s runtime (%s)", conn.address, mode, err
                    )
                    continue
                self._agents[conn.address] = client
                await self._declare_epoch(client)
                obs_events.emit(
                    "agent.started", address=conn.address, mode=client.mode
                )
                return client
            app_log.info(
                "worker %s: no resident runtime; using nohup+poll protocol",
                conn.address,
            )
            obs_events.emit(
                "agent.unavailable", address=conn.address, tried=modes
            )
            self._agents[conn.address] = None
            return None

    async def _declare_epoch(self, client: AgentClient) -> None:
        """Fence this channel with the journal's dispatcher epoch.

        Best-effort on workers that predate the verb (the native agent
        forwards unknown commands to its child, old pool servers answer
        with a plain error) — fencing is a recovery guarantee, not a
        dispatch prerequisite.
        """
        epoch = journal_mod.epoch()
        if not epoch:
            return
        try:
            await client.declare_epoch(epoch, timeout=10.0)
        except (AgentError, TransportError, asyncio.TimeoutError) as err:
            app_log.debug(
                "epoch declaration on %s failed (%s); channel unfenced",
                client.address, err,
            )

    async def _try_adopt_orphan(self, conn: Transport) -> AgentClient | None:
        """Re-attach a pool server orphaned by a prior dispatcher.

        Only engages with a journal configured (a journal-less dispatcher
        has no epoch to out-rank the orphan's): reads the worker's
        ``pool_orphan.json`` rendezvous from the remote cache, dials the
        unix socket through the normal transport (``--attach`` relay),
        and fences the adopted channel with OUR epoch.  Any failure —
        no rendezvous, stale socket, refused epoch — falls through to
        the fresh-start path, which is always correct.
        """
        journal = journal_mod.get_journal()
        if journal is None:
            return None
        meta = await read_orphan_rendezvous(conn, self.remote_cache)
        if not meta:
            return None
        if int(meta.get("epoch") or 0) >= journal.epoch:
            app_log.warning(
                "worker %s: orphan rendezvous carries epoch %s >= ours "
                "(%s); not adopting", conn.address, meta.get("epoch"),
                journal.epoch,
            )
            return None
        try:
            client = await attach_pool_server(
                conn,
                self.remote_cache,
                self.python_path,
                str(meta.get("sock") or ""),
                journal.epoch,
                conda_env=self.conda_env,
                frames_enabled=self.agent_frames,
                frames_codec=(
                    "zlib" if self.compress in ("zlib", "zstd") else ""
                ),
            )
        except (AgentError, TransportError, asyncio.TimeoutError) as err:
            app_log.info(
                "worker %s: orphan adoption failed (%s); starting fresh",
                conn.address, err,
            )
            return None
        app_log.info(
            "worker %s: adopted orphaned pool server pid=%s with %d "
            "surviving session(s)", conn.address, meta.get("pid"),
            len(client.banner_sessions),
        )
        return client

    async def _submit_via_agent(
        self, client: AgentClient, staged: StagedTask, process_id: int
    ) -> int:
        """Launch one worker's harness through its resident runtime.

        Pool mode forks the pre-warmed interpreter directly on the spec;
        native mode execs the same command line :meth:`submit_task` would.
        Either way the task artifacts (spec, log, result, PID semantics) are
        identical, so every downstream probe (pid liveness, result file,
        cancel-by-pid) works unchanged if the channel later dies.
        """
        if client.mode == "pool":
            return await client.run_task(
                staged.operation_id,
                spec=staged.remote_spec_file(process_id),
                log=staged.remote_log_file,
            )
        return await client.run_task(
            staged.operation_id,
            ["/bin/sh", "-c", self._task_command(staged, process_id)],
            log=staged.remote_log_file,
        )

    def _record_heartbeat(
        self, operation_id: str, worker: str, heartbeat: dict
    ) -> None:
        """File one worker heartbeat: liveness monitor + dispatcher stream.

        Shared by the poll path (snapshot piggybacked on the status probe)
        and the agent backhaul.  Only a FRESH beat (new ``seq`` — the
        monitor dedups re-reads/re-tails) is re-emitted as a dispatcher
        ``worker.heartbeat`` event and moves the per-worker gauges, so the
        streamed record matches the worker's actual cadence.
        """
        fresh = MONITOR.record(operation_id, worker, heartbeat)
        if not fresh:
            return
        # Passive health feed: inter-arrival jitter on the SAME fresh
        # beats the liveness monitor dedups — a worker whose cadence
        # turns erratic loses health score before it ever misses one.
        HEALTH.record_heartbeat(
            worker, group=str(getattr(self, "tpu_name", "") or "")
        )
        serve = heartbeat.get("serve")
        if isinstance(serve, dict):
            # A serving worker's beats carry its slot occupancy: surface
            # it as dispatcher gauges so load is visible per worker even
            # before any per-session stats record lands.
            for state in ("sessions", "slots", "busy", "queued"):
                if state in serve:
                    SERVE_WORKER_SLOTS.labels(
                        worker=worker, state=state
                    ).set(float(serve.get(state) or 0))
        body = {
            k: v for k, v in heartbeat.items()
            if k not in ("type", "pid", "ts")
        }
        worker_ts = heartbeat.get("ts")
        obs_events.emit(
            "worker.heartbeat",
            worker=worker,
            **({"worker_ts": worker_ts} if worker_ts else {}),
            **body,
        )

    def _handle_backhaul(
        self, operation_id: str, worker: str, data: dict
    ) -> None:
        """One telemetry line pushed up an agent channel's side-band.

        Heartbeats feed the liveness monitor; other worker events are
        re-emitted into the dispatcher's stream — except on the local
        transport, where the shared filesystem already delivered them
        (the harness writes the dispatcher's JSONL directly).  RPC-mode
        events (``rpc`` marker) exist ONLY on the channel — no file sink
        anywhere — so they re-emit regardless of transport.
        """
        if data.get("type") == "worker.heartbeat":
            self._record_heartbeat(operation_id, worker, data)
            return
        if data.get("type") == "worker.checkpoint_saved":
            # Elastic gangs: learn the lineage's newest checkpoint and
            # mirror the bundle locally while the worker is still alive —
            # the mirror survives a full-gang loss (the preempted VM's
            # disk does not).
            self._record_checkpoint(operation_id, worker, data)
        elif data.get("type") == "worker.preempt_notice":
            # SIGTERM reached this attempt's worker: the coming death is
            # a spot reclaim, not a crash.
            self._preempt_notices.add(operation_id)
        if self.transport_kind == "local" and not data.get("rpc"):
            return
        body = {k: v for k, v in data.items() if k not in ("type", "ts")}
        worker_ts = data.get("ts")
        obs_events.emit(
            str(data.get("type") or "worker.event"),
            worker=worker,
            backhaul=True,
            **({"worker_ts": worker_ts} if worker_ts else {}),
            **body,
        )

    # ------------------------------------------------------------------ #
    # Elastic gangs: checkpoint records, mirroring, resume discovery      #
    # ------------------------------------------------------------------ #

    @staticmethod
    def _verify_file(path: str, digest: str) -> bool:
        from .utils.checkpoint import verify_bundle_file

        return verify_bundle_file(path, digest)

    def _local_bundle_path(self, digest: str) -> str:
        return os.path.join(self.cache_dir, "cas", f"{digest}.ckpt")

    def _record_checkpoint(
        self, operation_id: str, worker: str, data: dict
    ) -> None:
        """File one worker.checkpoint_saved record (agent backhaul).

        Dedups on (lineage, step, digest) — the side-band re-tails from
        offset 0 after reconnects — counts the save, and schedules an
        off-critical-path mirror fetch of the bundle into the local CAS so
        resume survives the loss of the worker that wrote it.
        """
        lineage = str(data.get("lineage") or "")
        digest = str(data.get("digest") or "")
        try:
            step = int(data.get("step"))
        except (TypeError, ValueError):
            return
        if not lineage or not digest:
            return
        key = (lineage, step, digest)
        if key in self._ckpt_seen:
            return
        if len(self._ckpt_seen) > 8192:
            self._ckpt_seen.clear()
        self._ckpt_seen.add(key)
        CHECKPOINT_SAVES_TOTAL.labels(
            trigger=str(data.get("trigger") or "interval")
        ).inc()
        entry = {
            "step": step, "digest": digest,
            "file": str(data.get("path") or ""), "worker": worker,
        }
        records = self._ckpt_records.setdefault(lineage, [])
        records[:] = [r for r in records if r["step"] != step]
        records.append(entry)
        records.sort(key=lambda r: r["step"], reverse=True)
        del records[max(8, self.checkpoint_keep_n * 2):]
        if len(self._ckpt_records) > 256:  # unread lineages (direct API)
            self._ckpt_records.pop(next(iter(self._ckpt_records)))
        conns = self._op_conns.get(operation_id) or []
        addresses = self._worker_addresses()
        conn = next(
            (
                c for c, a in zip(conns, addresses)
                if a == worker and c is not None
            ),
            conns[0] if conns else None,
        )
        if conn is not None:
            task = asyncio.ensure_future(
                self._mirror_checkpoint(conn, entry)
            )
            self._cleanup_tasks.add(task)
            task.add_done_callback(self._cleanup_tasks.discard)

    async def _mirror_checkpoint(self, conn: Transport, entry: dict) -> None:
        """Best-effort digest-verified copy of one bundle into the local
        CAS (the durable side of the cooperative-checkpoint contract)."""
        digest = entry["digest"]
        local = self._local_bundle_path(digest)
        if os.path.exists(local):
            entry["local"] = local
            return
        remote = entry.get("file") or cas_path(
            self.remote_cache, digest, ".ckpt"
        )
        tmp = f"{local}.tmp.{os.getpid()}.{os.urandom(4).hex()}"
        try:
            os.makedirs(os.path.dirname(local), exist_ok=True)
            await conn.get(remote, tmp)
            if await asyncio.to_thread(self._verify_file, tmp, digest):
                os.replace(tmp, local)
                entry["local"] = local
            else:
                os.unlink(tmp)
        except (TransportError, OSError, asyncio.CancelledError) as err:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            if isinstance(err, asyncio.CancelledError):
                raise
            app_log.debug(
                "checkpoint mirror of %s failed: %s", digest[:12], err
            )

    async def _discover_resume(
        self, lineage: str, conns: list[Transport] | None
    ) -> dict[str, Any] | None:
        """The lineage's newest COMPLETE checkpoint, verified and mirrored
        locally — the resume reference the next retry attempt ships.

        Sources, newest step first: records learned from the telemetry
        backhaul (already mirrored when the fetch won the race with the
        preemption) merged with the worker-side manifest, probed over the
        failed attempt's still-alive channels or — when the whole gang is
        gone — one fresh pooled dial per address.  Every candidate's bytes
        are sha256-verified; a torn bundle (killed mid-save, truncated
        disk) is skipped with a ``task.resume_skipped_torn`` event and the
        previous complete step wins.
        """
        if self.checkpoint_interval_s <= 0:
            return self._resume_plans.get(lineage)
        usable = [c for c in (conns or []) if c is not None]
        manifest_path = _ckpt_manifest_remote(self.remote_cache, lineage)
        probe_cmd = f"cat {shlex.quote(manifest_path)} 2>/dev/null"

        async def probe(conn: Transport) -> list | None:
            result = await asyncio.wait_for(conn.run(probe_cmd), timeout=10.0)
            if result.exit_status != 0 or not result.stdout.strip():
                return None
            manifest = json.loads(result.stdout)
            history = manifest.get("history")
            return history if isinstance(history, list) else None

        history: list = []
        reader: Transport | None = None
        for conn in list(usable):
            try:
                found = await probe(conn)
            except (Exception, asyncio.TimeoutError):  # noqa: BLE001
                continue
            if found:
                history, reader = found, conn
                break
        have_verified_record = False
        for record in self._ckpt_records.get(lineage, ()):
            if record.get("local") and await asyncio.to_thread(
                self._verify_file, record["local"], record["digest"]
            ):
                have_verified_record = True
                break
        if reader is None and not have_verified_record:
            # The attempt's channels are all dead (full-gang loss) AND
            # nothing usable was mirrored over the backhaul: one fresh
            # pooled dial per address, until the first answer — against a
            # fully reclaimed gang every dial times out, so this road is
            # taken only when it is the ONLY road to a resume.  The pool
            # keeps whatever dials succeed for the next attempt to reuse.
            for address in self._worker_addresses():
                try:
                    conn = await asyncio.wait_for(
                        self._client_connect(address), timeout=15.0
                    )
                    found = await probe(conn)
                except (Exception, asyncio.TimeoutError):  # noqa: BLE001
                    continue
                usable.append(conn)
                if found:
                    history, reader = found, conn
                break
        merged: dict[tuple[int, str], dict] = {}
        for entry in list(self._ckpt_records.get(lineage, ())) + [
            h for h in history if isinstance(h, dict)
        ]:
            try:
                step = int(entry.get("step"))
            except (TypeError, ValueError):
                continue
            digest = str(entry.get("digest") or "")
            if digest:
                merged.setdefault((step, digest), dict(entry))
        best = self._resume_plans.get(lineage)
        fetch_order = (
            [reader] if reader is not None else []
        ) + [c for c in usable if c is not reader]
        for (step, digest), entry in sorted(merged.items(), reverse=True):
            if best is not None and step <= int(best.get("step", -1)):
                break  # nothing newer than the already-verified plan
            local = entry.get("local") or self._local_bundle_path(digest)
            verified = os.path.exists(local) and await asyncio.to_thread(
                self._verify_file, local, digest
            )
            if not verified:
                remote = entry.get("file") or cas_path(
                    self.remote_cache, digest, ".ckpt"
                )
                for conn in fetch_order:
                    tmp = f"{local}.tmp.{os.getpid()}.{os.urandom(4).hex()}"
                    try:
                        os.makedirs(os.path.dirname(local), exist_ok=True)
                        await asyncio.wait_for(
                            conn.get(remote, tmp), timeout=60.0
                        )
                    except (Exception, asyncio.TimeoutError):  # noqa: BLE001
                        try:
                            os.unlink(tmp)
                        except OSError:
                            pass
                        continue  # channel problem: try another worker
                    if await asyncio.to_thread(
                        self._verify_file, tmp, digest
                    ):
                        os.replace(tmp, local)
                        verified = True
                    else:
                        # The bundle ITSELF is torn (killed mid-save or a
                        # truncated disk): no channel will fetch it whole.
                        # Fall back to the previous complete step.
                        try:
                            os.unlink(tmp)
                        except OSError:
                            pass
                        obs_events.emit(
                            "task.resume_skipped_torn",
                            lineage=lineage, step=step, digest=digest,
                        )
                    break
            if verified:
                plan = {"step": step, "digest": digest, "local": local}
                self._resume_plans[lineage] = plan
                obs_events.emit(
                    "task.resume_planned",
                    lineage=lineage, step=step, digest=digest,
                )
                return plan
        return best

    async def _start_backhaul(
        self, operation_id: str, staged: StagedTask
    ) -> None:
        """Open the telemetry side-band on every agent-launched worker.

        Best-effort: a watch that fails leaves that worker on the
        file-based fallback (heartbeat snapshot piggybacked on probes,
        telemetry tail fetched on failure) — never fails the dispatch.
        The server auto-unwatches when the task exits, and events written
        while no channel was attached are flushed on the next (re-)watch,
        deduped by ``seq`` on this side.
        """
        if self.heartbeat_interval <= 0:
            return
        clients = self._op_agents.get(operation_id) or []
        addresses = self._worker_addresses()
        for i, client in enumerate(clients):
            if client is None or not client.alive:
                continue
            worker = addresses[i] if i < len(addresses) else client.address
            if client.on_telemetry is None:
                client.on_telemetry = (
                    lambda task_id, data, _worker=worker: (
                        self._handle_backhaul(task_id, _worker, data)
                    )
                )
            try:
                await client.watch(
                    operation_id, staged.remote_telemetry_file(i)
                )
            except AgentError:
                pass  # poll-path liveness still covers this worker

    async def _confirm_heartbeats(
        self,
        operation_id: str,
        conns: list[Transport],
        staged: StagedTask,
        pids: dict[str, int],
        addresses: list[str],
    ) -> None:
        """Read every suspect worker's heartbeat snapshot directly.

        The stall verdict must never hinge on the streaming side-band
        alone: before the agent wait declares a worker stalled, this
        re-reads the ``.hb`` files over the control channel (the same
        probe shape the polling path uses) so a healthy worker whose
        telemetry stream failed refreshes its liveness clock and survives.
        Best-effort — probe failures leave the monitor unchanged and the
        verdict to the caller.
        """

        async def probe_one(i: int, conn: Transport) -> None:
            worker = addresses[i] if i < len(addresses) else conn.address
            marker = (
                staged.remote_result_file
                if i == 0
                else f"{staged.remote_result_file}.done.{i}"
            )
            try:
                await self.get_status(
                    conn,
                    marker,
                    pids.get(worker),
                    f"{staged.remote_pid_file}.{i}",
                    hb_file=staged.remote_hb_file(i),
                    on_heartbeat=lambda hb, _w=worker: (
                        self._record_heartbeat(operation_id, _w, hb)
                    ),
                )
            except (TransportError, OSError):
                pass

        suspects = {w for w, _ in MONITOR.stalled(operation_id)}
        await asyncio.gather(
            *(
                probe_one(i, conn)
                for i, conn in enumerate(conns)
                if (addresses[i] if i < len(addresses) else conn.address)
                in suspects
            ),
            return_exceptions=True,
        )

    async def _await_all_agent(
        self,
        clients: list[AgentClient],
        conns: list[Transport],
        staged: StagedTask,
        pids: dict[str, int],
    ) -> tuple[TaskStatus, int]:
        """Event-driven analog of :meth:`_poll_all`: block on pushed exit
        events instead of status round-trips.

        Worker 0's exit resolves the task (one ``test -f`` round-trip then
        confirms the result file, preserving the polling path's READY
        definition); a non-zero worker exiting unsuccessfully first fails
        fast with correct blame.  Any agent-channel death downgrades to
        :meth:`_poll_all` — the tasks themselves are unaffected.  With
        heartbeats on, the wait wakes on a short tick to consult the
        liveness monitor (fed by the telemetry side-band) so a silent
        worker surfaces as STALLED before any hard timeout.
        """
        op = staged.operation_id
        timeout = self.task_timeout or None
        stall_after = self._stall_after()
        # Wake often enough to catch a stall promptly but never beat
        # faster than a quarter of the threshold (cheap: no round trips).
        wake = (
            min(1.0, max(0.25, stall_after / 4.0)) if stall_after else None
        )

        async def exit_of(i: int) -> tuple[int, int, int]:
            code, sig = await clients[i].wait_exit(op)
            return i, code, sig

        waiters = [asyncio.ensure_future(exit_of(i)) for i in range(len(clients))]
        #: worker index -> loop time its exit event landed (the gang
        #: straggler differential reads these).
        exit_at: dict[int, float] = {}
        try:
            addresses = self._worker_addresses()
            pending = set(waiters)
            deadline = (
                asyncio.get_running_loop().time() + timeout if timeout else None
            )
            while pending:
                remaining = None
                if deadline is not None:
                    remaining = deadline - asyncio.get_running_loop().time()
                    if remaining <= 0:
                        return TaskStatus.TIMEOUT, 0  # matches _poll_all
                wait_for = remaining
                if wake is not None:
                    wait_for = (
                        wake if remaining is None else min(remaining, wake)
                    )
                done, pending = await asyncio.wait(
                    pending, timeout=wait_for, return_when=asyncio.FIRST_COMPLETED
                )
                if not done:
                    if wake is not None:
                        if MONITOR.stalled(op):
                            # Confirm against the file-based ground truth
                            # before killing anything: the telemetry
                            # side-band can fail (watch rejected, channel
                            # congestion, unwritable telemetry file) while
                            # the worker beats on — its .hb snapshot is
                            # the feed that cannot lie about that.  One
                            # round-trip, only on stall suspicion.
                            await self._confirm_heartbeats(
                                op, conns, staged, pids, addresses
                            )
                        stalled = MONITOR.stalled(op)
                        if stalled:
                            worker, _silence = stalled[0]
                            return TaskStatus.STALLED, (
                                addresses.index(worker)
                                if worker in addresses
                                else 0
                            )
                        continue  # wake tick; deadline re-checked on top
                    return TaskStatus.TIMEOUT, 0
                # Worker 0 first: its successful completion outranks another
                # worker's post-barrier teardown failure, matching
                # _poll_all's statuses[0]-first precedence.
                for task in sorted(done, key=lambda t: t is not waiters[0]):
                    try:
                        i, code, _sig = task.result()
                    except AgentError:
                        # Channel died, task lives on: resume by polling.
                        return await self._poll_all(conns, staged, pids)
                    exit_at[i] = asyncio.get_running_loop().time()
                    if i == 0:
                        # Completion truth stays "result file exists", exactly
                        # like the polling path (reference: ssh.py:402-406).
                        status = await self.get_status(
                            conns[0], staged.remote_result_file, None
                        )
                        if status is TaskStatus.READY:
                            self._note_gang_stragglers(
                                op, addresses, exit_at
                            )
                            return TaskStatus.READY, 0
                        return TaskStatus.DEAD, 0
                    if code != 0:
                        # Before blaming worker i, check whether worker 0
                        # already delivered (its exit event may just be in a
                        # later batch): a written result outranks a post-
                        # barrier teardown failure, matching _poll_all's
                        # statuses[0]-first precedence.
                        status = await self.get_status(
                            conns[0], staged.remote_result_file, None
                        )
                        if status is TaskStatus.READY:
                            return TaskStatus.READY, 0
                        return TaskStatus.DEAD, i
            return TaskStatus.DEAD, 0
        finally:
            for task in waiters:
                task.cancel()

    def _note_gang_stragglers(
        self,
        operation_id: str,
        addresses: list[str],
        exit_at: dict[int, float],
    ) -> None:
        """Differential straggler detection on a completed gang launch.

        A gang is only as fast as its slowest worker; a worker whose
        exit lags the gang median by more than
        ``COVALENT_TPU_STRAGGLER_BUDGET_S`` (default 5s, ``0`` disables)
        is gray-failing even though it finished.  Flagging it feeds the
        health monitor (deprioritized in future placement) and, with
        ``COVALENT_TPU_STRAGGLER_REDIAL`` on, evicts its pooled channel
        so the next electron dials fresh instead of reusing a path that
        may be the real culprit.
        """
        if len(exit_at) < 2:
            return
        try:
            budget = float(
                os.environ.get("COVALENT_TPU_STRAGGLER_BUDGET_S", "5") or 5
            )
        except ValueError:
            budget = 5.0
        if budget <= 0:
            return
        times = sorted(exit_at.values())
        median = times[len(times) // 2]
        slowest_i = max(exit_at, key=lambda i: exit_at[i])
        differential = exit_at[slowest_i] - median
        if differential <= budget:
            return
        worker = (
            addresses[slowest_i]
            if slowest_i < len(addresses)
            else f"worker-{slowest_i}"
        )
        HEALTH.flag_straggler(
            worker, differential, operation_id=operation_id,
            gang_size=len(exit_at),
        )
        if os.environ.get(
            "COVALENT_TPU_STRAGGLER_REDIAL", ""
        ).strip().lower() in ("1", "on", "true", "yes"):
            task = asyncio.ensure_future(self._redial_straggler(worker))
            task.add_done_callback(
                lambda t: None if t.cancelled() else t.exception()
            )

    async def health_canary(self, address: str) -> bool:
        """Cheap gray-failure readmission probe for one worker: a single
        agent ping round trip (no task, no slot).  The fleet scheduler
        calls this through the pool while a worker is health-quarantined;
        True readmits it to PROBATION where real traffic re-earns (or
        re-loses) its score."""
        client = self._agents.get(address)
        if client is None or not client.alive:
            return False
        try:
            await client.ping(timeout=10.0)
            return True
        except (AgentError, TransportError, asyncio.TimeoutError, OSError):
            return False

    async def _redial_straggler(self, address: str) -> None:
        """Evict one straggling worker's pooled channel (eager redial).

        Scoped single-address analog of :meth:`_discard_workers`: the
        NEXT electron re-dials, re-preflights, and re-probes CAS on a
        fresh channel — a slow transport path (degraded NIC, dying SSH
        mux) stops taxing every subsequent gang.
        """
        await self._drain_cleanup_tasks()
        key = self._pool_key(address)
        discarded = await self._pool.discard(key)
        client = self._agents.pop(address, None)
        if client is not None:
            await client.close()
        self._preflighted.discard(key)
        self._wire_codecs.pop(key, None)
        self._cas.forget(key)
        obs_events.emit(
            "fleet.straggler_redial",
            worker=address,
            discarded=bool(discarded),
        )

    async def get_status(
        self,
        conn: Transport,
        remote_result_file: str,
        pid: int | None = None,
        pid_file: str | None = None,
        hb_file: str | None = None,
        on_heartbeat: Callable[[dict], None] | None = None,
    ) -> TaskStatus:
        """Combined result-exists + process-alive probe, one round-trip.

        Fixes the reference's brittle ``ls``-output string compare
        (ssh.py:402-406) with ``test -f`` exit status, and detects a crashed
        harness instead of polling forever.  When the dispatcher lost the
        pid (e.g. an agent channel died mid-launch), the pid file the
        harness writes at startup is the liveness source instead; a missing
        pid file reports STARTING, which the poller tolerates only for a
        bounded grace window.

        ``hb_file`` piggybacks the worker's latest heartbeat snapshot on
        the SAME round trip (its JSON precedes the status token on stdout);
        a parsed beat is handed to ``on_heartbeat`` — this is how the
        polling path gets worker liveness for free.
        """
        # Zombie-aware liveness: `kill -0` answers true for a zombie, and a
        # nohup-launched harness whose spawning shell already exited can
        # stay a zombie indefinitely on hosts without a reaping init
        # (containers).  A TERM-killed (e.g. preempted) worker must read
        # DEAD, not RUNNING-forever, so the probe checks the process STATE
        # first; hosts without `ps` fall through to the kill -0 answer.
        if pid is not None:
            liveness = (
                f"elif ps -o state= -p {pid} 2>/dev/null | grep -q Z; "
                "then echo DEAD; "
                f"elif kill -0 {pid} 2>/dev/null; then echo RUNNING; "
            )
        elif pid_file is not None:
            quoted = shlex.quote(pid_file)
            liveness = (
                f"elif test -s {quoted}; then "
                f"if ps -o state= -p \"$(cat {quoted})\" 2>/dev/null "
                "| grep -q Z; then echo DEAD; "
                f"elif kill -0 \"$(cat {quoted})\" 2>/dev/null; "
                "then echo RUNNING; else echo DEAD; fi; "
                "elif true; then echo STARTING; "
            )
        else:
            liveness = "elif true; then echo RUNNING; "
        hb_clause = ""
        if hb_file:
            quoted_hb = shlex.quote(hb_file)
            # `echo` terminates the snapshot (written without a newline) so
            # the status token below always sits alone on the last line.
            hb_clause = f"test -s {quoted_hb} && cat {quoted_hb} && echo; "
        probe = (
            hb_clause
            + f"if test -f {shlex.quote(remote_result_file)}; then echo READY; "
            + liveness
            + "else echo DEAD; fi"
        )
        result = await conn.run(probe)
        lines = result.stdout.strip().splitlines()
        token = lines[-1] if lines else ""
        if on_heartbeat is not None:
            for line in lines[:-1]:
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    heartbeat = json.loads(line)
                except ValueError:
                    continue
                if isinstance(heartbeat, dict):
                    on_heartbeat(heartbeat)
                break
        try:
            return TaskStatus(token)
        except ValueError:
            raise TransportError(
                f"status probe on {conn.address} failed: {result.stderr.strip()!r}"
            )

    #: How long a task may stay STARTING (no result, no pid file) before it
    #: is declared DEAD: covers the launch window between the run command
    #: landing and the harness's first act of writing its pid file.
    STARTING_GRACE_S = 30.0

    #: With no task_timeout set, log a still-running reminder this often so
    #: a silently-stuck electron is at least visible on billed TPU time.
    WATCHDOG_LOG_INTERVAL_S = 600.0

    #: Liveness-probe budget for a cached agent channel (a healthy resident
    #: runtime pongs in channel-RTT; a hung one must not stall dispatch).
    AGENT_PING_TIMEOUT_S = 10.0

    #: TERM-to-KILL grace when task_timeout escalation reaps the gang.
    TIMEOUT_KILL_GRACE_S = 1.0

    async def _wait_while_running(
        self,
        probe: Callable,
        timeout: float | None = None,
    ) -> tuple[TaskStatus, int]:
        """Adaptive-backoff wait shared by every poller.

        Calls ``probe() -> (status, blamed_worker)`` until it stops
        reporting RUNNING/STARTING.  Replaces the reference's fixed
        15 s × 5-retry loop (ssh.py:408-432): the interval starts at 50 ms
        and doubles up to ``poll_freq``, so short electrons pay milliseconds
        of latency, not seconds, and there is no artificial retry ceiling —
        a live process keeps being awaited.  When ``timeout`` (default
        ``task_timeout``; 0 disables) elapses, returns the last RUNNING
        status and lets the caller decide what a timeout means.  STARTING —
        liveness unknowable because the pid file hasn't appeared — is
        tolerated only for ``STARTING_GRACE_S`` and then becomes DEAD, so a
        harness that died before its first write cannot be polled forever.
        """
        if timeout is None:
            timeout = self.task_timeout
        interval = 0.05
        waited = 0.0
        starting_for = 0.0
        last_watchdog = 0.0
        while True:
            status, blamed = await probe()
            if status not in (TaskStatus.RUNNING, TaskStatus.STARTING):
                return status, blamed
            if status is TaskStatus.STARTING:
                if starting_for >= self.STARTING_GRACE_S:
                    app_log.error(
                        "task has no result and no pid file after %.0fs; "
                        "declaring worker %d dead", starting_for, blamed,
                    )
                    return TaskStatus.DEAD, blamed
                starting_for += interval
            else:
                starting_for = 0.0
            if timeout and waited >= timeout:
                return TaskStatus.RUNNING, blamed
            if (
                not timeout
                and waited - last_watchdog >= self.WATCHDOG_LOG_INTERVAL_S
            ):
                last_watchdog = waited
                app_log.warning(
                    "task still running after %.0fs with no task_timeout set",
                    waited,
                )
            await asyncio.sleep(interval)
            waited += interval
            interval = min(interval * 2, float(self.poll_freq))

    def _tolerant_status(self, max_consecutive: int = 3) -> Callable:
        """Wrap ``get_status`` with bounded tolerance for garbled probes.

        A single corrupted status line on a flaky control channel must not
        abort a long-running task (the probe repeats anyway); only
        ``max_consecutive`` failures in a row — a genuinely broken channel —
        re-raise the ``TransportError``.  Per-key state so each worker's
        channel is judged independently.
        """
        failures: dict[Any, int] = {}

        async def probe_once(
            key, conn, path, pid, pid_file=None, hb_file=None,
            on_heartbeat=None,
        ) -> TaskStatus:
            try:
                status = await self.get_status(
                    conn, path, pid, pid_file,
                    hb_file=hb_file, on_heartbeat=on_heartbeat,
                )
            except TransportError:
                failures[key] = failures.get(key, 0) + 1
                if failures[key] >= max_consecutive:
                    raise
                return TaskStatus.RUNNING
            failures[key] = 0
            return status

        return probe_once

    async def _poll_task(
        self,
        conn: Transport,
        remote_result_file: str,
        pid: int | None = None,
        pid_file: str | None = None,
    ) -> TaskStatus:
        """Wait for one worker's result; ``task_timeout`` expiry reports
        TIMEOUT so the caller can escalate (kill the gang, classify, retry)
        instead of conflating it with a crashed harness."""
        tolerant = self._tolerant_status()

        async def probe() -> tuple[TaskStatus, int]:
            return await tolerant(0, conn, remote_result_file, pid, pid_file), 0

        status, _ = await self._wait_while_running(probe)
        return TaskStatus.TIMEOUT if status is TaskStatus.RUNNING else status

    async def _poll_all(
        self, conns: list[Transport], staged: StagedTask, pids: dict[str, int]
    ) -> tuple[TaskStatus, int]:
        """Wait for worker 0's result while watching every worker's liveness.

        Returns ``(status, worker_index)`` where the index identifies which
        worker to blame for a non-READY outcome.  A non-zero worker that
        dies before the distributed barrier (e.g. a failed pip install)
        would otherwise leave process 0 hung in
        ``jax.distributed.initialize`` until its coordination timeout; this
        poller turns that into a fast, correctly-attributed failure
        (all-or-nothing semantics, SURVEY §5 failure detection).
        """
        addresses = self._worker_addresses()
        tolerant = self._tolerant_status()
        op = staged.operation_id
        liveness = self.heartbeat_interval > 0

        def hb_recorder(worker: str):
            if not liveness:
                return None
            return lambda hb: self._record_heartbeat(op, worker, hb)

        async def probe() -> tuple[TaskStatus, int]:
            statuses = await asyncio.gather(
                tolerant(
                    0,
                    conns[0],
                    staged.remote_result_file,
                    pids.get(addresses[0]),
                    f"{staged.remote_pid_file}.0",
                    hb_file=staged.remote_hb_file(0) if liveness else None,
                    on_heartbeat=hb_recorder(addresses[0]),
                ),
                *(
                    # Workers 1..N-1 are "done" at their marker file — same
                    # probe shape as worker 0's result file.
                    tolerant(
                        i,
                        conns[i],
                        f"{staged.remote_result_file}.done.{i}",
                        pids.get(addresses[i]),
                        f"{staged.remote_pid_file}.{i}",
                        hb_file=(
                            staged.remote_hb_file(i) if liveness else None
                        ),
                        on_heartbeat=hb_recorder(addresses[i]),
                    )
                    for i in range(1, len(conns))
                ),
            )
            if statuses[0] not in (TaskStatus.RUNNING, TaskStatus.STARTING):
                return statuses[0], 0
            for i, status in enumerate(statuses[1:], start=1):
                if status is TaskStatus.DEAD:
                    return TaskStatus.DEAD, i
            # Any worker still in its launch window keeps the whole task in
            # STARTING so the bounded grace (not an infinite RUNNING poll)
            # governs a harness that died before writing its pid file.
            for i, status in enumerate(statuses):
                if status is TaskStatus.STARTING:
                    return TaskStatus.STARTING, i
            # Liveness: every process looked alive, but a worker that WAS
            # heartbeating and has gone silent past its threshold is wedged
            # — surface it now, before the hard task_timeout would.
            if liveness:
                stalled = MONITOR.stalled(op)
                if stalled:
                    worker, _silence = stalled[0]
                    blamed = (
                        addresses.index(worker)
                        if worker in addresses
                        else 0
                    )
                    return TaskStatus.STALLED, blamed
            return TaskStatus.RUNNING, 0

        status, blamed = await self._wait_while_running(probe)
        return (
            (TaskStatus.TIMEOUT, 0)
            if status is TaskStatus.RUNNING
            else (status, blamed)
        )

    async def query_result(
        self, conn: Transport, staged: StagedTask, key: str | None = None
    ) -> tuple[Any, BaseException | None]:
        """Fetch + unpickle ``(result, exception)`` (reference: ssh.py:434-458).

        With an explicitly pinned codec the result rides the wire
        compressed (codec.get_file) — one extra pack round trip, so it is
        never engaged by the ``auto`` policy, whose wins must be free.
        ``key`` is the worker's pool key (the identity codecs were
        negotiated under — the *configured* address, which can differ
        from ``conn.address``); callers without one get the raw path.
        """
        codec = (
            self._codec_for(key, conn)
            if key is not None and self.compress in ("zlib", "zstd")
            else None
        )
        await codec_mod.get_file(
            conn, staged.remote_result_file, staged.local_result_file,
            codec=codec, python_path=self.python_path,
        )
        return load_result(staged.local_result_file)

    async def _remote_log_tail(self, conn: Transport, staged: StagedTask) -> str:
        """Worker logs are the #1 debugging surface on pods (SURVEY §5)."""
        result = await conn.run(f"tail -n 50 {shlex.quote(staged.remote_log_file)}")
        return result.stdout.strip()

    async def _remote_telemetry_tail(
        self, conn: Transport, staged: StagedTask, process_id: int
    ) -> str:
        """Last worker-side telemetry lines for a failure report.

        Events buffered in the worker-local side-band file while no agent
        channel was attached (or on the poll path, which never streams)
        surface here with the failure instead of needing a post-mortem
        scp.  Best-effort: an empty string when telemetry is off or the
        tail itself fails.
        """
        if self.heartbeat_interval <= 0:
            return ""
        path = staged.remote_telemetry_file(process_id)
        try:
            result = await conn.run(
                f"tail -n 20 {shlex.quote(path)} 2>/dev/null; true"
            )
        except (TransportError, OSError):
            return ""
        return result.stdout.strip()

    def attempts_of(self, operation_id: str) -> int:
        """Attempts the given (base) operation consumed; pops the record.

        The workflow runner calls this right after ``run()`` settles to
        stamp per-node retry counts onto node events.
        """
        return self._op_attempts.pop(operation_id, 1)

    def _is_cancelled(self, operation_id: str) -> bool:
        """Whether this operation — or its retry lineage — was cancelled.

        Retry attempts run under ``{base}.r{n}`` operation ids; a caller
        cancelling the base id (the only id the workflow layer knows) must
        reach whichever attempt is currently in flight.
        """
        if operation_id in self._cancelled_ops:
            return True
        base = operation_id.split(".r", 1)[0]
        return base != operation_id and base in self._cancelled_ops

    async def cancel(
        self, operation_id: str | None = None, mark: bool = True
    ) -> None:
        """Kill the remote harness process on every worker.

        Implements what the reference stubs with ``NotImplementedError``
        (ssh.py:460-464).  ``operation_id`` also matches retry attempts of
        that operation (``{id}.r{n}``), so cancelling a dispatch reaches a
        gang that is mid-retry.

        ``mark=False`` is the executor's own gang teardown (a failed or
        timed-out attempt being cleaned up for retry): the pids die but the
        operation is NOT flagged as user-cancelled — a concurrent real
        ``cancel()``'s mark must survive the teardown so the retry driver
        still sees it.
        """
        if operation_id:
            targets = {
                op_id: pids
                for op_id, pids in self._active.items()
                if op_id == operation_id
                or op_id.startswith(f"{operation_id}.r")
            }
            if not targets:
                targets = {operation_id: {}}
            # Mark the requested id too: an attempt not yet in _active (or
            # the retry driver between attempts) must still see the cancel.
            if mark:
                self._cancelled_ops.add(operation_id)
        else:
            targets = dict(self._active)
        for op_id, pids in targets.items():
            # Flag FIRST: the moment a kill lands, the op's poller can see
            # DEAD and must classify it as cancelled, not failed (a failure
            # with run_local_on_dispatch_fail would re-run the body).
            if mark:
                self._cancelled_ops.add(op_id)
            obs_events.emit(
                "task.cancel_requested", operation_id=op_id, pids=pids
            )
            for address, pid in pids.items():
                try:
                    conn = await self._client_connect(address)
                    # `-s TERM -- -pid` (not `-TERM -- -pid`): dash's kill
                    # builtin rejects the latter, which silently reduced
                    # this to a direct-pid kill on dash /bin/sh workers.
                    await conn.run(
                        f"kill -s TERM -- -{pid} 2>/dev/null "
                        f"|| kill -s TERM {pid}"
                    )
                except Exception as err:  # noqa: BLE001 - best-effort teardown
                    app_log.warning("cancel: could not kill %s on %s: %s", pid, address, err)
            self._active.pop(op_id, None)

    async def _escalate_timeout(
        self,
        operation_id: str,
        conns: list[Transport],
        addresses: list[str],
        pids: dict[str, int],
        reason: str = "timeout",
    ) -> None:
        """Reap a timed-out (or stalled) gang: TERM every worker's process
        group, give ``TIMEOUT_KILL_GRACE_S`` for cleanup handlers, then
        KILL survivors.

        The harness calls ``setsid`` at startup, so ``kill -- -pid``
        reaches the user function's own children too — no orphan pids left
        accruing billed TPU time.  The KILL pass is what makes this safe
        for stalls: a truly wedged (e.g. stopped) process may never act on
        TERM.  Deliberately does NOT go through :meth:`cancel`: escalation
        is a *failure* being classified for retry, and must never read as
        a user cancellation.
        """
        obs_events.emit(
            "task.timeout_escalated"
            if reason == "timeout"
            else "task.stall_escalated",
            operation_id=operation_id,
            timeout_s=self.task_timeout,
            **({"stall_after_s": self._stall_after()}
               if reason != "timeout" else {}),
            pids=pids,
        )
        if reason == "timeout":
            app_log.warning(
                "task %s exceeded task_timeout=%.1fs; killing the gang (%s)",
                operation_id, self.task_timeout, pids,
            )
        else:
            app_log.warning(
                "task %s stalled (no heartbeat for %.1fs); killing the "
                "gang (%s)",
                operation_id, self._stall_after(), pids,
            )

        def group_kill(pid: int, sig: str) -> str:
            # `kill -s SIG -- -pid`: the one group-kill spelling both bash
            # and dash builtins accept (dash rejects `kill -SIG -- -pid`
            # with "Illegal number").  Direct-pid kill rides along for the
            # pre-setsid launch window.
            return (
                f"kill -s {sig} -- -{pid} 2>/dev/null; "
                f"kill -s {sig} {pid} 2>/dev/null; true"
            )

        async def term_one(conn: Transport, address: str) -> None:
            pid = pids.get(address)
            if pid is not None:
                await conn.run(group_kill(pid, "TERM"))

        async def kill_survivor(conn: Transport, address: str) -> None:
            pid = pids.get(address)
            if pid is None:
                return
            await conn.run(
                f"if kill -0 {pid} 2>/dev/null; "
                f"then {group_kill(pid, 'KILL')}; fi; true"
            )

        await asyncio.gather(
            *(term_one(c, a) for c, a in zip(conns, addresses)),
            return_exceptions=True,
        )
        await asyncio.sleep(self.TIMEOUT_KILL_GRACE_S)
        await asyncio.gather(
            *(kill_survivor(c, a) for c, a in zip(conns, addresses)),
            return_exceptions=True,
        )
        self._active.pop(operation_id, None)

    async def _logged_cleanup(
        self, conns: list[Transport], staged: StagedTask
    ) -> None:
        """Deferred-cleanup wrapper: nobody awaits the task's exception, so
        a failure must reach the log (not just asyncio's GC warning)."""
        try:
            await self.cleanup(conns, staged)
        except Exception as err:  # noqa: BLE001
            app_log.warning(
                "deferred cleanup for %s failed: %s", staged.operation_id, err
            )

    async def cleanup(
        self, conns: list[Transport], staged: StagedTask
    ) -> None:
        """Delete per-operation staged files locally and on every worker
        (ref: ssh.py:284-315).

        Dedupable CAS artifacts (function pickle, harness) deliberately
        survive cleanup: they ARE the remote cache — deleting them would
        invalidate the per-connection present sets mid-flight for
        concurrent electrons and force every repeat dispatch to re-upload
        (the pre-flight TTL prune bounds their long-tail growth instead).
        Spec files, though CAS-named, embed the operation id and so can
        never dedupe across electrons — they are removed with the other
        per-operation files (result, done markers, log, pid), and their
        digests evicted from the CAS index so a retried operation
        re-uploads instead of launching against a missing spec.
        """
        for path in [
            staged.function_file,
            staged.local_result_file,
            *staged.local_spec_files,
        ]:
            try:
                os.remove(path)
            except FileNotFoundError:
                pass
        for digest in staged.spec_digests:
            self._cas.forget_digest(digest)

        async def clean_worker(process_id: int, conn: Transport) -> None:
            files = [
                staged.remote_spec_file(process_id),
                staged.remote_log_file,
                f"{staged.remote_pid_file}.{process_id}",
                # Liveness/telemetry side-band artifacts.
                staged.remote_telemetry_file(process_id),
                staged.remote_hb_file(process_id),
                f"{staged.remote_pid_file}.{process_id}.metrics",
            ]
            if staged.resume_artifact is not None:
                # Op-scoped resume bundle (shipped outside the CAS): the
                # harness read it at startup; nothing dedupes against it.
                files.append(staged.resume_artifact[1])
            if process_id == 0:
                files.append(staged.remote_result_file)
                # Pinned-codec downloads stage a packed copy next to the
                # result (codec.get_file); harmless rm -f otherwise.
                files.append(f"{staged.remote_result_file}.z")
            else:
                files.append(f"{staged.remote_result_file}.done.{process_id}")
            result = await conn.remove(files)
            if result.exit_status != 0:
                app_log.warning(
                    "cleanup on %s: %s", conn.address, result.stderr.strip()
                )
            # Keep the op's dedupable artifacts hot + age out stale CAS
            # entries (best-effort: the clause ends in `true`, and a failed
            # round-trip must not fail a cleanup that already succeeded).
            try:
                maintained = await conn.run(
                    self._cas_maintenance_command(staged)
                )
            except (TransportError, OSError) as err:
                app_log.debug(
                    "CAS maintenance on %s skipped: %s", conn.address, err
                )
            else:
                if self.cas_max_bytes > 0:
                    for token in (maintained.stdout or "").split():
                        if token.startswith("CAS_EVICTED="):
                            try:
                                evicted = int(token.split("=", 1)[1])
                            except ValueError:
                                continue
                            if evicted > 0:
                                CAS_EVICTIONS_TOTAL.labels(
                                    site="remote"
                                ).inc(evicted)
                            break

        await asyncio.gather(
            *(clean_worker(i, c) for i, c in enumerate(conns)),
            return_exceptions=True,
        )

    def _guard_event_loop(self) -> None:
        """Reset loop-bound state when the executor moves between loops.

        Pooled transports, agent channels, and their locks/conditions are
        bound to the event loop that created them.  A library user driving
        the executor from successive ``asyncio.run`` calls would otherwise
        hit dead-loop errors on the second run; the workflow layer avoids
        this by using one shared dispatcher loop, so this guard is the
        safety net for direct API use.
        """
        loop = asyncio.get_running_loop()
        bound = getattr(self, "_bound_loop", None)
        if bound is None:
            self._bound_loop = loop
            return
        if bound is loop:
            return
        app_log.warning(
            "TPUExecutor reused on a new event loop; abandoning pooled "
            "transports and resident agent channels from the previous loop"
        )
        if not bound.is_closed() and bound.is_running():
            # Best-effort teardown on the loop that owns the resources.
            # A caller-shared pool (_owns_pool False) is NOT closed: other
            # executors may be mid-electron on the old loop; we only drop
            # our reference to it.
            old_agents = dict(self._agents)
            old_pool = self._pool if self._owns_pool else None

            async def teardown() -> None:
                for client in old_agents.values():
                    if client is not None:
                        await client.close()
                if old_pool is not None:
                    await old_pool.close_all()

            future = asyncio.run_coroutine_threadsafe(teardown(), bound)

            def _log_teardown(f) -> None:
                if f.cancelled():
                    app_log.warning("old-loop teardown was cancelled")
                elif f.exception() is not None:
                    app_log.warning("old-loop teardown failed: %s", f.exception())

            future.add_done_callback(_log_teardown)
        elif not bound.is_closed():
            # Stopped-but-open loop: scheduling a coroutine on it would
            # never run (and warn about never-awaited coroutines); the
            # remote pool-server/agent processes are abandoned instead, and
            # their own channel-loss handling reaps them.
            app_log.warning(
                "previous event loop is stopped; abandoning its pooled "
                "transports and agent channels without teardown"
            )
        self._pool = TransportPool()
        self._owns_pool = True
        self._agents = {}
        self._agent_locks = {}
        if self._cleanup_tasks:
            # Old-loop tasks can't be awaited from here; the staged files
            # they would have removed leak, so say so.
            app_log.warning(
                "dropping %d pending deferred-cleanup task(s) from the "
                "previous event loop; their staged files may leak",
                len(self._cleanup_tasks),
            )
        self._cleanup_tasks = set()
        self._preflighted.clear()
        self._wire_codecs.clear()
        self._prewarmed = False
        # CASIndex holds loop-bound locks/futures; present-set knowledge is
        # cheap to rebuild via one probe per redialed connection.  The RPC
        # registry's futures are loop-bound too, and its resident runtimes
        # were abandoned with the agents above.
        self._cas = CASIndex()
        self._fn_registry = FnRegistry()
        self._bound_loop = loop

    async def close(self) -> None:
        """Release agent channels + pooled transports (once per executor)."""
        # From here on, run() stops deferring cleanup (inline instead): a
        # task scheduled after this drain begins would race the pool close.
        self._closing = True
        unregister_status_provider(self._ops_provider_name)
        unregister_profile_provider(self._ops_provider_name)
        pending = [t for t in self._cleanup_tasks if not t.done()]
        loop = asyncio.get_running_loop()
        foreign = [t for t in pending if t.get_loop() is not loop]
        if foreign:
            # close() called from a fresh asyncio.run before any run():
            # tasks bound to the old loop can't be awaited here (gather
            # would raise), only dropped — same contract as the loop guard.
            app_log.warning(
                "dropping %d deferred-cleanup task(s) bound to a previous "
                "event loop; their staged files may leak",
                len(foreign),
            )
        await self._drain_cleanup_tasks(until_empty=True)
        self._cleanup_tasks.clear()
        for client in self._agents.values():
            if client is not None:
                await client.close()
        self._agents.clear()
        if self._owns_pool:
            await self._pool.close_all()

    # ------------------------------------------------------------------ #
    # Orchestrator                                                       #
    # ------------------------------------------------------------------ #

    def _resolve_dispatch_mode(self, task_metadata: dict) -> str:
        """Effective mode for one electron: metadata overrides config.

        An invalid metadata value falls back to the executor's configured
        (constructor-validated) mode with a warning — NOT silently to
        "launch", which would quietly strip the fast path from an
        executor pinned to ``rpc`` over a typo.
        """
        raw = task_metadata.get("dispatch_mode")
        if raw is not None:
            mode = str(raw).strip().lower()
            if mode in ("launch", "auto", "rpc"):
                return mode
            app_log.warning(
                "ignoring invalid electron dispatch_mode %r "
                '(expected "launch", "auto" or "rpc"); using %r',
                raw, self.dispatch_mode,
            )
        return self.dispatch_mode

    def _rpc_preselect(self, task_metadata: dict) -> bool:
        """Static RPC eligibility, decided before an attempt starts.

        RPC mode runs the electron inside the resident worker process, so
        it is reserved for the shapes that path can serve faithfully:
        single-worker gangs (multi-host electrons need the per-process
        ``jax.distributed`` bootstrap only the launch harness performs),
        no pip installs (process-scoped), and an agent policy that allows
        the pool runtime.  ``profile_dir`` no longer disqualifies: the
        resident runtime drives ``jax.profiler`` itself via the
        profile_start/profile_stop verbs, so the warm fast path — the one
        carrying the interesting traffic — is exactly what gets profiled.
        Under a chaos plan
        ``auto`` defers to launch — fault budgets target the launch
        protocol's round trips — while an explicit ``rpc`` pin keeps the
        fast path so chaos tests can kill resident workers mid-invoke.
        Dynamic conditions (no runtime on the worker) fall back later via
        :class:`_RpcUnavailable`.
        """
        mode = self._resolve_dispatch_mode(task_metadata)
        if mode == "launch":
            return False
        if self.use_agent not in (True, "auto", "pool"):
            return False
        if task_metadata.get("pip_deps"):
            return False
        if self._chaos is not None and mode != "rpc":
            return False
        if self.checkpoint_interval_s > 0 and mode != "rpc":
            # Cooperative checkpointing needs the launch harness: the
            # interval thread and the SIGTERM handler (main-thread signal
            # API) belong to a per-task process, not a shared resident
            # runtime hosting concurrent invocations.  An explicit "rpc"
            # pin wins (same contract as the chaos gate above) — the
            # electron keeps the fast path and simply isn't checkpointed.
            return False
        # Worker-count check without triggering discovery: pod slices
        # (explicit multi-worker lists or tpu_name topologies) launch.
        if self.tpu_name or len(self.workers) > 1:
            return False
        return True

    def _plan_retry(
        self,
        attempt: int,
        deadline: Deadline,
        reason: str | None = None,
        error: BaseException | None = None,
        message: str = "",
        conns: list[Transport] | None = None,
    ) -> _RetryDispatch | None:
        """A :class:`_RetryDispatch` when the budget allows one, else None.

        ``error`` (when given) is classified first: a permanent fault (user
        code, config errors, cancellation) never yields a retry regardless
        of budget.  ``reason`` overrides the classified label for metrics.
        """
        fault = FaultClass.TRANSIENT
        label = reason
        if error is not None:
            fault, classified = classify_error(error)
            # The site's label (connect/launch/channel) names WHERE it
            # failed; circuit_open is more specific — an operator alerting
            # on quarantine-driven retries must be able to tell them from
            # ordinary connect failures.
            label = (
                classified
                if classified == "circuit_open"
                else reason or classified
            )
        if not self._retry_policy.should_retry(attempt, fault, deadline):
            return None
        label = label or "transient"
        # First retry reuses pooled channels (cheap, covers one-off blips);
        # later retries — and channel-shaped failures — redial from scratch
        # in case the worker was recreated behind the same address.  A
        # preempted worker's channel is gone by definition (the VM is being
        # reclaimed), so preemption always redials.
        redial = attempt >= 1 or label in ("channel", "worker_preempted")
        return _RetryDispatch(
            label, message or str(error or "transient failure"), redial,
            conns=conns,
        )

    async def run(
        self,
        function: Callable,
        args: list | tuple,
        kwargs: dict,
        task_metadata: dict,
    ) -> Any:
        """Full electron lifecycle with gang-level retry.

        Drives :meth:`_run_attempt` under the resilience policy: a
        transient failure (channel death, connect/preflight failure, worker
        death without a result, timeout) tears the whole gang down and
        re-submits the electron under a fresh operation id
        (``{base}.r{n}``) after a jittered backoff — re-staging is nearly
        free thanks to the CAS layer.  Permanent faults (user-code
        exceptions, cancellation) and an exhausted budget fall through to
        the pre-existing behavior: the fallback policy or the original
        error.  Degradation order: retry -> redial/alternate connection ->
        ``run_local_on_dispatch_fail``.
        """
        args = tuple(args or ())
        kwargs = dict(kwargs or {})
        dispatch_id = task_metadata.get("dispatch_id", "dispatch")
        node_id = task_metadata.get("node_id", 0)
        base_operation_id = f"{dispatch_id}_{node_id}"
        policy = self._retry_policy
        deadline = Deadline(policy.wall_budget)
        # Write-ahead dispatch intent: a dispatcher that dies mid-run
        # leaves this electron discoverable (with its retry lineage) for
        # the successor's recovery report; the terminal record clears it.
        journal_mod.record(
            "task", op=base_operation_id, dispatch_id=dispatch_id,
            node=node_id, t_dispatch=time.time(),
        )
        try:
            result = await self._run_with_retries(
                function, args, kwargs, task_metadata,
                base_operation_id, policy, deadline,
            )
        except BaseException as err:
            journal_mod.record(
                "task_terminal", op=base_operation_id,
                outcome=(
                    "cancelled"
                    if isinstance(err, asyncio.CancelledError)
                    else "error"
                ),
                error=repr(err), sync=True,
            )
            raise
        else:
            journal_mod.record(
                "task_terminal", op=base_operation_id, outcome="ok",
                sync=True,
            )
            return result
        finally:
            # cancel(base_id) marks the base id so whichever attempt is in
            # flight sees it; the per-attempt finally only clears attempt
            # ids, so the base mark must die with the run (else a later
            # dispatch reusing the id would read as pre-cancelled).
            self._cancelled_ops.discard(base_operation_id)
            # Checkpoint lineage state dies with the run: a later dispatch
            # reusing the operation id is NEW work and must never resume
            # from (or dedup against) this run's checkpoints.
            self._resume_plans.pop(base_operation_id, None)
            self._ckpt_records.pop(base_operation_id, None)
            self._ckpt_seen = {
                k for k in self._ckpt_seen if k[0] != base_operation_id
            }

    async def _run_with_retries(
        self,
        function: Callable,
        args: tuple,
        kwargs: dict,
        task_metadata: dict,
        base_operation_id: str,
        policy: RetryPolicy,
        deadline: Deadline,
    ) -> Any:
        attempt = 0
        # One span — one TRACE — for the whole electron, however many gang
        # attempts it takes: each attempt's `executor.run` root parents
        # here (or under the ambient workflow.node span when dispatched
        # through the runner), so a single trace id follows the electron
        # across retries with the attempt number as a span attribute.
        task_span = Span(
            "executor.task",
            {
                "operation_id": base_operation_id,
                "max_retries": policy.max_retries,
            },
        )
        task_span.__enter__()
        try:
            while True:
                operation_id = (
                    base_operation_id
                    if attempt == 0
                    else f"{base_operation_id}.r{attempt}"
                )
                self.last_attempts = attempt + 1
                if len(self._op_attempts) > 1024:  # unread (direct API use)
                    self._op_attempts.pop(next(iter(self._op_attempts)))
                self._op_attempts[base_operation_id] = attempt + 1
                journal_mod.record(
                    "task", op=base_operation_id,
                    operation_id=operation_id, attempt=attempt + 1,
                )
                try:
                    if self._rpc_preselect(task_metadata):
                        try:
                            return await self._run_attempt_rpc(
                                function, args, kwargs, task_metadata,
                                operation_id, attempt, deadline,
                            )
                        except _RpcUnavailable as unavailable:
                            # Same attempt, launch path: the gang has no
                            # resident runtime to execute by digest.
                            obs_events.emit(
                                "task.rpc_fallback",
                                operation_id=operation_id,
                                reason=str(unavailable),
                            )
                            app_log.info(
                                "task %s: RPC dispatch unavailable (%s); "
                                "using the launch path",
                                operation_id, unavailable,
                            )
                    return await self._run_attempt(
                        function, args, kwargs, task_metadata,
                        operation_id, attempt, deadline,
                    )
                except _RetryDispatch as retry:
                    TASK_RETRIES_TOTAL.labels(reason=retry.reason).inc()
                    delay = policy.delay(attempt)
                    remaining = deadline.remaining()
                    if remaining is not None:
                        # The wall budget bounds when new attempts may
                        # START (an in-flight attempt is never killed by
                        # it): never sleep past it, and the next failure's
                        # should_retry sees the expired deadline and takes
                        # the terminal path.
                        delay = min(delay, remaining)
                    app_log.warning(
                        "task %s attempt %d/%d failed (%s: %s); retrying in "
                        "%.2fs%s",
                        base_operation_id, attempt + 1,
                        policy.max_retries + 1,
                        retry.reason, retry.message, delay,
                        " after redial" if retry.redial else "",
                    )
                    obs_events.emit(
                        "task.retry",
                        operation_id=operation_id,
                        attempt=attempt + 1,
                        max_retries=policy.max_retries,
                        reason=retry.reason,
                        delay_s=round(delay, 3),
                        redial=retry.redial,
                        error=retry.message,
                    )
                    if self.checkpoint_interval_s > 0:
                        # Elastic resume: find (and digest-verify) the
                        # lineage's newest complete checkpoint so the next
                        # attempt restores instead of recomputing.  Runs
                        # BEFORE the discard: a preempted gang's surviving
                        # channels are still open inside the grace window
                        # and answer the manifest probe in one round trip.
                        # Never fatal — a failed discovery just means a
                        # cold restart, which is what retries always did.
                        try:
                            await self._discover_resume(
                                base_operation_id, retry.conns
                            )
                        except Exception as err:  # noqa: BLE001
                            app_log.debug(
                                "resume discovery for %s failed: %s",
                                base_operation_id, err,
                            )
                    if retry.redial and retry.conns:
                        await self._discard_workers(retry.conns)
                    if delay:
                        await asyncio.sleep(delay)
                    if self._is_cancelled(base_operation_id):
                        raise asyncio.CancelledError(
                            f"task {base_operation_id} cancelled between "
                            "retries"
                        )
                    attempt += 1
        finally:
            task_span.set_attribute("attempts", attempt + 1)
            task_span.end()

    async def _run_attempt(
        self,
        function: Callable,
        args: tuple,
        kwargs: dict,
        task_metadata: dict,
        operation_id: str,
        attempt: int,
        deadline: Deadline,
    ) -> Any:
        """One full dispatch attempt (reference orchestrator: ssh.py:466-591).

        Every stage runs in its own span (``executor.<stage>``) under one
        ``executor.run`` root, so each electron leaves a full trace in the
        event stream and per-stage histograms in the metrics registry
        (the reference captured none — SURVEY §5 tracing gap).  Stage
        timings still land in ``self.last_timings`` — now on every exit
        path, success or not — for callers of the pre-obs API.  Transient
        failures raise :class:`_RetryDispatch` (per-attempt outcome
        ``retried``) when the budget allows; otherwise the single-shot
        failure semantics are unchanged.
        """
        dispatch_id = task_metadata.get("dispatch_id", "dispatch")
        node_id = task_metadata.get("node_id", 0)
        # The lineage (base operation id) is constant across gang retries:
        # it keys the worker-side checkpoint manifest and the resume plan
        # a retry attempt ships.
        lineage = (
            operation_id
            if attempt == 0
            else operation_id[: -len(f".r{attempt}")]
        )
        resume_plan = self._resume_plans.get(lineage)

        current_remote_workdir = self.remote_workdir
        if self.create_unique_workdir:  # ssh.py:486-491
            current_remote_workdir = os.path.join(
                self.remote_workdir, dispatch_id, f"node_{node_id}"
            )

        self._guard_event_loop()

        root = Span(
            "executor.run",
            {
                "operation_id": operation_id,
                "dispatch_id": dispatch_id,
                "node_id": node_id,
                "transport": self.transport_kind,
                "attempt": attempt,
            },
        )
        root.__enter__()
        _ACTIVE_ELECTRONS.inc()
        obs_events.emit(
            "task.state",
            operation_id=operation_id,
            state="starting",
            trace_id=root.trace_id,
        )
        # Live ops view (/status): stage advances at each lifecycle edge.
        self._op_status[operation_id] = {
            "stage": "starting",
            "mode": "launch",
            "attempt": attempt + 1,
            "trace_id": root.trace_id,
            "dispatch_id": dispatch_id,
            "node_id": node_id,
            "since": time.time(),
        }
        self.last_dispatch_mode = "launch"
        # Worker-side records join this attempt's trace (same trace id
        # across attempts — the parent executor.task span owns it).
        trace_context = context_of(root, attempt=attempt)
        outcome = "failed"
        staged: StagedTask | None = None
        conns: list[Transport] = []
        result_cache_key: str | None = None
        staged_payload: bytes | None = None
        try:
            if self.cache_results:
                # Level-2 memoization sits AHEAD of connect: a hit returns
                # the completed result without touching the transport.  The
                # pickled payload is kept for staging so a cold run pays
                # ONE serialization pass, not two.
                with Span("executor.cache_lookup"):
                    try:
                        staged_payload = await asyncio.to_thread(
                            cloudpickle.dumps, (function, args, kwargs)
                        )
                    except Exception as err:  # noqa: BLE001 - user payloads
                        RESULT_CACHE_TOTAL.labels(
                            result="unpicklable"
                        ).inc()
                        app_log.debug(
                            "result cache: electron not picklable (%s)", err
                        )
                    else:
                        result_cache_key = self._result_cache_key(
                            function, args, kwargs, task_metadata,
                            payload=staged_payload,
                        )
                    if result_cache_key is not None:
                        hit, cached = await asyncio.to_thread(
                            self._result_cache.get, result_cache_key
                        )
                        if hit:
                            obs_events.emit(
                                "task.result_cached",
                                operation_id=operation_id,
                                trace_id=root.trace_id,
                            )
                            outcome = "cached"
                            return cached

            with Span("executor.validate"):
                await self._validate_credentials()

            # Pipelined attempt, leg 1: cloudpickle serialization + spec
            # staging run on a worker thread WHILE the connection dial and
            # pre-flight round-trips are in flight — the two legs share no
            # state beyond the (pre-resolved) worker topology.
            await self._ensure_workers()

            def _stage() -> StagedTask:
                with Span("executor.stage"):
                    return self._write_function_files(
                        operation_id,
                        function,
                        args,
                        kwargs,
                        current_remote_workdir,
                        pip_deps=task_metadata.get("pip_deps", ()),
                        payload=staged_payload,
                        trace=trace_context,
                        lineage=lineage,
                        resume=resume_plan,
                    )

            stage_task = asyncio.create_task(asyncio.to_thread(_stage))
            # Retrieve the staging exception even on paths that never await
            # the task (outer cancellation mid-dial): the error is either
            # re-raised from the awaits below or deliberately secondary.
            stage_task.add_done_callback(
                lambda t: None if t.cancelled() else t.exception()
            )
            self._set_stage(operation_id, "connecting")
            try:
                # Gang acquisition goes through the ownership seam: the
                # attempt machine consumes a warm lease and never touches
                # the transport pool directly (the fleet scheduler holds
                # the same lease type when it owns placement).  `conns`
                # doubles as the dialed out-param so a pre-flight failure
                # still hands this attempt's channels to the retry
                # planner's discard (redial must not reuse them).
                lease = await self.lease_gang(dialed=conns)
                conns = lease.conns
            except (TransportError, OSError, ValueError) as err:
                # Join the staging leg (its own error, if any, is
                # secondary to the connect failure — exactly the error
                # precedence of the pre-pipeline sequential order) and
                # remove the dead attempt's local staging.
                try:
                    doomed: StagedTask | None = await stage_task
                except Exception:  # noqa: BLE001 - connect error wins
                    doomed = None
                if doomed is not None:
                    self._remove_local_staging(doomed)
                retry = self._plan_retry(
                    attempt, deadline, reason="connect", error=err,
                    message=f"could not reach TPU workers: {err}",
                    conns=conns,
                )
                if retry is not None:
                    outcome = "retried"
                    raise retry from err
                result = await self._on_dispatch_fail_async(
                    function,
                    args,
                    kwargs,
                    f"could not reach TPU workers: {err}",
                    operation_id=operation_id,
                )
                outcome = "fallback_local"
                return result
            except BaseException:
                # Cancellation (or an unexpected error) mid-dial: the
                # staging thread is uncancellable and its files would
                # otherwise leak in cache_dir — join it briefly and
                # unlink them before re-raising.
                try:
                    self._remove_local_staging(await stage_task)
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass  # double-cancel or staging's own error: nothing staged
                raise

            # Staging errors (e.g. an unpicklable electron) surface here,
            # after a successful connect — same precedence as before.
            staged = await stage_task
            #: mirror fetches (checkpoint_saved backhaul) resolve this
            #: attempt's transports by operation id.
            self._op_conns[operation_id] = conns

            if resume_plan is not None and staged.resume_artifact:
                # This attempt restores instead of recomputing: the bundle
                # rides the CAS staging road to every worker and the spec
                # points the harness (and the electron's resume_state())
                # at it.
                CHECKPOINT_RESTORES_TOTAL.inc()
                _CHECKPOINT_RESUMED_STEP.set(
                    float(resume_plan.get("step", 0))
                )
                obs_events.emit(
                    "task.resumed",
                    operation_id=operation_id,
                    lineage=lineage,
                    attempt=attempt,
                    step=resume_plan.get("step"),
                    digest=resume_plan.get("digest"),
                    trace_id=root.trace_id,
                )

            self._set_stage(operation_id, "launching")
            try:
                # Leg 2: per-worker upload -> launch pipelines with no
                # global barrier between the stages (worker 0 can launch
                # while worker 7 still uploads); the all-or-nothing launch
                # guarantee is enforced on the far side of the gather.
                pids = await self._dispatch_all(conns, staged)
            except _StageUploadFailed as tag:
                err = tag.__cause__ or tag
                if not isinstance(err, (TransportError, OSError)):
                    # Content faults (CodecIntegrityError: torn/corrupt
                    # payload) are permanent — fail loud, keep the channel.
                    raise err
                # A channel that dies mid-upload is the same transient as
                # one dying mid-poll: tear down, redial, re-stage (CAS
                # makes the repeat cheap).  Without budget the error
                # propagates as before — upload failures never fell back.
                await self._discard_workers(conns)
                retry = self._plan_retry(
                    attempt, deadline, reason="channel", error=err,
                    message=f"artifact upload failed: {err}", conns=conns,
                )
                if retry is not None:
                    outcome = "retried"
                    raise retry from err
                raise err
            except TransportError as err:
                if self._is_cancelled(operation_id):
                    raise asyncio.CancelledError(
                        f"task {operation_id} cancelled during launch"
                    ) from err
                retry = self._plan_retry(
                    attempt, deadline, reason="launch", error=err,
                    message=f"task launch failed: {err}",
                    conns=conns,
                )
                if retry is not None:
                    outcome = "retried"
                    raise retry from err
                # Nonzero-submit routing mirrors ssh.py:553-557.
                result = await self._on_dispatch_fail_async(
                    function,
                    args,
                    kwargs,
                    f"task launch failed: {err}",
                    operation_id=operation_id,
                )
                outcome = "fallback_local"
                return result

            obs_events.emit(
                "task.state",
                operation_id=operation_id,
                state="submitted",
                trace_id=root.trace_id,
                pids=pids,
            )
            addresses = self._worker_addresses()
            for conn, address in zip(conns, addresses):
                # Chaos preemption targeting: a wrapped transport records
                # its worker's process-group leader so a preempt fault can
                # deliver the SIGTERM notice to the right processes.
                notify = getattr(conn, "chaos_notify_pid", None)
                if notify is not None and address in pids:
                    notify(pids[address])
            self._set_stage(operation_id, "executing")
            if self.heartbeat_interval > 0:
                # Liveness bookkeeping for this attempt, then the telemetry
                # side-band on every agent-launched worker (best-effort).
                MONITOR.watch(
                    operation_id,
                    self._stall_after(),
                    workers=addresses,
                    interval=self.heartbeat_interval,
                )
                await self._start_backhaul(operation_id, staged)
            try:
                with Span("executor.execute"):
                    agents = self._op_agents.get(operation_id, [])
                    if agents and all(c is not None and c.alive for c in agents):
                        # Every worker launched through its agent: completion
                        # is pushed, no status round-trips.
                        status, blamed = await self._await_all_agent(
                            agents, conns, staged, pids
                        )
                    else:
                        status, blamed = await self._poll_all(conns, staged, pids)
                if status is not TaskStatus.READY:
                    if self._is_cancelled(operation_id):
                        # cancel() killed the harness: surface cancellation,
                        # never the local-fallback re-run of the body.
                        raise asyncio.CancelledError(
                            f"task {operation_id} cancelled"
                        )
                    if status is TaskStatus.STALLED:
                        # Confirmed verdict (the pollers already re-read
                        # the snapshot ground truth): count it here, not
                        # at suspicion time in the monitor.
                        STALLS_TOTAL.labels(worker=addresses[blamed]).inc()
                    if status in (TaskStatus.TIMEOUT, TaskStatus.STALLED):
                        # Both escalate: kill the whole gang (TERM, grace,
                        # KILL) instead of abandoning RUNNING processes on
                        # billed TPU time.  KILL matters doubly for stalls
                        # — a SIGSTOP'd/wedged harness may never act on
                        # TERM — then the failure classifies as transient
                        # for the retry budget.
                        await self._escalate_timeout(
                            operation_id, conns, addresses, pids,
                            reason=(
                                "timeout"
                                if status is TaskStatus.TIMEOUT
                                else "stall"
                            ),
                        )
                    log_tail = await self._remote_log_tail(conns[blamed], staged)
                    telemetry_tail = await self._remote_telemetry_tail(
                        conns[blamed], staged, blamed
                    )
                    last_beats = MONITOR.last(operation_id)
                    obs_events.emit(
                        "task.failed",
                        operation_id=operation_id,
                        trace_id=root.trace_id,
                        worker=addresses[blamed],
                        status=status.value,
                        log_tail=log_tail,
                        **(
                            {"telemetry_tail": telemetry_tail}
                            if telemetry_tail
                            else {}
                        ),
                        **(
                            {"last_heartbeats": last_beats}
                            if last_beats
                            else {}
                        ),
                    )
                    if status is TaskStatus.TIMEOUT:
                        failure_msg = (
                            f"remote task {operation_id} timed out after "
                            f"{self.task_timeout:.1f}s on "
                            f"{addresses[blamed]}; gang killed; log tail:\n"
                            f"{log_tail}"
                        )
                    elif status is TaskStatus.STALLED:
                        silence = last_beats.get(addresses[blamed], {}).get(
                            "age_s"
                        )
                        failure_msg = (
                            f"remote task {operation_id} stalled on "
                            f"{addresses[blamed]}: process alive but no "
                            f"heartbeat for "
                            f"{silence if silence is not None else '?'}s "
                            f"(threshold {self._stall_after():.1f}s); gang "
                            f"killed; log tail:\n{log_tail}"
                        )
                    else:
                        failure_msg = (
                            f"remote task {operation_id} failed on "
                            f"{addresses[blamed]} ({status.value}); "
                            f"log tail:\n{log_tail}"
                        )
                    preempted = (
                        operation_id in self._preempt_notices
                        or "worker.preempt_notice" in (telemetry_tail or "")
                    )
                    if status is TaskStatus.STALLED:
                        # Route through the classifier: WorkerStalledError
                        # is the liveness layer's fault type, keeping its
                        # own retry-reason label.
                        retry = self._plan_retry(
                            attempt, deadline,
                            error=WorkerStalledError(failure_msg),
                            message=failure_msg, conns=conns,
                        )
                    elif status is not TaskStatus.TIMEOUT and preempted:
                        # The worker announced the SIGTERM preemption
                        # notice before dying: spot reclaim, not a crash —
                        # its own label, and the retry that follows will
                        # resume from the notice-triggered checkpoint.
                        retry = self._plan_retry(
                            attempt, deadline,
                            error=WorkerPreemptedError(failure_msg),
                            message=failure_msg, conns=conns,
                        )
                    else:
                        retry = self._plan_retry(
                            attempt,
                            deadline,
                            reason=(
                                "timeout"
                                if status is TaskStatus.TIMEOUT
                                else "worker_dead"
                            ),
                            message=failure_msg,
                            conns=conns,
                        )
                    if status not in (TaskStatus.TIMEOUT, TaskStatus.STALLED):
                        # Tear the rest of the gang down (escalation already
                        # did for timeouts/stalls) WITHOUT the cancelled
                        # mark: this is failure cleanup, not a user cancel,
                        # and it must not clobber (or fake) one arriving
                        # concurrently.
                        await self.cancel(operation_id, mark=False)
                    if retry is not None:
                        outcome = "retried"
                        raise retry
                    result = await self._on_dispatch_fail_async(
                        function,
                        args,
                        kwargs,
                        failure_msg,
                        operation_id=operation_id,
                        log_tail=log_tail,
                    )
                    outcome = "fallback_local"
                    return result

                if len(conns) > 1:
                    with Span("executor.reap"):
                        await self._await_stragglers(conns, staged, pids)

                self._set_stage(operation_id, "fetching")
                with Span("executor.fetch"):
                    result, exception = await self.query_result(
                        conns[0], staged, key=self._pool_key(addresses[0])
                    )

                if self.profile_dir:
                    # Trace retrieval (best-effort, swallows its own
                    # transport faults): the harness wrote the profiler
                    # trace on the WORKER; nothing fetched it before.
                    with Span("executor.profile"):
                        await self._fetch_launch_profile(
                            conns[0], operation_id
                        )
            except (TransportError, OSError) as err:
                # A control-plane channel died mid-task: drop the pooled
                # transports so the next electron redials (the reference
                # would silently reuse nothing — it never pooled).
                # mark=False: failure cleanup, not a user cancel.
                await self.cancel(operation_id, mark=False)
                await self._discard_workers(conns)
                retry = self._plan_retry(
                    attempt, deadline,
                    reason=(
                        # A channel dying after its worker announced the
                        # preemption notice IS the preemption (the grace
                        # window elapsed): keep the spot-reclaim label.
                        "worker_preempted"
                        if operation_id in self._preempt_notices
                        else "channel"
                    ),
                    error=err,
                    message=f"control-plane channel died mid-task: {err}",
                    conns=conns,
                )
                if retry is not None:
                    outcome = "retried"
                    raise retry from err
                raise

            self._active.pop(operation_id, None)

            if self.do_cleanup:
                with Span("executor.cleanup"):
                    if self.defer_cleanup and not self._closing:
                        # Result is in hand; the rm round-trips happen off
                        # the critical path.  close() drains stragglers
                        # (and flips _closing so late tasks go inline
                        # rather than racing the pool teardown).
                        task = asyncio.create_task(
                            self._logged_cleanup(conns, staged)
                        )
                        self._cleanup_tasks.add(task)
                        task.add_done_callback(self._cleanup_tasks.discard)
                    else:
                        await self.cleanup(conns, staged)

            if exception is not None:
                # Re-raise the remote exception locally (ssh.py:581-583);
                # the finally below still runs, unlike the reference's leak.
                outcome = "remote_exception"
                raise exception
            if result_cache_key is not None:
                # Only a clean remote completion is memoized: failures,
                # fallbacks, and remote exceptions always re-run.
                with Span("executor.cache_store"):
                    await asyncio.to_thread(
                        self._result_cache.put, result_cache_key, result
                    )
            outcome = "completed"
            return result
        except asyncio.CancelledError:
            outcome = "cancelled"
            raise
        finally:
            # Terminal accounting runs on EVERY exit path — success,
            # failure, fallback, cancel — so overhead attribution and the
            # outcome counter survive failed runs.  Shared with the RPC
            # attempt path (_attempt_epilogue).
            self._attempt_epilogue(root, outcome, operation_id, attempt)
            # Pooled transports stay open for the next electron; close()
            # tears them down.  Non-pooled (error) states are handled by
            # the pool itself.

    def _attempt_epilogue(
        self, root: Span, outcome: str, operation_id: str, attempt: int
    ) -> None:
        """Terminal accounting shared by the launch and RPC attempt paths."""
        root.set_attribute("outcome", outcome)
        if outcome not in ("completed", "fallback_local", "cached"):
            root.record_error(outcome)
        root.end()
        self.last_timings = root.summary()
        # Stage spans SUM concurrent work (pipelined upload/submit run
        # per worker, staging overlaps the dial), so the wall-clock
        # overhead the caller actually waited is reported separately:
        # elapsed time minus the task's own runtime.  Profile capture
        # (trace stop + tar + fetch, potentially seconds) observes the
        # dispatch rather than being part of it — charging it as
        # overhead would burn the dispatch_overhead SLO and bench
        # budgets on profiled-but-healthy traffic.
        not_overhead = ("execute", "profile")
        self.last_timings["wall_overhead"] = max(
            0.0,
            root.total() - sum(
                root.stage_durations.get(stage, 0.0)
                for stage in not_overhead
            ),
        )
        self.last_timings["overhead"] = root.overhead(exclude=not_overhead)
        _ACTIVE_ELECTRONS.dec()
        _TASKS_TOTAL.labels(outcome=outcome).inc()
        _OVERHEAD_HIST.observe(root.overhead(exclude=not_overhead))
        # The wall view (elapsed minus execute) is the number the
        # overhead budget is asserted against — give it its own
        # percentile-capable series, not just a per-run scalar.
        _WALL_OVERHEAD_HIST.observe(self.last_timings["wall_overhead"])
        artifact = self._profile_artifacts.pop(operation_id, None)
        if artifact:
            self.last_timings["profile_trace"] = artifact
        self._op_status.pop(operation_id, None)
        self._op_conns.pop(operation_id, None)
        self._preempt_notices.discard(operation_id)
        MONITOR.forget(operation_id)
        obs_events.emit(
            "task.state",
            operation_id=operation_id,
            state=outcome,
            trace_id=root.trace_id,
            overhead_s=round(root.overhead(exclude=not_overhead), 6),
            total_s=round(root.total(), 6),
        )
        # Flight recorder: a terminal failure dumps the task's black box
        # (events + heartbeats + stage transitions across the whole retry
        # lineage) next to the cache; a clean completion retires the ring.
        # "retried" keeps recording — the lineage is still in flight.
        if outcome in ("failed", "fallback_local", "remote_exception"):
            box = FLIGHT_RECORDER.dump_to_file(
                operation_id, outcome,
                os.path.join(self.cache_dir, "blackbox"),
            )
            if box:
                obs_events.emit(
                    "task.blackbox",
                    operation_id=operation_id,
                    reason=outcome,
                    path=box,
                )
        elif outcome in ("completed", "cached"):
            FLIGHT_RECORDER.forget(operation_id)
        self._active.pop(operation_id, None)
        if attempt > 0:
            # Attempt-scoped cancel marks die with the attempt; the
            # BASE id's mark is cleared only by run()'s own finally —
            # discarding it here would erase a user cancel() that
            # raced a transient failure on attempt 0 (whose operation
            # id IS the base id) and let the retry driver relaunch a
            # cancelled electron.
            self._cancelled_ops.discard(operation_id)
        # Release per-task state retained by resident agent channels
        # (e.g. straggler exit events whose waiters were cancelled, or an
        # RPC result that arrived after its waiter gave up) — the leak
        # audit's guarantee that EVERY exit path drops per-task state.
        for client in self._op_agents.pop(operation_id, []) or []:
            if client is not None:
                client.forget(operation_id)

    # ------------------------------------------------------------------ #
    # RPC dispatch: execute-by-digest on the warm resident runtime        #
    # ------------------------------------------------------------------ #

    @staticmethod
    def _write_payload_file(path: str, payload: bytes) -> None:
        """Atomic write of a digest-named payload (immutable: skip if
        present — concurrent electrons share function payload files)."""
        if os.path.exists(path):
            return
        # Suffix must be unique per call: concurrent electrons in ONE
        # process may race to publish the same digest.
        tmp = f"{path}.tmp.{os.getpid()}.{os.urandom(4).hex()}"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, path)

    @staticmethod
    def _decode_rpc_result(event: dict) -> tuple[Any, BaseException | None]:
        """``(result, exception)`` from a streamed result event — the same
        pickle layout launch mode fetches from the result file.

        A binary-frame result carries the raw pickle in ``data_bytes``;
        the JSONL fallback base64-inlines it as ``data``.  A frame whose
        body failed decompression arrives marked ``torn`` — content
        corruption, raised as :class:`CodecIntegrityError` so the
        resilience classifier makes it PERMANENT instead of burning gang
        retries re-requesting the same torn bytes.
        """
        if event.get("torn"):
            from .transport.codec import CodecIntegrityError

            raise CodecIntegrityError(
                f"streamed RPC result arrived torn: {event['torn']}"
            )
        raw = event.get("data_bytes")
        data = (
            bytes(raw)
            if raw is not None
            else base64.b64decode(str(event.get("data") or ""))
        )
        return pickle.loads(data)

    async def _fetch_staged_rpc_result(
        self, conn: Transport, event: dict, operation_id: str
    ) -> tuple[Any, BaseException | None]:
        """Fetch an oversized result the worker staged instead of inlining.

        The return leg of the ``rpc_inline_args_max`` policy: the result
        event announces a remote path + sha256 instead of carrying the
        pickle.  Bytes are digest-verified after the fetch (a mismatch is
        a torn artifact — deterministic corruption, so the unrecognized-
        exception default classifies it PERMANENT, like any torn CAS
        payload); the remote file is unlinked either way.
        """
        remote = str(event["data_path"])
        local = os.path.join(
            self.cache_dir, f"result_rpc_{os.urandom(8).hex()}.pkl"
        )
        try:
            await conn.get(remote, local)
            data = await asyncio.to_thread(
                lambda: open(local, "rb").read()
            )
            expected = str(event.get("data_digest") or "")
            if expected and hashlib.sha256(data).hexdigest() != expected:
                raise RuntimeError(
                    f"staged RPC result for {operation_id} does not match "
                    "its announced digest (torn artifact)"
                )
            obs_events.emit(
                "task.rpc_result_staged",
                operation_id=operation_id,
                bytes=len(data),
            )
            return await asyncio.to_thread(pickle.loads, data)
        finally:
            try:
                os.remove(local)
            except OSError:
                pass
            try:
                await conn.remove([remote])
            except (TransportError, OSError):
                pass

    # ------------------------------------------------------------------ #
    # Profiling: resident-mode capture + launch-mode trace retrieval      #
    # ------------------------------------------------------------------ #

    async def _start_resident_profile(
        self, client: AgentClient, profile_id: str, sid: str = ""
    ) -> bool:
        """Start a ``jax.profiler`` trace inside a resident runtime.

        Best-effort by contract: profiling observes the dispatch, so a
        refused start (``busy`` — one process-wide trace at a time — or a
        worker without jax) is an event, never a failed electron.
        """
        try:
            await client.profile_start(
                profile_id,
                f"{self.remote_cache}/profile_{profile_id}",
                sid=sid,
            )
        except (AgentError, asyncio.TimeoutError) as err:
            if isinstance(err, asyncio.TimeoutError):
                # The worker may have ACTIVATED the trace and lost only
                # the ack — without a compensating stop it records
                # forever and refuses every later start as busy.
                self._detach_profile_abort(client, profile_id, sid)
            obs_events.emit(
                "task.profile_error",
                operation_id=profile_id,
                stage="start",
                error=str(err),
            )
            app_log.warning(
                "resident profile start for %s failed: %s", profile_id, err
            )
            return False
        return True

    def _detach_profile_abort(
        self, client: AgentClient, profile_id: str, sid: str
    ) -> None:
        """Best-effort compensating stop, detached from the caller.

        Used when a capture loses track of a possibly-active trace (start
        ack timed out, capture cancelled mid-sleep): the artifact is
        abandoned but the runtime's one process-wide profiler slot is
        freed.  A stop landing on a never-started trace answers
        ``not_running`` — harmless.
        """
        async def _abort() -> None:
            try:
                await client.profile_stop(
                    profile_id, sid=sid, timeout=30.0, discard=True
                )
            except (AgentError, asyncio.TimeoutError, OSError):
                pass

        task = asyncio.create_task(_abort())
        self._cleanup_tasks.add(task)
        task.add_done_callback(self._cleanup_tasks.discard)

    def _profile_stop_failed(
        self, operation_id: str, profile_id: str, err: Exception
    ) -> None:
        obs_events.emit(
            "task.profile_error",
            operation_id=operation_id,
            stage="stop",
            error=str(err),
        )
        app_log.warning(
            "resident profile stop for %s failed: %s", profile_id, err
        )
        return None

    async def _finish_resident_profile(
        self,
        client: AgentClient,
        conn: Transport,
        profile_id: str,
        operation_id: str,
        sid: str = "",
    ) -> dict[str, Any] | None:
        """Stop the trace, stage the artifact back, digest-verify it.

        The worker packages the trace into ONE content-addressed
        ``<sha256>.profile.tgz`` under the CAS dir; the fetch re-hashes
        the bytes locally before trusting them — the same end-to-end
        publish-by-content contract every staged payload rides.
        """
        artifact_dir = cas_path(self.remote_cache, "").rstrip("/")
        try:
            event = await client.profile_stop(
                profile_id, artifact_dir=artifact_dir, sid=sid
            )
        except asyncio.TimeoutError:
            # The worker packages on a thread and a slow tar can outlive
            # the waiter; a RESEND now would be refused "already
            # stopping" and orphan the artifact it is about to announce
            # — wait out one more settle window on the same event.
            try:
                event = await client.profile_wait_stopped(profile_id)
            except (AgentError, asyncio.TimeoutError) as err:
                return self._profile_stop_failed(
                    operation_id, profile_id, err
                )
        except AgentError:
            # A failed stop (stop_failed) KEEPS the trace active on the
            # worker so the stop is retryable — without a retry that
            # runtime would refuse every later start as busy for the
            # rest of its life.
            await asyncio.sleep(0.5)
            try:
                event = await client.profile_stop(
                    profile_id, artifact_dir=artifact_dir, sid=sid
                )
            except (AgentError, asyncio.TimeoutError) as err:
                return self._profile_stop_failed(
                    operation_id, profile_id, err
                )
        return await self._retrieve_profile_artifact(
            conn,
            str(event.get("path") or ""),
            str(event.get("digest") or ""),
            operation_id,
        )

    async def _retrieve_profile_artifact(
        self,
        conn: Transport,
        remote_path: str,
        digest: str,
        operation_id: str,
    ) -> dict[str, Any] | None:
        """Fetch one announced trace artifact, verify, record, clean up."""
        if not remote_path or not digest:
            return None
        profiles_dir = os.path.join(self.cache_dir, "profiles")
        local = os.path.join(
            profiles_dir, f"{operation_id}_{digest[:12]}.profile.tgz"
        )
        tmp = f"{local}.tmp.{os.getpid()}.{os.urandom(4).hex()}"
        try:
            os.makedirs(profiles_dir, exist_ok=True)
            await conn.get(remote_path, tmp)
            if file_digest(tmp) != digest:
                raise RuntimeError(
                    f"profile artifact for {operation_id} does not match "
                    "its announced digest (torn artifact)"
                )
            size = os.path.getsize(tmp)
            os.replace(tmp, local)
        except (TransportError, OSError, RuntimeError) as err:
            obs_events.emit(
                "task.profile_error",
                operation_id=operation_id,
                stage="fetch",
                error=str(err),
            )
            app_log.warning(
                "profile artifact fetch for %s failed: %s", operation_id, err
            )
            return None
        finally:
            try:
                os.remove(tmp)
            except OSError:
                pass
            try:
                await conn.remove([remote_path])
            except (TransportError, OSError):
                pass
        self._profile_artifacts[operation_id] = local
        obs_events.emit(
            "task.profile_captured",
            operation_id=operation_id,
            path=local,
            digest=digest,
            bytes=size,
            worker=conn.address,
        )
        return {"path": local, "digest": digest, "bytes": size}

    async def _fetch_launch_profile(
        self, conn: Transport, operation_id: str
    ) -> None:
        """Satellite: pull launch-mode profiler traces back automatically.

        The launch harness writes its ``jax.profiler`` trace to
        ``{profile_dir}/{operation_id}`` on the WORKER's filesystem; before
        this, nothing ever retrieved it — on a remote transport the trace
        was effectively lost.  On completion the trace dir is tarred
        remotely, hashed (same interpreter the harness ran under), fetched,
        digest-verified and recorded in ``last_timings["profile_trace"]``
        + a ``task.profile_captured`` event.  Best-effort: a missing trace
        (profiler unavailable) or a failed fetch never fails the electron.
        """
        remote_dir = f"{self.profile_dir}/{operation_id}"
        remote_tmp = (
            f"{self.remote_cache}/profile_{operation_id}."
            f"{os.urandom(4).hex()}.tgz"
        )
        q_dir, q_tmp = shlex.quote(remote_dir), shlex.quote(remote_tmp)
        # Streamed hash: a tarred trace routinely reaches hundreds of MB
        # and the worker may be memory-tight right after the task ran.
        hash_snippet = (
            "import hashlib,sys\n"
            "h = hashlib.sha256()\n"
            "with open(sys.argv[1], 'rb') as f:\n"
            "    for chunk in iter(lambda: f.read(1 << 20), b''):\n"
            "        h.update(chunk)\n"
            "print(h.hexdigest())"
        )
        try:
            probe = await conn.run(
                f"if [ -d {q_dir} ]; then tar -C {q_dir} -czf {q_tmp} . && "
                f"{self.python_path} -E -S -c {shlex.quote(hash_snippet)} "
                f"{q_tmp} && rm -rf {q_dir}; else echo MISSING; fi"
            )
            token = (
                probe.stdout.strip().split()[-1]
                if probe.stdout.strip()
                else ""
            )
            if probe.exit_status != 0 or not token or token == "MISSING":
                return  # no trace written (profiler unavailable on worker)
            await self._retrieve_profile_artifact(
                conn, remote_tmp, token, operation_id
            )
        except (TransportError, OSError) as err:
            obs_events.emit(
                "task.profile_error",
                operation_id=operation_id,
                stage="fetch",
                error=str(err),
            )
            app_log.warning(
                "launch profile fetch for %s failed: %s", operation_id, err
            )

    def _profile_targets(
        self, sid: str
    ) -> tuple[str, list[tuple[str, AgentClient]]]:
        """Resolve a capture's ``(remote sid, candidate agents)``.

        Pool servers sort first (they host RPC invocations AND pool-mode
        serving sessions in-process).  A sid naming a local
        :class:`ServeHandle` is translated to the current generation's
        remote id and pins the candidates to the agent hosting that
        session — every other worker would profile the wrong process.
        (Raw remote sids without a local handle rely on the worker-side
        ``unknown_session`` refusal instead.)
        """
        handle = self._serve_handles.get(sid)
        pinned_client = None
        if handle is not None:
            sid = getattr(handle, "_sid_g", sid)
            pinned_client = getattr(handle, "_client", None)
        targets = [
            (address, client)
            for address, client in list(self._agents.items())
            if client is not None and client.alive
        ]
        targets.sort(key=lambda t: t[1].mode != "pool")
        if pinned_client is not None:
            hosted = [t for t in targets if t[1] is pinned_client]
            if hosted:
                targets = hosted
        return sid, targets

    async def capture_profile(
        self, duration_s: float = 2.0, sid: str = ""
    ) -> dict[str, Any] | None:
        """On-demand capture of a resident runtime's ``jax.profiler`` trace.

        The ``POST /profile`` action (and a public API): picks a live
        resident runtime — pool servers first (they host RPC invocations
        AND pool-mode serving sessions in-process), then native agents
        (which forward into a ``--serve-child`` session runner) — records
        for ``duration_s``, stages the artifact back through the CAS with
        digest verification, and returns its info.  ``sid`` pins a serving
        session (a :class:`ServeHandle` sid or a remote session id).
        Returns None when no resident runtime is available to profile.
        """
        self._guard_event_loop()
        sid, targets = self._profile_targets(sid)
        profile_id = f"prof-{os.urandom(4).hex()}"
        for address, client in targets:
            if client.mode != "pool" and not sid and not self._serve_handles:
                # A native agent holds no Python runtime of its own; with
                # no serving session there is nothing it can profile.
                continue
            try:
                conn = await self._client_connect(address)
            except (TransportError, OSError) as err:
                app_log.debug("profile connect %s failed: %s", address, err)
                continue
            if not await self._start_resident_profile(
                client, profile_id, sid=sid
            ):
                continue
            info = await self._finish_capture(
                client, conn, profile_id, duration_s, sid=sid
            )
            if info:
                return {
                    "worker": address,
                    "duration_s": float(duration_s),
                    **info,
                }
        return None

    async def _finish_capture(
        self,
        client: AgentClient,
        conn: Transport,
        profile_id: str,
        duration_s: float,
        sid: str = "",
    ) -> dict[str, Any] | None:
        """Shared on-demand tail: record for ``duration_s``, stop, fetch.

        Used by :meth:`capture_profile` and ``ServeHandle.capture_profile``
        after a successful start.  Cancellation mid-capture (the HTTP
        deadline, a dropped caller) detaches a compensating stop so the
        runtime's one profiler slot is freed; the synthetic profile id
        never reaches the task epilogue, so its ``_profile_artifacts``
        entry is popped here.
        """
        try:
            await asyncio.sleep(max(0.0, float(duration_s)))
            return await self._finish_resident_profile(
                client, conn, profile_id, profile_id, sid=sid
            )
        except asyncio.CancelledError:
            self._detach_profile_abort(client, profile_id, sid)
            raise
        finally:
            self._profile_artifacts.pop(profile_id, None)

    def _capture_profile_blocking(
        self, params: dict
    ) -> dict[str, Any] | None:
        """``POST /profile`` provider body (runs on the HTTP thread).

        Bridges onto the executor's bound event loop — agent channels are
        loop-bound, so the capture must run where they live.  None when
        no loop is running (no dispatch in progress) or no resident
        runtime exists; the ops server then tries the next provider.
        """
        loop = getattr(self, "_bound_loop", None)
        if loop is None or loop.is_closed() or not loop.is_running():
            return None
        try:
            duration = float(params.get("duration_s") or 2.0)
        except (TypeError, ValueError):
            duration = 2.0
        duration = min(max(duration, 0.1), 60.0)
        sid = str(params.get("sid") or "")
        future = asyncio.run_coroutine_threadsafe(
            self.capture_profile(duration_s=duration, sid=sid), loop
        )
        import concurrent.futures

        try:
            return future.result(timeout=duration + 180.0)
        except concurrent.futures.TimeoutError:
            # Distinct from builtin TimeoutError on py3.10.
            future.cancel()
            raise

    def _rpc_result_cache_key(
        self,
        fn: Callable,
        fn_digest: str,
        args_digest: str,
        task_metadata: dict,
    ) -> str | None:
        """Memoization key for an RPC-mode electron.

        Same shape as the launch key (payload digest, code digest, env
        fingerprint) with the payload digest derived from the separately
        pickled function + args, and the mode folded into the fingerprint
        so the two paths never serve each other's entries.
        """
        fingerprint = json.dumps(
            {
                "transport": self.transport_kind,
                "python_path": self.python_path,
                "conda_env": self.conda_env,
                "task_env": self.task_env,
                "pip_deps": list(task_metadata.get("pip_deps", ()) or ()),
                "workers": self.workers
                or [self.tpu_name or self.hostname or "local"],
                "workdir": self.remote_workdir,
                "mode": "rpc",
            },
            sort_keys=True,
            default=str,
        )
        return ResultCache.make_key(
            bytes_digest(f"{fn_digest}:{args_digest}".encode()),
            self._fn_code_digest(fn),
            bytes_digest(fingerprint.encode()),
        )

    async def _await_rpc_result(
        self, client: AgentClient, operation_id: str
    ) -> tuple[str, Any]:
        """Wait for one invocation's streamed result with liveness checks.

        Returns a verdict pair: ``("result", event)`` on success,
        ``("timeout", None)`` when ``task_timeout`` elapsed,
        ``("stalled", None)`` when the liveness monitor flagged the
        resident worker silent past its threshold, or
        ``("channel", AgentError)`` when the agent channel died — a dead
        resident worker and a dropped channel are indistinguishable here,
        and both are the transient the caller tears the gang down for.
        Wakes on a short tick to notice cancellation and stalls; with no
        timeout set, logs the same still-running watchdog reminder the
        polling path would.
        """
        timeout = self.task_timeout or None
        stall_after = self._stall_after()
        wake = (
            min(1.0, max(0.25, stall_after / 4.0)) if stall_after else 0.5
        )
        loop = asyncio.get_running_loop()
        started = loop.time()
        deadline_t = started + timeout if timeout else None
        last_watchdog = 0.0
        waiter = asyncio.ensure_future(client.wait_result(operation_id))
        try:
            while True:
                remaining = None
                if deadline_t is not None:
                    remaining = deadline_t - loop.time()
                    if remaining <= 0:
                        return "timeout", None
                wait_for = wake if remaining is None else min(wake, remaining)
                done, _pending = await asyncio.wait(
                    {waiter}, timeout=wait_for
                )
                if done:
                    try:
                        return "result", waiter.result()
                    except AgentError as err:
                        return "channel", err
                if self._is_cancelled(operation_id):
                    raise asyncio.CancelledError(
                        f"task {operation_id} cancelled"
                    )
                if stall_after and MONITOR.stalled(operation_id):
                    return "stalled", None
                waited = loop.time() - started
                if (
                    not timeout
                    and waited - last_watchdog >= self.WATCHDOG_LOG_INTERVAL_S
                ):
                    last_watchdog = waited
                    app_log.warning(
                        "RPC task %s still running after %.0fs with no "
                        "task_timeout set", operation_id, waited,
                    )
        finally:
            waiter.cancel()
            try:
                await waiter
            except (asyncio.CancelledError, AgentError):
                pass

    async def _run_attempt_rpc(
        self,
        function: Callable,
        args: tuple,
        kwargs: dict,
        task_metadata: dict,
        operation_id: str,
        attempt: int,
        deadline: Deadline,
    ) -> Any:
        """One dispatch attempt in RPC mode: execute-by-digest on the warm
        resident runtime.

        The per-electron cost collapses to (warm path): one ``invoke``
        write on the agent channel with args inline, one pushed ``result``
        event back — no per-electron process, no pid file, no status poll,
        no remote disk for args or results.  The connection-scoped costs
        (dial, pre-flight, agent start, CAS ship of the function pickle,
        ``register_fn``) amortize across every electron sharing the
        connection and digest, exactly like the CAS amortizes staging.

        Failure routing matches the launch path's classification: a dead
        resident worker or dropped channel is a transient (label
        ``rpc_channel``) that tears the gang down for retry; a digest
        mismatch at registration is PERMANENT (torn payload); timeouts
        and stalls escalate by tearing down the resident runtime (an
        in-process invocation cannot be killed any other way) and retry
        under their existing labels.  :class:`_RpcUnavailable` (no pool
        runtime on the gang) unwinds minimally — the retry driver re-runs
        the same attempt through the launch path.
        """
        dispatch_id = task_metadata.get("dispatch_id", "dispatch")
        node_id = task_metadata.get("node_id", 0)
        self._guard_event_loop()

        root = Span(
            "executor.run",
            {
                "operation_id": operation_id,
                "dispatch_id": dispatch_id,
                "node_id": node_id,
                "transport": self.transport_kind,
                "attempt": attempt,
                "mode": "rpc",
            },
        )
        root.__enter__()
        _ACTIVE_ELECTRONS.inc()
        obs_events.emit(
            "task.state",
            operation_id=operation_id,
            state="starting",
            trace_id=root.trace_id,
            mode="rpc",
        )
        self._op_status[operation_id] = {
            "stage": "starting",
            "mode": "rpc",
            "attempt": attempt + 1,
            "trace_id": root.trace_id,
            "dispatch_id": dispatch_id,
            "node_id": node_id,
            "since": time.time(),
        }
        self.last_dispatch_mode = "rpc"
        trace_context = context_of(root, attempt=attempt)
        outcome = "failed"
        fallback_to_launch = False
        conns: list[Transport] = []
        local_args: str | None = None
        result_cache_key: str | None = None
        try:
            with Span("executor.stage"):
                # Function and args pickle SEPARATELY (unlike the launch
                # path's one (fn, args, kwargs) payload): the function's
                # digest is the stable registry key electrons share, while
                # args vary per call and ride the channel.
                fn_payload, args_payload = await asyncio.to_thread(
                    lambda: (
                        cloudpickle.dumps(function),
                        cloudpickle.dumps((tuple(args), dict(kwargs))),
                    )
                )
                fn_digest = bytes_digest(fn_payload)
                args_digest = bytes_digest(args_payload)
                inline = len(args_payload) <= self.rpc_inline_args_max
                local_fn = os.path.join(
                    self.cache_dir, f"fn_rpc_{fn_digest}.pkl"
                )
                await asyncio.to_thread(
                    self._write_payload_file, local_fn, fn_payload
                )
                if not inline:
                    # Attempt-private name: concurrent electrons with
                    # identical args must not share this file — each
                    # attempt's finally unlinks its own copy, and a
                    # digest-shared name would let one attempt's cleanup
                    # race another's CAS upload (the CAS itself still
                    # dedupes the remote bytes by digest).
                    local_args = os.path.join(
                        self.cache_dir,
                        f"args_rpc_{args_digest}.{os.urandom(6).hex()}.pkl",
                    )
                    await asyncio.to_thread(
                        self._write_payload_file, local_args, args_payload
                    )

            if self.cache_results:
                with Span("executor.cache_lookup"):
                    result_cache_key = self._rpc_result_cache_key(
                        function, fn_digest, args_digest, task_metadata
                    )
                    hit, cached = await asyncio.to_thread(
                        self._result_cache.get, result_cache_key
                    )
                    if hit:
                        obs_events.emit(
                            "task.result_cached",
                            operation_id=operation_id,
                            trace_id=root.trace_id,
                        )
                        outcome = "cached"
                        return cached

            with Span("executor.validate"):
                await self._validate_credentials()

            self._set_stage(operation_id, "connecting")
            try:
                lease = await self.lease_gang(dialed=conns)
                conns = lease.conns
            except (TransportError, OSError, ValueError) as err:
                retry = self._plan_retry(
                    attempt, deadline, reason="connect", error=err,
                    message=f"could not reach TPU workers: {err}",
                    conns=conns,
                )
                if retry is not None:
                    outcome = "retried"
                    raise retry from err
                result = await self._on_dispatch_fail_async(
                    function, args, kwargs,
                    f"could not reach TPU workers: {err}",
                    operation_id=operation_id,
                )
                outcome = "fallback_local"
                return result

            addresses = self._worker_addresses()
            address, conn = addresses[0], conns[0]
            key = self._pool_key(address)
            client = self._agents.get(conn.address)
            if client is None or not client.alive or client.mode != "pool":
                # The native C++ agent speaks the verbs but pays an
                # interpreter start per invoke; the resident pool loop is
                # the runtime that actually delivers the sub-100ms path,
                # so anything else routes this electron through launch.
                fallback_to_launch = True
                raise _RpcUnavailable(
                    f"no resident pool runtime on {address} "
                    f"(agent: {getattr(client, 'mode', None)!r})"
                )

            self._set_stage(operation_id, "launching")
            remote_fn = cas_path(self.remote_cache, fn_digest, ".pkl")
            spec: dict[str, Any] = {
                "operation_id": operation_id,
                "trace": trace_context,
            }
            if self.task_env:
                # The resident runtime applies the same env contract a
                # launch-mode harness child would (os.environ, PYTHONPATH
                # sys.path mirror, jax platform pin) — task_env must not
                # silently change meaning between dispatch modes.
                spec["env"] = dict(self.task_env)
            if self.heartbeat_interval > 0:
                spec["heartbeat_s"] = self.heartbeat_interval
            invoke_kwargs: dict[str, Any] = {}
            try:
                with Span("executor.upload"):
                    # Ship-once: the CAS skips bytes the worker holds, the
                    # registry skips digests the resident runtime loaded.
                    codec = self._codec_for(key, conn)
                    await self._cas.ensure_probed(
                        key, conn, [(fn_digest, remote_fn)]
                    )
                    await self._cas.ensure(
                        key, conn, fn_digest, local_fn, remote_fn,
                        codec=codec, python_path=self.python_path,
                    )
                    await self._fn_registry.ensure(
                        key, client, fn_digest, remote_fn
                    )
                    if inline:
                        # Raw pickle bytes: the client ships them as a
                        # binary frame body on a negotiated channel, or
                        # base64-inlines them on the JSONL fallback.
                        invoke_kwargs["args_bytes"] = args_payload
                    else:
                        # Oversized args take the CAS road (digest
                        # verified remotely), results still stream back.
                        remote_args = cas_path(
                            self.remote_cache, args_digest, ".pkl"
                        )
                        await self._cas.ensure(
                            key, conn, args_digest, local_args, remote_args,
                            codec=codec, python_path=self.python_path,
                        )
                        invoke_kwargs["args_path"] = remote_args
                        invoke_kwargs["args_digest"] = args_digest
                        obs_events.emit(
                            "task.rpc_args_staged",
                            operation_id=operation_id,
                            bytes=len(args_payload),
                        )
                with Span("executor.submit"):
                    if client.on_telemetry is None:
                        client.on_telemetry = (
                            lambda task_id, data, _worker=address: (
                                self._handle_backhaul(task_id, _worker, data)
                            )
                        )
                    # The inline-args size policy applies symmetrically on
                    # the way back: a result pickle over the threshold is
                    # staged remotely (attempt-private path, sha256
                    # announced) instead of base64-inlined onto the
                    # channel in one multi-MB write.
                    remote_result = (
                        f"{self.remote_cache}/result_rpc_"
                        f"{os.urandom(8).hex()}.pkl"
                    )
                    await client.invoke(
                        operation_id, fn_digest, spec=spec,
                        path=remote_fn,
                        result_path=remote_result,
                        result_max_inline=self.rpc_inline_args_max,
                        **invoke_kwargs,
                    )
            except AgentError as err:
                # Registration/invoke failure.  classify_error reads the
                # duck-typed permanent tag a digest mismatch carries; for
                # everything transient the dead-resident-runtime remedy is
                # NOT a redial — the transport may be fine — but the next
                # attempt's lease re-pings the cached agent, rebuilds it,
                # and the registry's owner check forces re-registration.
                retry = self._plan_retry(
                    attempt, deadline, reason="rpc_channel", error=err,
                    message=f"RPC dispatch failed: {err}", conns=conns,
                )
                if retry is not None:
                    outcome = "retried"
                    raise retry from err
                fault, _label = classify_error(err)
                if fault is FaultClass.PERMANENT:
                    # Torn payload (digest mismatch): fail loud — neither
                    # a retry nor a local re-run can make these bytes
                    # match their content address.
                    raise
                result = await self._on_dispatch_fail_async(
                    function, args, kwargs,
                    f"RPC dispatch failed: {err}",
                    operation_id=operation_id,
                )
                outcome = "fallback_local"
                return result
            except (TransportError, OSError) as err:
                # CAS ship of the function/args payload failed: the same
                # channel transient the launch path's upload leg routes.
                retry = self._plan_retry(
                    attempt, deadline, reason="channel", error=err,
                    message=f"artifact upload failed: {err}", conns=conns,
                )
                if retry is not None:
                    outcome = "retried"
                    await self._discard_workers(conns)
                    raise retry from err
                raise

            obs_events.emit(
                "task.state",
                operation_id=operation_id,
                state="submitted",
                trace_id=root.trace_id,
                mode="rpc",
            )
            self._set_stage(operation_id, "executing")
            self._op_agents[operation_id] = [client]
            profiling = False
            if self.profile_dir:
                # Resident-mode capture: the trace runs INSIDE the warm
                # runtime executing this invocation (profile_dir used to
                # force the launch path — the profiled dispatch was never
                # the fast one anyone cared about).  Started after the
                # invoke ack so a refused start (busy/unavailable) can't
                # leave an orphan trace when submit fails; failure paths
                # below tear the runtime down, which ends any trace with
                # it.
                profiling = await self._start_resident_profile(
                    client, operation_id
                )
            if self.heartbeat_interval > 0:
                MONITOR.watch(
                    operation_id,
                    self._stall_after(),
                    workers=[address],
                    interval=self.heartbeat_interval,
                )
            with Span("executor.execute"):
                verdict, payload = await self._await_rpc_result(
                    client, operation_id
                )

            if verdict != "result":
                if self._is_cancelled(operation_id):
                    raise asyncio.CancelledError(
                        f"task {operation_id} cancelled"
                    )
                if verdict == "stalled":
                    STALLS_TOTAL.labels(worker=address).inc()
                last_beats = MONITOR.last(operation_id)
                if verdict == "timeout":
                    failure_msg = (
                        f"RPC task {operation_id} timed out after "
                        f"{self.task_timeout:.1f}s on {address}; resident "
                        "runtime torn down"
                    )
                elif verdict == "stalled":
                    failure_msg = (
                        f"RPC task {operation_id} stalled on {address}: no "
                        f"heartbeat for {self._stall_after():.1f}s; resident "
                        "runtime torn down"
                    )
                else:
                    failure_msg = (
                        f"resident worker died mid-invoke on {address}: "
                        f"{payload}"
                    )
                obs_events.emit(
                    "task.failed",
                    operation_id=operation_id,
                    trace_id=root.trace_id,
                    worker=address,
                    status=verdict,
                    mode="rpc",
                    **({"last_heartbeats": last_beats} if last_beats else {}),
                )
                # An in-process invocation has no pid to kill: tearing the
                # gang down (agents closed, channels dropped, registry
                # evicted) IS the escalation, for timeouts and stalls as
                # much as for channel deaths.
                await self._discard_workers(conns)
                if verdict == "stalled":
                    retry = self._plan_retry(
                        attempt, deadline,
                        error=WorkerStalledError(failure_msg),
                        message=failure_msg, conns=conns,
                    )
                elif verdict == "timeout":
                    retry = self._plan_retry(
                        attempt, deadline, reason="timeout",
                        message=failure_msg, conns=conns,
                    )
                else:
                    retry = self._plan_retry(
                        attempt, deadline, reason="rpc_channel",
                        error=payload, message=failure_msg, conns=conns,
                    )
                if retry is not None:
                    outcome = "retried"
                    raise retry
                if verdict == "channel" and payload is not None:
                    raise payload
                result = await self._on_dispatch_fail_async(
                    function, args, kwargs, failure_msg,
                    operation_id=operation_id,
                )
                outcome = "fallback_local"
                return result

            if profiling:
                with Span("executor.profile"):
                    await self._finish_resident_profile(
                        client, conn, operation_id, operation_id
                    )

            with Span("executor.fetch"):
                if payload.get("data_path"):
                    result, exception = await self._fetch_staged_rpc_result(
                        conn, payload, operation_id
                    )
                else:
                    result, exception = await asyncio.to_thread(
                        self._decode_rpc_result, payload
                    )

            if exception is not None:
                outcome = "remote_exception"
                raise exception
            if result_cache_key is not None:
                with Span("executor.cache_store"):
                    await asyncio.to_thread(
                        self._result_cache.put, result_cache_key, result
                    )
            outcome = "completed"
            return result
        except asyncio.CancelledError:
            outcome = "cancelled"
            # A cancelled invocation keeps running inside the shared
            # resident interpreter — there is no per-task pid to kill, so
            # dropping the runtime is the cancel escalation (launch mode
            # kills the task's process group here instead).  Shielded so
            # a second cancel cannot abandon the teardown half-done;
            # concurrent electrons on this gang see a channel death and
            # retry.
            if conns:
                try:
                    await asyncio.shield(self._discard_workers(conns))
                except (Exception, asyncio.CancelledError):  # noqa: BLE001
                    pass
            raise
        finally:
            if local_args is not None:
                # One-off payload (args are call-specific); the function
                # payload file stays — it is the local CAS source shared
                # by every electron with this digest.
                try:
                    os.remove(local_args)
                except OSError:
                    pass
            if fallback_to_launch:
                # Minimal unwind: the launch attempt that follows owns the
                # real accounting for this electron — a full epilogue here
                # would double-count the outcome and overhead series.
                root.set_attribute("outcome", "rpc_fallback")
                root.end()
                _ACTIVE_ELECTRONS.dec()
                self._op_status.pop(operation_id, None)
            else:
                self._attempt_epilogue(root, outcome, operation_id, attempt)

    def _remove_local_staging(self, staged: StagedTask) -> None:
        """Unlink a dead attempt's local staging (pipelining stages them
        even when the concurrent connect leg fails)."""
        for path in [staged.function_file, *staged.local_spec_files]:
            try:
                os.remove(path)
            except OSError:
                pass

    async def _dispatch_all(
        self,
        conns: list[Transport],
        staged: StagedTask,
        upload: bool = True,
    ) -> dict[str, int]:
        """Per-worker upload→launch pipelines with an all-or-nothing
        launch barrier (SURVEY §7 'hard parts').

        Each worker's chain runs independently — no global barrier between
        the upload and submit stages, so a fast worker launches while a
        slow one still uploads; the per-worker ``executor.upload``/
        ``executor.submit`` spans therefore SUM worker time in
        ``last_timings`` (wall savings show in ``wall_overhead``).  If any
        chain fails, workers that did start are killed before raising —
        upload-leg failures re-raise tagged :class:`_StageUploadFailed` so
        the caller keeps the channel-vs-launch failure routing.  PIDs are
        keyed by the *configured* worker address so :meth:`cancel`
        resolves them through the same pool key that opened the
        connection.
        """
        addresses = self._worker_addresses()
        launched_via: list[AgentClient | None] = [None] * len(conns)

        async def chain(i: int, conn: Transport) -> int:
            if upload:
                try:
                    with Span("executor.upload"):
                        await self._upload_task(
                            conn, staged, i,
                            key=self._pool_key(addresses[i]),
                        )
                except Exception as err:
                    raise _StageUploadFailed(str(err)) from err
            with Span("executor.submit"):
                return await self._launch_one(i, conn, staged, launched_via)

        results = await asyncio.gather(
            *(chain(i, c) for i, c in enumerate(conns)),
            return_exceptions=True,
        )
        pids: dict[str, int] = {}
        errors: list[BaseException] = []
        for address, res in zip(addresses, results):
            if isinstance(res, BaseException):
                errors.append(res)
            else:
                pids[address] = res
        self._active[staged.operation_id] = pids
        self._op_agents[staged.operation_id] = launched_via
        if errors:
            # The all-or-nothing abort, not a user cancel (mark=False):
            # the failure must still route to the fallback policy, and a
            # real concurrent cancel's mark must survive.
            await self.cancel(staged.operation_id, mark=False)
            for err in errors:
                if isinstance(err, asyncio.CancelledError):
                    raise err
            for err in errors:
                if isinstance(err, _StageUploadFailed):
                    raise err
            raise TransportError(
                f"launch failed on {len(errors)}/{len(conns)} workers: "
                f"{errors[0]}"
            ) from errors[0]
        return pids

    async def _launch_one(
        self,
        i: int,
        conn: Transport,
        staged: StagedTask,
        launched_via: "list[AgentClient | None]",
    ) -> int:
        """Start one worker's harness (agent fast path, nohup fallback)."""
        client = await self._agent_for(conn)
        if client is not None:
            try:
                pid = await self._submit_via_agent(client, staged, i)
                launched_via[i] = client
                return pid
            except AgentError as err:
                if getattr(err, "maybe_started", False):
                    # The run command reached (or may have reached) the
                    # worker before the channel failed: the harness could
                    # already be alive.  Relaunching would double-run the
                    # task; kill any orphan and abort this worker's
                    # launch instead.  Two handles cover both runtimes:
                    # the pid file the harness writes at startup (pool
                    # forks keep the server's cmdline, so pkill alone
                    # can't find them) and the spec path in the native
                    # agent's exec'd command line.  The pid file is
                    # written moments after fork, so retry over a short
                    # grace window rather than racing it once.
                    pid_file = shlex.quote(f"{staged.remote_pid_file}.{i}")
                    # -s (non-empty) + the harness's atomic pid write
                    # mean a readable pid IS complete; echo only on a
                    # kill that had a real target so the retry loop
                    # can't declare victory on an empty race window.
                    # The pkill pattern brackets its first character
                    # ([s]pec-style) so the reaping shell — whose own
                    # command line contains the spec path — can never
                    # match and TERM itself.
                    spec_path = staged.remote_spec_file(i)
                    pkill_pattern = f"[{spec_path[0]}]{spec_path[1:]}"
                    reap = (
                        f"if [ -s {pid_file} ]; then "
                        f"kill -TERM $(cat {pid_file}) 2>/dev/null; "
                        "echo KILLED; fi; pkill -f "
                        + shlex.quote(pkill_pattern)
                        + " 2>/dev/null && echo PKILLED || true"
                    )
                    for _attempt in range(4):
                        reaped = await conn.run(reap)
                        if "KILLED" in reaped.stdout:  # matches PKILLED too
                            break
                        await asyncio.sleep(0.5)
                    raise TransportError(
                        f"agent submit on {conn.address} failed after the "
                        f"run command was sent: {err}"
                    ) from err
                app_log.warning(
                    "agent submit on %s failed (%s); nohup fallback",
                    conn.address, err,
                )
        return await self.submit_task(conn, staged, i)

    async def _await_stragglers(
        self,
        conns: list[Transport],
        staged: StagedTask,
        pids: dict[str, int],
        grace: float = 10.0,
    ) -> None:
        """Reap workers 1..N-1 after process 0 produced the result.

        Replicated outputs mean the non-zero processes finish their final
        collective around the same time as process 0; give them a short
        grace window to write their done-markers, then TERM any leftover so
        no harness outlives its task on billed TPU time.
        """
        addresses = self._worker_addresses()

        async def reap(process_id: int, conn: Transport, address: str) -> None:
            pid = pids.get(address)
            marker = f"{staged.remote_result_file}.done.{process_id}"
            pid_file = f"{staged.remote_pid_file}.{process_id}"

            async def probe() -> tuple[TaskStatus, int]:
                try:
                    return (
                        await self.get_status(conn, marker, pid, pid_file),
                        process_id,
                    )
                except TransportError:
                    # Garbled probe output on a flaky channel: keep waiting
                    # so the grace deadline (and the kill below) still fires.
                    return TaskStatus.RUNNING, process_id

            status, _ = await self._wait_while_running(probe, timeout=grace)
            if status is not TaskStatus.RUNNING:
                return
            app_log.warning(
                "worker %s straggling %.1fs after result; killing pid %s",
                address, grace, pid,
            )
            if pid is not None:
                await conn.run(f"kill -TERM {pid} 2>/dev/null || true")
            else:
                quoted = shlex.quote(pid_file)
                await conn.run(
                    f"test -s {quoted} && "
                    f"kill -TERM \"$(cat {quoted})\" 2>/dev/null || true"
                )

        await asyncio.gather(
            *(
                reap(i, conn, addr)
                for i, (conn, addr) in enumerate(zip(conns, addresses))
                if i > 0
            ),
            return_exceptions=True,
        )


# Merge defaults so a bare install self-registers under [executors.tpu]
# (what Covalent's plugin loader does with the defaults dict, ssh.py:39-50).
update_config(_EXECUTOR_PLUGIN_DEFAULTS, section="executors.tpu")
