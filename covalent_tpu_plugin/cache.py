"""Two-level dispatch cache: content-addressed staging + result memoization.

Level 1 — **content-addressed artifact store (CAS)**.  Every file the
executor stages (harness module, function pickle, per-worker spec JSON)
is named by its sha256 digest under ``{remote_cache}/cas/``, and a
per-connection :class:`CASIndex` remembers which digests each worker
already holds.  The index is seeded by ONE batched existence probe per
connection lifetime (``Transport.exists_batch``) and maintained locally
after that, so repeat uploads collapse to set lookups: the harness ships
once per connection instead of once per electron × worker, and identical
function pickles across a map-style fan-out ship once total.  This is the
Podracer amortize-the-setup pattern (PAPERS): keep workers hot, ship work
*descriptions*, not payloads.

Level 2 — **electron result memoization** (:class:`ResultCache`).  An
opt-in, disk-backed LRU keyed by (function digest, call digest, executor
environment fingerprint): a repeat dispatch of an identical electron
returns the completed result without touching the transport at all.
Bounded by entry count and total bytes; only *successful* results are
stored (failures and fallbacks always re-run), and memoization is only
safe for side-effect-free electrons — it is off unless ``cache_results``
/ ``COVALENT_TPU_RESULT_CACHE`` asks for it.

Both levels record into the PR-1 obs layer:
``covalent_tpu_cas_uploads_total{result=hit|miss}`` and
``covalent_tpu_result_cache_total{result=...}`` counters, plus an
``executor.cas_put`` span per *actual* upload so the span histogram shows
the put traffic falling off after warm-up.
"""

from __future__ import annotations

import asyncio
import hashlib
import os
import pickle
import threading
import uuid
from functools import lru_cache
from typing import Any

import cloudpickle

from .obs import events as obs_events
from .obs.metrics import REGISTRY
from .obs.trace import Span
from .transport import codec as codec_mod
from .transport.base import Transport, TransportError
from .utils.log import app_log

__all__ = [
    "CAS_DIR",
    "CASIndex",
    "FnRegistry",
    "ResultCache",
    "bytes_digest",
    "cas_bytes_prune_command",
    "cas_path",
    "file_digest",
    "harness_digest",
    "prune_cas_dir",
    "CAS_EVICTIONS_TOTAL",
    "CAS_UPLOADS_TOTAL",
    "RESULT_CACHE_TOTAL",
    "RPC_REGISTRATIONS_TOTAL",
    "STAGING_OPS_TOTAL",
]

#: Subdirectory of ``remote_cache`` holding digest-addressed artifacts.
CAS_DIR = "cas"

CAS_UPLOADS_TOTAL = REGISTRY.counter(
    "covalent_tpu_cas_uploads_total",
    "CAS artifact upload decisions (hit = worker already holds the digest, "
    "put skipped; miss = payload shipped)",
    ("result",),
)
RESULT_CACHE_TOTAL = REGISTRY.counter(
    "covalent_tpu_result_cache_total",
    "Electron result-memoization events by result",
    ("result",),
)
STAGING_OPS_TOTAL = REGISTRY.counter(
    "covalent_tpu_staging_ops_total",
    "Control-plane round trips spent shipping staged artifacts, by path "
    "(per_file = put+publish per artifact, bundled = one tar per worker)",
    ("mode",),
)
RPC_REGISTRATIONS_TOTAL = REGISTRY.counter(
    "covalent_tpu_rpc_registrations_total",
    "RPC function-registry decisions (hit = the connection's resident "
    "runtime already holds the digest; miss = bytecode registered)",
    ("result",),
)


CAS_EVICTIONS_TOTAL = REGISTRY.counter(
    "covalent_tpu_cas_evictions_total",
    "CAS artifacts evicted by the byte-budget LRU prune "
    "(site = the dispatcher's local mirror vs a worker's remote cache)",
    ("site",),
)


def bytes_digest(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def file_digest(path: str) -> str:
    """Streaming sha256 of a file's content."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


@lru_cache(maxsize=1)
def harness_digest() -> str:
    """Digest of the (static) worker harness module — one hash per process.

    The harness is copied verbatim to workers (harness.py module docstring),
    so its digest is constant for an installed package version; memoizing it
    keeps the stage path at one sha256 of the function pickle + specs.
    """
    from . import harness as _harness_module

    return file_digest(_harness_module.__file__)


def cas_path(remote_cache: str, digest: str, suffix: str = "") -> str:
    """Digest-addressed remote path under ``{remote_cache}/cas/``."""
    return f"{remote_cache}/{CAS_DIR}/{digest}{suffix}"


def prune_cas_dir(root: str, max_bytes: int) -> int:
    """Byte-budget LRU prune of one CAS directory; returns evictions.

    The ``cas_ttl_hours`` age prune bounds *staleness* but not *size*:
    KV bundles (disaggregated serving) are orders of magnitude larger
    than function pickles and can fill a disk well inside the TTL.
    Oldest-access-first (mtime — the maintenance pass ``touch``\\ es hot
    artifacts, so recency IS the mtime) until the directory fits
    ``max_bytes``; 0 disables.  Best-effort: a file vanishing mid-scan
    (a concurrent prune, an in-flight publish) is skipped, never an
    error.
    """
    if max_bytes <= 0:
        return 0
    entries: list[tuple[float, int, str]] = []
    try:
        names = os.listdir(root)
    except OSError:
        return 0
    for name in names:
        path = os.path.join(root, name)
        try:
            stat = os.stat(path)
        except OSError:
            continue
        if os.path.isfile(path):
            entries.append((stat.st_mtime, stat.st_size, path))
    entries.sort()
    total = sum(size for _, size, _ in entries)
    evicted = 0
    for _, size, path in entries:
        if total <= max_bytes:
            break
        try:
            os.remove(path)
        except OSError:
            continue
        total -= size
        evicted += 1
    if evicted:
        CAS_EVICTIONS_TOTAL.labels(site="local").inc(evicted)
        obs_events.emit(
            "cas.bytes_pruned", root=root, evicted=evicted,
            budget=max_bytes,
        )
    return evicted


#: Remote mirror of :func:`prune_cas_dir` — runs under the worker's own
#: interpreter inside the per-electron maintenance round trip, printing
#: ``CAS_EVICTED=<n>`` for the dispatcher to account.  Kept tiny and
#: stdlib-only (it executes via ``python -E -S -c``).
_REMOTE_PRUNE_PROGRAM = """\
import os, sys
root, budget = sys.argv[1], int(sys.argv[2])
entries = []
try:
    names = os.listdir(root)
except OSError:
    names = []
for name in names:
    path = os.path.join(root, name)
    try:
        stat = os.stat(path)
    except OSError:
        continue
    if os.path.isfile(path):
        entries.append((stat.st_mtime, stat.st_size, path))
entries.sort()
total = sum(size for _, size, _ in entries)
evicted = 0
for _, size, path in entries:
    if total <= budget:
        break
    try:
        os.remove(path)
    except OSError:
        continue
    total -= size
    evicted += 1
print('CAS_EVICTED=%d' % evicted)
"""


def cas_bytes_prune_command(
    python_path: str, cas_dir: str, max_bytes: int
) -> str:
    """Shell clause running the byte-budget LRU prune on a worker."""
    import shlex

    return (
        f"{python_path} -E -S -c {shlex.quote(_REMOTE_PRUNE_PROGRAM)} "
        f"{shlex.quote(cas_dir)} {int(max_bytes)}"
    )


class CASIndex:
    """Per-connection "already present" digest sets with single-flight puts.

    Keys are the executor's pool keys (``transport:address``) — the same
    identity the transport pool and pre-flight cache use — so a discarded
    connection evicts its CAS knowledge with it (:meth:`forget`) and a
    recreated worker re-probes instead of trusting stale state.
    """

    def __init__(self) -> None:
        self._present: dict[str, set[str]] = {}
        self._probed: set[str] = set()
        #: (key, digest) -> future resolved when the winning put settles;
        #: losers re-check the present set and retry if the put failed.
        self._inflight: dict[tuple[str, str], asyncio.Future] = {}
        self._probe_locks: dict[str, asyncio.Lock] = {}

    def known(self, key: str, digest: str) -> bool:
        return digest in self._present.get(key, ())

    def holds(self, digest: str) -> bool:
        """Whether ANY live connection's present set holds ``digest`` —
        the replica-placement affinity probe (a holding gang re-stages
        nothing when a serving session of that factory re-opens)."""
        return bool(digest) and any(
            digest in present for present in self._present.values()
        )

    async def ensure_probed(
        self, key: str, conn: Transport, entries: list[tuple[str, str]]
    ) -> None:
        """Seed ``key``'s present set with ONE batched existence probe.

        ``entries`` is ``[(digest, remote_path), ...]`` for the artifacts
        about to upload.  Runs at most once per key: later electrons trust
        the locally-maintained set instead of re-probing (a fresh digest
        they introduce is simply treated as absent and uploaded).
        """
        if key in self._probed:
            return
        lock = self._probe_locks.setdefault(key, asyncio.Lock())
        async with lock:
            if key in self._probed:
                return
            present = self._present.setdefault(key, set())
            paths = [path for _, path in entries]
            try:
                flags = await conn.exists_batch(paths)
            except (TransportError, OSError) as err:
                # The batched probe is an optimization, never a
                # correctness gate: degrade to per-artifact probes, and
                # from there to all-absent — a spurious re-upload at worst,
                # never a failed pre-flight.  (If the channel is truly
                # dead, the uploads that follow will say so.)
                app_log.warning(
                    "CAS batched probe on %s failed (%s); "
                    "falling back to per-artifact probes", key, err,
                )
                obs_events.emit(
                    "cas.probe_fallback", key=key, error=repr(err)
                )
                flags = await self._probe_each(conn, paths)
            for (digest, _), held in zip(entries, flags):
                if held:
                    present.add(digest)
            self._probed.add(key)
            obs_events.emit(
                "cas.probed",
                key=key,
                probed=len(entries),
                already_present=sum(flags),
            )

    @staticmethod
    async def _probe_each(conn: Transport, paths: list[str]) -> list[bool]:
        """One ``test -e`` round-trip per artifact; failures read as absent."""
        import shlex

        flags = []
        for path in paths:
            try:
                result = await conn.run(f"test -e {shlex.quote(path)}")
                flags.append(result.exit_status == 0)
            except (TransportError, OSError):
                flags.append(False)
        return flags

    async def ensure(
        self,
        key: str,
        conn: Transport,
        digest: str,
        local_path: str,
        remote_path: str,
        *,
        codec: "codec_mod.Codec | None" = None,
        python_path: str = "python3",
    ) -> None:
        """Upload ``local_path`` unless ``key`` already holds ``digest``.

        Single-flight per (key, digest): concurrent electrons of a fan-out
        sharing one function pickle trigger exactly one put; the rest await
        it and count as hits.  With a negotiated ``codec``, the payload
        ships compressed and the remote publish verifies the CAS digest
        against the *decompressed* bytes — same round-trip count as the
        raw temp-put + atomic-rename path (codec.put_file), fewer bytes.
        """
        while True:
            present = self._present.setdefault(key, set())
            if digest in present:
                CAS_UPLOADS_TOTAL.labels(result="hit").inc()
                return
            pending = self._inflight.get((key, digest))
            if pending is None:
                break
            await pending  # winner settles (never raises: result-only)
        future: asyncio.Future = asyncio.get_running_loop().create_future()
        self._inflight[(key, digest)] = future
        try:
            with Span(
                "executor.cas_put",
                {"key": key, "digest": digest[:12]},
            ):
                # Atomic publish either way: CAS paths are shared across
                # executors (each workflow dispatch builds its own index),
                # so another dispatcher's existence probe must never see a
                # half-written artifact at the digest path.  Orphaned .tmp
                # files from a crashed put are swept by the pre-flight TTL
                # prune.
                stats = await codec_mod.put_file(
                    conn, local_path, remote_path,
                    codec=codec, python_path=python_path, digest=digest,
                )
            STAGING_OPS_TOTAL.labels(mode="per_file").inc(stats["ops"])
            present.add(digest)
            CAS_UPLOADS_TOTAL.labels(result="miss").inc()
        finally:
            self._inflight.pop((key, digest), None)
            if not future.done():
                future.set_result(None)

    async def ensure_bundle(
        self,
        key: str,
        conn: Transport,
        artifacts: "list[tuple[str, str, str]]",
        *,
        codec: "codec_mod.Codec | None" = None,
        python_path: str = "python3",
    ) -> None:
        """Ship every missing artifact of ``[(local, remote, digest)]`` in
        ONE bundle (one put + one unpack exec) instead of per-file pairs.

        Artifacts the worker already holds (or that a concurrent electron
        is uploading) count as hits exactly like :meth:`ensure`; when at
        most one artifact is actually missing the per-file path is used —
        a bundle of one would pay tar overhead for zero round-trip
        savings.  Missing digests are registered in the single-flight map
        for the bundle's duration, so a concurrent electron sharing the
        function pickle awaits this bundle instead of double-uploading.
        """
        # Wait out any in-flight uploads overlapping our artifact set, then
        # settle hits/misses against the post-wait present set.
        while True:
            pending = [
                self._inflight[(key, digest)]
                for _, _, digest in artifacts
                if (key, digest) in self._inflight
            ]
            if not pending:
                break
            await asyncio.gather(*pending)
        present = self._present.setdefault(key, set())
        missing: list[tuple[str, str, str]] = []
        seen: set[str] = set()
        for local, remote, digest in artifacts:
            if digest in present:
                CAS_UPLOADS_TOTAL.labels(result="hit").inc()
            elif digest not in seen:  # identical payloads bundle once
                seen.add(digest)
                missing.append((local, remote, digest))
        if len(missing) <= 1:
            for local, remote, digest in missing:
                await self.ensure(
                    key, conn, digest, local, remote,
                    codec=codec, python_path=python_path,
                )
            return
        loop = asyncio.get_running_loop()
        futures = {}
        for _, _, digest in missing:
            futures[digest] = loop.create_future()
            self._inflight[(key, digest)] = futures[digest]
        try:
            bundle_path = (
                f"{os.path.dirname(missing[0][1])}/"
                f"bundle-{uuid.uuid4().hex[:12]}.tar"
            )
            with Span(
                "executor.cas_bundle",
                {"key": key, "members": len(missing)},
            ):
                stats = await conn.put_bundle(
                    missing, bundle_path,
                    python_path=python_path, codec=codec,
                )
            STAGING_OPS_TOTAL.labels(mode="bundled").inc(stats["ops"])
            for _, _, digest in missing:
                present.add(digest)
                CAS_UPLOADS_TOTAL.labels(result="miss").inc()
            obs_events.emit(
                "cas.bundle",
                key=key,
                members=len(missing),
                wire_bytes=stats["wire_bytes"],
                codec=stats["codec"],
            )
        finally:
            for _, _, digest in missing:
                self._inflight.pop((key, digest), None)
                if not futures[digest].done():
                    futures[digest].set_result(None)

    def forget(self, key: str) -> None:
        """Evict one connection's CAS knowledge (channel discarded: the
        worker may have been preempted/recreated with an empty cache)."""
        self._present.pop(key, None)
        self._probed.discard(key)
        self._probe_locks.pop(key, None)

    def forget_digest(self, digest: str) -> None:
        """Drop one digest from every present set (its remote file was
        deleted, e.g. a per-operation spec removed by cleanup)."""
        for present in self._present.values():
            present.discard(digest)


class FnRegistry:
    """Per-connection registered-function digests for RPC dispatch.

    Mirrors :class:`CASIndex`: keyed by the executor's pool keys, with
    single-flight registration so a fan-out of electrons sharing one
    function triggers exactly one ``register_fn`` round trip per
    connection, and per-key eviction (:meth:`forget`) when the channel is
    discarded.  One extra wrinkle the CAS doesn't have: the remote
    registry lives in the *agent process*, not on disk, so a restarted
    agent under the same pool key silently loses everything — each set is
    therefore bound to the client object that populated it, and a new
    client resets the set before its first registration.
    """

    def __init__(self) -> None:
        self._registered: dict[str, set[str]] = {}
        #: pool key -> id(client) whose resident runtime owns the set.
        self._owners: dict[str, int] = {}
        self._inflight: dict[tuple[str, str], asyncio.Future] = {}

    def known(self, key: str, digest: str) -> bool:
        return digest in self._registered.get(key, ())

    def holds(self, digest: str) -> bool:
        """Whether ANY live connection registered this digest — the fleet
        scheduler's placement-affinity probe."""
        return any(digest in held for held in self._registered.values())

    def count(self, key: str) -> int:
        return len(self._registered.get(key, ()))

    def counts(self) -> dict[str, int]:
        """pool key -> registered-digest count (ops ``/status`` view)."""
        return {key: len(held) for key, held in self._registered.items()}

    def digests(self) -> set[str]:
        """Union of registered digests across every connection."""
        out: set[str] = set()
        for held in self._registered.values():
            out |= held
        return out

    async def ensure(
        self,
        key: str,
        client,
        digest: str,
        path: str,
        runner: "list[str] | None" = None,
    ) -> None:
        """Register ``digest`` on ``key``'s resident runtime, at most once.

        ``client`` is the live :class:`~covalent_tpu_plugin.agent.
        AgentClient`; its ``register_fn`` digest-verifies the CAS artifact
        remotely before unpickling.  Raises exactly what the client
        raises (``AgentError`` — a digest mismatch arrives tagged
        permanent), leaving the digest unregistered so a retry re-runs
        the registration.
        """
        if self._owners.get(key) != id(client):
            # Fresh client under this key: the old resident runtime (and
            # its in-process registry) is gone — re-register everything.
            self._registered.pop(key, None)
            self._owners[key] = id(client)
        while True:
            registered = self._registered.setdefault(key, set())
            if digest in registered:
                RPC_REGISTRATIONS_TOTAL.labels(result="hit").inc()
                return
            pending = self._inflight.get((key, digest))
            if pending is None:
                break
            await pending  # winner settles (result-only, never raises)
        future: asyncio.Future = asyncio.get_running_loop().create_future()
        self._inflight[(key, digest)] = future
        try:
            with Span(
                "executor.rpc_register",
                {"key": key, "digest": digest[:12]},
            ):
                await client.register_fn(digest, path, runner=runner)
            registered.add(digest)
            RPC_REGISTRATIONS_TOTAL.labels(result="miss").inc()
        finally:
            self._inflight.pop((key, digest), None)
            if not future.done():
                future.set_result(None)

    def forget(self, key: str) -> None:
        """Evict one connection's registrations (channel discarded)."""
        self._registered.pop(key, None)
        self._owners.pop(key, None)


class ResultCache:
    """Disk-backed LRU of completed electron results.

    One file per entry (``{key}.pkl`` under ``root``); recency is the
    file's mtime, touched on every hit, so the store survives process
    restarts and is shared by every executor instance pointing at the same
    ``cache_dir`` — including the fresh executor each workflow dispatch
    resolves from a string alias.  Bounded by ``max_entries`` and
    ``max_bytes`` with oldest-first eviction.
    """

    def __init__(
        self,
        root: str,
        max_entries: int = 512,
        max_bytes: int = 256 * 1024 * 1024,
    ) -> None:
        self.root = root
        self.max_entries = max(1, int(max_entries))
        self.max_bytes = max(1, int(max_bytes))
        self._lock = threading.Lock()

    @staticmethod
    def make_key(*parts: str) -> str:
        return bytes_digest("\x00".join(parts).encode())

    def _path(self, key: str) -> str:
        return os.path.join(self.root, f"{key}.pkl")

    def get(self, key: str) -> tuple[bool, Any]:
        """``(hit, value)`` — a corrupt/missing entry is a miss, never an
        error in the dispatch it was accelerating."""
        path = self._path(key)
        with self._lock:
            try:
                with open(path, "rb") as f:
                    value = pickle.load(f)
                os.utime(path)  # LRU touch
            except Exception:  # noqa: BLE001 - any corrupt entry is a miss
                RESULT_CACHE_TOTAL.labels(result="miss").inc()
                return False, None
        RESULT_CACHE_TOTAL.labels(result="hit").inc()
        return True, value

    def put(self, key: str, value: Any) -> bool:
        """Best-effort store; returns True when the entry landed."""
        try:
            data = cloudpickle.dumps(value)
        except Exception as err:  # noqa: BLE001 - arbitrary user objects
            RESULT_CACHE_TOTAL.labels(result="unpicklable").inc()
            app_log.debug("result cache: value not picklable (%s)", err)
            return False
        if len(data) > self.max_bytes:
            RESULT_CACHE_TOTAL.labels(result="oversize").inc()
            return False
        path = self._path(key)
        with self._lock:
            try:
                os.makedirs(self.root, exist_ok=True)
                tmp = f"{path}.tmp.{os.getpid()}"
                with open(tmp, "wb") as f:
                    f.write(data)
                os.replace(tmp, path)
            except OSError as err:
                RESULT_CACHE_TOTAL.labels(result="error").inc()
                app_log.warning("result cache write failed: %s", err)
                return False
            RESULT_CACHE_TOTAL.labels(result="store").inc()
            self._evict_locked()
        return True

    def _entries(self) -> list[tuple[float, int, str]]:
        out = []
        try:
            with os.scandir(self.root) as it:
                for entry in it:
                    if not entry.name.endswith(".pkl"):
                        continue
                    try:
                        stat = entry.stat()
                    except OSError:
                        continue
                    out.append((stat.st_mtime, stat.st_size, entry.path))
        except OSError:
            return []
        return sorted(out)

    def _evict_locked(self) -> None:
        entries = self._entries()
        total = sum(size for _, size, _ in entries)
        evicted = 0
        while entries and (
            len(entries) > self.max_entries or total > self.max_bytes
        ):
            _, size, path = entries.pop(0)
            try:
                os.remove(path)
            except OSError:
                continue
            total -= size
            evicted += 1
            RESULT_CACHE_TOTAL.labels(result="evict").inc()
        if evicted:
            obs_events.emit(
                "result_cache.evicted", count=evicted, root=self.root
            )

    def clear(self) -> None:
        with self._lock:
            for _, _, path in self._entries():
                try:
                    os.remove(path)
                except OSError:
                    pass

    def __len__(self) -> int:
        return len(self._entries())
