"""``RemoteExecutor`` template.

The reference subclasses Covalent's async remote-executor template
(``covalent_ssh_plugin/ssh.py:32`` —
``from covalent.executor.executor_plugins.remote_executor import
RemoteExecutor``).  When Covalent is installed we use the real class so
``TPUExecutor`` plugs into a live server unmodified; otherwise this module
provides a behaviour-compatible shim exposing the same abstract lifecycle
(`_validate_credentials`, `_upload_task`, `submit_task`, `get_status`,
`_poll_task`, `query_result`, `cancel`, `run` — signatures at
``ssh.py:317,337,363,388,408,434,460,466``), keeping the framework fully
standalone.
"""

from __future__ import annotations

from abc import abstractmethod
from typing import Any, Callable

try:  # covered by the stub-covalent interop tier when importable
    from covalent.executor.executor_plugins.remote_executor import (
        RemoteExecutor as _CovalentRemoteExecutor,
    )

    _BASE: type = _CovalentRemoteExecutor
    HAVE_COVALENT = True
except Exception:
    HAVE_COVALENT = False

    class _StandaloneRemoteExecutor:
        """Async executor template (standalone stand-in for Covalent's)."""

        def __init__(
            self,
            poll_freq: float = 15,
            remote_cache: str = "",
            credentials_file: str = "",
        ) -> None:
            self.poll_freq = poll_freq
            self.remote_cache = remote_cache
            self.credentials_file = credentials_file

        @abstractmethod
        async def _validate_credentials(self) -> bool: ...

        @abstractmethod
        async def _upload_task(self, *args, **kwargs) -> None: ...

        @abstractmethod
        async def submit_task(self, *args, **kwargs) -> Any: ...

        @abstractmethod
        async def get_status(self, *args, **kwargs) -> Any: ...

        @abstractmethod
        async def _poll_task(self, *args, **kwargs) -> Any: ...

        @abstractmethod
        async def query_result(self, *args, **kwargs) -> Any: ...

        @abstractmethod
        async def cancel(self, *args, **kwargs) -> None: ...

        @abstractmethod
        async def run(
            self, function: Callable, args: list, kwargs: dict, task_metadata: dict
        ) -> Any: ...

    _BASE = _StandaloneRemoteExecutor

RemoteExecutor = _BASE

__all__ = ["RemoteExecutor", "HAVE_COVALENT"]
