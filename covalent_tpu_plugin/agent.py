"""Client for the resident worker agent (``native/agent.cc``).

The reference's submit/status protocol costs one SSH round-trip per probe
(``covalent_ssh_plugin/ssh.py:383`` submit, ``ssh.py:402-406`` status,
``ssh.py:408-432`` poll loop).  The agent collapses all of that into one
persistent channel per worker: the executor writes a ``run`` command and the
agent *pushes* ``started``/``exit`` events the moment they happen — no poll
traffic, and task-completion latency bounded by the channel RTT instead of
the poll interval.

Deployment is self-contained: the single C++ source ships inside this
package, is uploaded to the worker's cache dir, and is compiled there by the
system compiler (cached by content hash, so compilation happens once per
worker per agent version).  Workers without a C++ toolchain simply raise
:class:`AgentError` and the executor falls back to the stateless
``nohup`` + poll protocol — the agent is an accelerator, never a
requirement.  Agent-launched tasks run in their own sessions, so even if the
agent or its channel dies mid-task, the fallback poller can resume
supervision using the PID from the ``started`` event.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import shlex
import uuid
from functools import lru_cache
from pathlib import Path
from typing import Any

from .obs import events as obs_events
from .obs.metrics import REGISTRY
from .obs.trace import Span
from .transport.base import Transport, TransportError
from .utils.log import app_log

_AGENT_RPCS = REGISTRY.counter(
    "covalent_tpu_agent_rpcs_total",
    "Commands written to resident agent channels",
    ("cmd",),
)
_AGENT_EVENTS = REGISTRY.counter(
    "covalent_tpu_agent_events_total",
    "Events pushed by resident agent channels",
    ("event",),
)
AGENT_RESTARTS_TOTAL = REGISTRY.counter(
    "covalent_tpu_agent_restarts_total",
    "Cached agent channels discarded and restarted after a failed ping",
)

AGENT_SOURCE = Path(__file__).parent / "native" / "agent.cc"

#: Remote filename of the staged harness module.  Shared by the per-task
#: stager (StagedTask.remote_harness_file) and the pool server so the
#: resident interpreter always serves the same file task specs point at.
HARNESS_BASENAME = "covalent_tpu_harness.py"


class AgentError(TransportError):
    """Agent unavailable or its channel failed; callers fall back to polling."""


@lru_cache(maxsize=1)
def agent_source_hash() -> str:
    """Content hash naming the remote binary, so stale agents never run."""
    return hashlib.sha256(AGENT_SOURCE.read_bytes()).hexdigest()[:12]


async def ensure_agent_binary(conn: Transport, remote_cache: str) -> str:
    """Upload + compile the agent on the worker (idempotent, hash-cached).

    One round-trip when the binary already exists; upload + one compile
    round-trip the first time.  Raises :class:`AgentError` when the worker
    has no C++ compiler.
    """
    binary = f"{remote_cache}/agent_{agent_source_hash()}"
    q_binary = shlex.quote(binary)
    # mkdir rides the probe: this may run concurrently with (or before) the
    # executor preflight that normally creates the cache dir.
    probe = await conn.run(
        f"mkdir -p {shlex.quote(remote_cache)}; "
        f"test -x {q_binary} && echo HAVE || echo MISSING"
    )
    if "HAVE" in probe.stdout:
        return binary

    source = f"{binary}.cc"
    await conn.put(str(AGENT_SOURCE), source)
    # Unique tmp name + atomic mv so concurrent electrons can race safely.
    tmp = shlex.quote(f"{binary}.tmp.{uuid.uuid4().hex[:8]}")
    build = await conn.run(
        "CXX=$(command -v g++ || command -v c++ || command -v clang++) "
        "&& [ -n \"$CXX\" ] "
        f"&& $CXX -O2 -std=c++17 -o {tmp} {shlex.quote(source)} "
        f"&& mv {tmp} {q_binary}",
        timeout=120.0,
    )
    if build.exit_status != 0:
        raise AgentError(
            f"no agent on {conn.address}: compile failed or no C++ compiler "
            f"({build.stderr.strip()[:200]})"
        )
    return binary


async def start_pool_server(
    conn: Transport,
    remote_cache: str,
    python_path: str,
    conda_env: str = "",
    preload: str = "cloudpickle",
    timeout: float = 90.0,
) -> "AgentClient":
    """Start the harness forkserver (``harness.py --serve``) on a worker.

    The resident interpreter preloads ``preload`` modules once; each task
    then costs a fork instead of interpreter startup + imports.  The
    generous timeout covers a cold jax import on the worker.  Speaks the
    same protocol as the native agent, so the returned client is a drop-in
    (``mode == "pool"``).
    """
    from . import harness as harness_module

    remote_harness = f"{remote_cache}/{HARNESS_BASENAME}"
    try:
        await conn.run(f"mkdir -p {shlex.quote(remote_cache)}")
        await conn.put(harness_module.__file__, remote_harness)
    except TransportError as err:
        raise AgentError(f"cannot stage pool server on {conn.address}: {err}") from err

    command = (
        f"env COVALENT_TPU_POOL_PRELOAD={shlex.quote(preload)} "
        f"{python_path} {shlex.quote(remote_harness)} --serve"
    )
    if conda_env:
        command = (
            f'eval "$(conda shell.bash hook)" && conda activate '
            f"{shlex.quote(conda_env)} && {command}"
        )
    try:
        process = await conn.start_process(command, describe=f"pool@{conn.address}")
    except TransportError as err:
        raise AgentError(f"cannot start pool server on {conn.address}: {err}") from err
    client = AgentClient(process, conn.address)
    client.mode = "pool"
    try:
        await client.ping(timeout)
    except AgentError:
        await client.close()
        raise
    return client


class AgentClient:
    """One agent channel to one worker, demultiplexing pushed events.

    A background reader drains the channel and files events by task id;
    any number of concurrent tasks can await their own ``started``/``exit``
    notifications.
    """

    #: "native" (C++ agent, argv exec) or "pool" (harness forkserver, spec).
    mode: str = "native"

    def __init__(self, process, address: str):
        self._process = process
        self.address = address
        self._started: dict[str, int] = {}
        self._exits: dict[str, tuple[int, int]] = {}
        self._errors: dict[str, str] = {}
        #: function digests this channel's resident runtime has registered
        #: (RPC dispatch); dies with the client, exactly like the remote
        #: registry dies with the agent process.
        self._registered: set[str] = set()
        #: digest -> (code, message) for a failed registration.
        self._register_errors: dict[str, tuple[str, str]] = {}
        #: task id -> pushed ``result`` event (RPC invocations).
        self._results: dict[str, dict] = {}
        self._pongs = 0
        self._dead: BaseException | None = None
        self._cond = asyncio.Condition()
        #: sink for backhauled telemetry lines: called ``(task_id, data)``
        #: for every FRESH event the agent's watch side-band pushes.  Set
        #: by the executor; exceptions are contained (observer contract).
        self.on_telemetry = None
        #: task id -> highest worker-event ``seq`` seen; a re-watch after a
        #: reconnect re-tails from offset 0, so duplicates are expected and
        #: dropped here.
        self._telemetry_seq: dict[str, int] = {}
        #: serving sessions: sid -> pushed serve_opened / serve_error /
        #: serve_closed events, and sid -> per-session telemetry sink
        #: (serve.token / serve.reject / serve.stats data routed here
        #: instead of :attr:`on_telemetry`).
        self._serve_opened: dict[str, dict] = {}
        self._serve_errors: dict[str, dict] = {}
        self._serve_closed: dict[str, dict] = {}
        self._serve_sinks: dict[str, Any] = {}
        #: resident-mode profiling: profile id -> pushed profile_started /
        #: profile_stopped / profile_error events.
        self._profile_started: dict[str, dict] = {}
        self._profile_stopped: dict[str, dict] = {}
        self._profile_errors: dict[str, dict] = {}
        self._reader = asyncio.create_task(self._read_loop())

    # -- lifecycle -----------------------------------------------------------

    @classmethod
    async def start(
        cls, conn: Transport, binary: str, timeout: float = 15.0
    ) -> "AgentClient":
        try:
            process = await conn.start_process(
                shlex.quote(binary), describe=f"agent@{conn.address}"
            )
        except TransportError as err:
            raise AgentError(f"cannot start agent on {conn.address}: {err}") from err
        client = cls(process, conn.address)
        try:
            # A ping round-trip both consumes the ready banner and proves the
            # channel is live before any task is entrusted to it.
            await client.ping(timeout)
        except AgentError:
            await client.close()
            raise
        return client

    @property
    def alive(self) -> bool:
        return self._dead is None and not self._reader.done()

    async def close(self) -> None:
        try:
            if self._dead is None:
                await self._process.write_line('{"cmd":"shutdown"}')
        except TransportError:
            pass
        self._reader.cancel()
        try:
            await self._reader
        except (asyncio.CancelledError, Exception):
            pass
        await self._process.close()

    # -- event plumbing ------------------------------------------------------

    async def _read_loop(self) -> None:
        try:
            while True:
                line = await self._process.read_line()
                try:
                    event = json.loads(line)
                except ValueError:
                    continue  # stray non-protocol output; ignore
                async with self._cond:
                    kind = event.get("event")
                    task_id = event.get("id", "")
                    _AGENT_EVENTS.labels(event=str(kind)).inc()
                    if kind == "telemetry":
                        self._handle_telemetry(task_id, event.get("data"))
                        continue  # side-band: no waiter state to notify
                    if kind == "started":
                        self._started[task_id] = int(event["pid"])
                    elif kind == "serve_opened":
                        self._serve_opened[task_id] = event
                    elif kind == "serve_error":
                        self._serve_errors[task_id] = event
                    elif kind == "serve_closed":
                        self._serve_closed[task_id] = event
                    elif kind == "profile_started":
                        self._profile_started[task_id] = event
                    elif kind == "profile_stopped":
                        self._profile_stopped[task_id] = event
                    elif kind == "profile_error":
                        self._profile_errors[task_id] = event
                    elif kind == "exit":
                        self._exits[task_id] = (
                            int(event.get("code", -1)),
                            int(event.get("signal", 0)),
                        )
                    elif kind == "result":
                        self._results[task_id] = event
                    elif kind == "registered":
                        self._registered.add(str(event.get("digest") or ""))
                    elif kind == "register_error":
                        self._register_errors[
                            str(event.get("digest") or "")
                        ] = (
                            str(event.get("code") or "error"),
                            str(event.get("message") or "?"),
                        )
                    elif kind == "pong":
                        self._pongs += 1
                    elif kind == "error":
                        if task_id:  # id-less errors are log-only, not stored
                            self._errors[task_id] = str(event.get("message", "?"))
                        app_log.warning(
                            "agent@%s error: %s", self.address, event.get("message")
                        )
                    self._cond.notify_all()
        except asyncio.CancelledError:
            raise
        except BaseException as err:  # noqa: BLE001 - ANY reader death must
            # wake waiters: an unnotified exception here would leave
            # wait_exit() blocked forever (e.g. asyncssh.ConnectionLost is
            # neither TransportError nor OSError).
            obs_events.emit(
                "agent.channel_died", address=self.address, error=repr(err)
            )
            async with self._cond:
                self._dead = err
                self._cond.notify_all()

    def _handle_telemetry(self, task_id: str, data) -> None:
        """Dedup one backhauled event by ``seq`` and hand it to the sink.

        Worker events carry a per-process monotonically increasing ``seq``
        (harness ``_emit_worker_event``); a re-watch after channel loss
        replays the whole file, so everything at-or-below the high-water
        mark is a duplicate.  Events without a seq pass through — better a
        duplicate observation than a dropped one.
        """
        if not isinstance(data, dict):
            return
        seq = data.get("seq")
        if isinstance(seq, int):
            if seq <= self._telemetry_seq.get(task_id, 0):
                return
            self._telemetry_seq[task_id] = seq
        # Serving sessions own their side-band traffic: every record for a
        # watched sid (tokens, rejects, stats) routes to that session's
        # sink instead of the executor's generic backhaul handler.
        callback = self._serve_sinks.get(task_id) or self.on_telemetry
        if callback is None:
            return
        try:
            callback(task_id, data)
        except Exception as err:  # noqa: BLE001 - observers must not break
            app_log.debug("telemetry callback failed: %s", err)

    async def watch(self, task_id: str, path: str) -> None:
        """Start the telemetry side-band for one task's worker-local file.

        The agent tails ``path`` from offset 0 (flushing any backlog
        buffered while no channel was attached) and pushes each JSONL line
        as a ``telemetry`` event routed to :attr:`on_telemetry`.
        """
        await self._send({"cmd": "watch", "id": task_id, "path": path})

    async def unwatch(self, task_id: str) -> None:
        await self._send({"cmd": "unwatch", "id": task_id})

    async def _wait(self, predicate, timeout: float | None):
        """Await ``predicate(self)`` truthy, raising AgentError on channel death."""

        async def waiter():
            async with self._cond:
                while True:
                    if self._dead is not None:
                        raise AgentError(
                            f"agent@{self.address} channel died: {self._dead}"
                        )
                    value = predicate(self)
                    if value:
                        return value
                    await self._cond.wait()

        try:
            return await asyncio.wait_for(waiter(), timeout)
        except asyncio.TimeoutError:
            raise AgentError(f"agent@{self.address}: no event within {timeout}s")

    # -- commands ------------------------------------------------------------

    async def ping(self, timeout: float = 15.0) -> None:
        before = self._pongs
        await self._send({"cmd": "ping"})
        await self._wait(lambda c: c._pongs > before, timeout)

    async def run_task(
        self,
        task_id: str,
        argv: list[str] | None = None,
        cwd: str = "",
        env: dict[str, str] | None = None,
        log: str = "",
        timeout: float = 30.0,
        spec: str = "",
    ) -> int:
        """Launch a task; returns the remote PID from the ``started`` event.

        ``argv`` targets the native C++ agent (it execs the command);
        ``spec`` targets the harness pool server (it forks and runs the spec
        in the pre-warmed interpreter).  Exactly one must be given.
        """
        command: dict = {"cmd": "run", "id": task_id}
        if spec:
            command["spec"] = spec
        else:
            command["argv"] = list(argv or [])
        if cwd:
            command["cwd"] = cwd
        if env:
            command["env"] = {str(k): str(v) for k, v in env.items()}
        if log:
            command["log"] = log
        sent = False
        # The span times command-write -> `started` push: the agent-path
        # analog of submit_task's round-trip, and the number that proves
        # (or disproves) the resident runtime's launch-latency win.
        submit_span = Span(
            "agent.submit", {"address": self.address, "task_id": task_id}
        )
        submit_span.__enter__()
        try:
            await self._send(command)
            sent = True

            def ready(c: "AgentClient"):
                if task_id in c._errors:
                    rejection = AgentError(
                        f"agent@{c.address} rejected {task_id}: "
                        f"{c._errors.pop(task_id)}"
                    )
                    # A definitive rejection means the task never forked:
                    # relaunching through the fallback path is safe.
                    rejection.rejected = True  # type: ignore[attr-defined]
                    raise rejection
                return c._started.get(task_id)

            # Pop on success: a resident client serves many electrons;
            # per-task entries must not accumulate for the channel's lifetime.
            pid = await self._wait(ready, timeout)
            self._started.pop(task_id, None)
            return pid
        except AgentError as err:
            # Once the run command left for the worker, the harness may
            # already be alive there even though we never saw `started` —
            # the caller must NOT relaunch (double harness), only abort.
            # Exception: an explicit error event proves it never started.
            err.maybe_started = sent and not getattr(  # type: ignore[attr-defined]
                err, "rejected", False
            )
            submit_span.record_error(err)
            raise
        finally:
            submit_span.end()

    async def wait_exit(
        self, task_id: str, timeout: float | None = None
    ) -> tuple[int, int]:
        """Block until the pushed exit event: ``(exit_code, term_signal)``."""
        event = await self._wait(lambda c: c._exits.get(task_id), timeout)
        self._exits.pop(task_id, None)
        return event

    # -- RPC execute-by-digest ----------------------------------------------

    @property
    def registered_digests(self) -> frozenset:
        """Function digests this channel's resident runtime holds."""
        return frozenset(self._registered)

    async def register_fn(
        self,
        digest: str,
        path: str,
        runner: list[str] | None = None,
        timeout: float = 60.0,
    ) -> None:
        """Register a CAS-staged cloudpickled function by its digest.

        The remote side verifies ``path``'s sha256 against ``digest``
        BEFORE unpickling and keeps the loaded callable for invoke-by-
        digest.  Idempotent per client: a digest this channel already
        registered is a no-op.  A digest mismatch (torn or stale CAS
        artifact) raises an :class:`AgentError` tagged PERMANENT via the
        duck-typed ``fault_label`` hook — re-registering identical bytes
        can never succeed, so the resilience layer must not burn gang
        retries on it.  ``runner`` (native agent only) names the argv the
        agent forks per invocation (``[python, harness, --rpc-child]``).
        """
        if digest in self._registered:
            return
        command: dict = {"cmd": "register_fn", "digest": digest, "path": path}
        if runner:
            command["runner"] = [str(part) for part in runner]
        await self._send(command)

        def settled(c: "AgentClient"):
            if digest in c._register_errors:
                code, message = c._register_errors.pop(digest)
                failure = AgentError(
                    f"agent@{c.address}: register {digest[:12]} failed "
                    f"({code}): {message}"
                )
                if code == "digest_mismatch":
                    failure.fault_label = "rpc_digest_mismatch"  # type: ignore[attr-defined]
                    failure.fault_transient = False  # type: ignore[attr-defined]
                raise failure
            return digest in c._registered

        await self._wait(settled, timeout)

    async def invoke(
        self,
        task_id: str,
        digest: str,
        spec: dict | None = None,
        args_b64: str | None = None,
        args_path: str = "",
        args_digest: str = "",
        path: str = "",
        result_path: str = "",
        result_max_inline: int | None = None,
        timeout: float = 30.0,
    ) -> int:
        """Invoke a registered function by digest; returns the worker pid.

        Args travel inline (``args_b64``) below the executor's size
        threshold, else by CAS path + digest.  ``path`` (the function's
        CAS artifact) rides along so a restarted runtime can self-heal a
        lost registration, digest-verified.  The same size policy applies
        on the way back: given ``result_path`` + ``result_max_inline``,
        a result pickle over the threshold is staged to that remote path
        (announced by sha256 digest) instead of base64-inlined onto the
        channel in one write.  The ``started`` ack bounds this call; the
        result streams back separately (:meth:`wait_result`).
        """
        command: dict = {"cmd": "invoke", "id": task_id, "digest": digest}
        if path:
            command["path"] = path
        if spec:
            command["spec"] = dict(spec)
        if args_b64 is not None:
            command["args"] = args_b64
        elif args_path:
            command["args_path"] = args_path
            if args_digest:
                command["args_digest"] = args_digest
        if result_path and result_max_inline is not None:
            command["result_path"] = result_path
            command["result_max_inline"] = int(result_max_inline)
        submit_span = Span(
            "agent.invoke", {"address": self.address, "task_id": task_id}
        )
        submit_span.__enter__()
        try:
            await self._send(command)

            def ready(c: "AgentClient"):
                if task_id in c._errors:
                    rejection = AgentError(
                        f"agent@{c.address} rejected invoke {task_id}: "
                        f"{c._errors.pop(task_id)}"
                    )
                    rejection.rejected = True  # type: ignore[attr-defined]
                    raise rejection
                return c._started.get(task_id)

            pid = await self._wait(ready, timeout)
            self._started.pop(task_id, None)
            return pid
        except AgentError as err:
            submit_span.record_error(err)
            raise
        finally:
            submit_span.end()

    async def wait_result(
        self, task_id: str, timeout: float | None = None
    ) -> dict:
        """Block until the invocation's pushed ``result`` event."""
        event = await self._wait(lambda c: c._results.get(task_id), timeout)
        self._results.pop(task_id, None)
        return event

    # -- serving sessions ----------------------------------------------------

    async def serve_open(
        self,
        sid: str,
        digest: str,
        path: str,
        options: dict | None = None,
        spec: dict | None = None,
        runner: "list[str] | None" = None,
        timeout: float = 120.0,
    ) -> dict:
        """Open a resident serving session; returns the ``serve_opened``
        event (``slots``, worker ``pid``).

        Ships a cloudpickled model-factory by CAS digest: the worker
        verifies ``path``'s sha256 against ``digest`` BEFORE unpickling,
        calls the factory ONCE (model load + compile — hence the generous
        timeout), and serves request commands for the session's lifetime.
        A refused open raises :class:`AgentError`; permanent refusals
        (digest mismatch, a factory rejecting its model shape) carry the
        duck-typed ``fault_label`` so the resilience layer never burns
        gang retries re-opening them.  ``runner`` (native agent only)
        names the argv forked to host the session
        (``[python, harness, --serve-child]``).
        """
        command: dict = {
            "cmd": "serve_open", "id": sid, "digest": digest, "path": path,
        }
        if options:
            command["options"] = dict(options)
        if spec:
            command["spec"] = dict(spec)
        if runner:
            command["runner"] = [str(part) for part in runner]
        await self._send(command)

        def settled(c: "AgentClient"):
            if sid in c._serve_errors:
                event = c._serve_errors.pop(sid)
                failure = AgentError(
                    f"agent@{c.address}: serve_open {sid} failed "
                    f"({event.get('code')}): {event.get('message')}"
                )
                if event.get("permanent"):
                    failure.fault_label = str(  # type: ignore[attr-defined]
                        event.get("label")
                        or f"serve_{event.get('code') or 'error'}"
                    )
                    failure.fault_transient = False  # type: ignore[attr-defined]
                raise failure
            return c._serve_opened.pop(sid, None)

        return await self._wait(settled, timeout)

    async def serve_request(
        self,
        sid: str,
        rid: str,
        prompt,
        params: dict | None = None,
        deadline_s: float = 0.0,
        tenant: str = "",
    ) -> None:
        """Submit one request to an open session (fire-and-stream).

        The response streams back over the telemetry side-band as
        ``serve.token`` records routed to the session's
        :meth:`watch_serve` sink; backpressure and unknown sessions
        arrive as ``serve.reject`` records the same way.
        """
        command: dict = {
            "cmd": "serve_request", "id": sid, "rid": rid, "prompt": prompt,
        }
        if params:
            command["params"] = dict(params)
        if deadline_s:
            command["deadline_s"] = float(deadline_s)
        if tenant:
            command["tenant"] = str(tenant)
        await self._send(command)

    async def serve_close(self, sid: str, timeout: float = 30.0) -> dict:
        """Close a session; returns the ``serve_closed`` event (``served``
        request count) after the worker drains admitted lanes."""
        await self._send({"cmd": "serve_close", "id": sid})

        def settled(c: "AgentClient"):
            if sid in c._serve_errors:
                event = c._serve_errors.pop(sid)
                failure = AgentError(
                    f"agent@{c.address}: serve_close {sid} failed "
                    f"({event.get('code')}): {event.get('message')}"
                )
                if event.get("permanent"):
                    # Same duck-tag propagation as serve_open: closing a
                    # session that does not exist is deterministic — the
                    # resilience layer must not burn retries on it.
                    failure.fault_label = str(  # type: ignore[attr-defined]
                        event.get("label")
                        or f"serve_{event.get('code') or 'error'}"
                    )
                    failure.fault_transient = False  # type: ignore[attr-defined]
                raise failure
            return c._serve_closed.pop(sid, None)

        return await self._wait(settled, timeout)

    # -- resident-mode profiling ---------------------------------------------

    async def profile_start(
        self,
        profile_id: str,
        trace_dir: str,
        sid: str = "",
        timeout: float = 60.0,
    ) -> dict:
        """Start a ``jax.profiler`` trace inside the resident runtime.

        The pool server runs the trace in its own process (where RPC
        invocations and pool-mode serving sessions execute); the native
        C++ agent forwards the command into a live ``--serve-child``
        session runner (``sid`` pins which one; otherwise the agent picks
        any).  Exactly one trace runs per runtime — a second start is
        refused ``busy``.  Returns the ``profile_started`` event.
        """
        command: dict = {
            "cmd": "profile_start", "id": profile_id, "dir": trace_dir,
        }
        if sid:
            command["sid"] = sid
        await self._send(command)
        return await self._wait(
            self._profile_settled(profile_id, self._profile_started), timeout
        )

    async def profile_stop(
        self,
        profile_id: str,
        artifact_dir: str = "",
        sid: str = "",
        timeout: float = 120.0,
        discard: bool = False,
    ) -> dict:
        """Stop the active trace; returns the ``profile_stopped`` event.

        The worker packages the trace directory into one content-addressed
        ``<sha256>.profile.tgz`` under ``artifact_dir`` (the dispatcher
        points this at the CAS dir) and announces ``path``/``digest``/
        ``bytes`` — the caller fetches and digest-verifies before trusting
        the artifact.  The generous timeout covers tarring a large trace.
        ``discard=True`` (a compensating stop for an abandoned capture)
        skips packaging entirely: the worker deletes the raw trace dir.
        """
        command: dict = {"cmd": "profile_stop", "id": profile_id}
        if artifact_dir:
            command["artifact_dir"] = artifact_dir
        if sid:
            command["sid"] = sid
        if discard:
            command["discard"] = True
        await self._send(command)
        return await self._wait(
            self._profile_settled(profile_id, self._profile_stopped), timeout
        )

    async def profile_wait_stopped(
        self, profile_id: str, timeout: float = 120.0
    ) -> dict:
        """Wait out an in-flight stop's ``profile_stopped`` WITHOUT
        re-sending the command — the worker packages the trace on a
        thread, and a resend during packaging is refused ("already
        stopping"), abandoning the artifact it is about to announce."""
        return await self._wait(
            self._profile_settled(profile_id, self._profile_stopped), timeout
        )

    def _profile_settled(self, profile_id: str, table: dict):
        def settled(c: "AgentClient"):
            if profile_id in c._profile_errors:
                event = c._profile_errors.pop(profile_id)
                raise AgentError(
                    f"agent@{c.address}: profile {profile_id} failed "
                    f"({event.get('code')}): {event.get('message')}"
                )
            return table.pop(profile_id, None)

        return settled

    def watch_serve(self, sid: str, sink) -> None:
        """Route session ``sid``'s side-band records to ``sink(sid, data)``
        (instead of :attr:`on_telemetry`).  Register BEFORE the first
        request so no token can slip past."""
        self._serve_sinks[sid] = sink

    def unwatch_serve(self, sid: str) -> None:
        """Drop a closed session's sink and retained per-sid state."""
        self._serve_sinks.pop(sid, None)
        self._telemetry_seq.pop(sid, None)
        self._serve_opened.pop(sid, None)
        self._serve_errors.pop(sid, None)
        self._serve_closed.pop(sid, None)

    async def wait_dead(self) -> None:
        """Block until this channel dies, then raise :class:`AgentError`.

        The serving tier's supervisor awaits this to notice a dropped
        channel (or dead resident worker) the moment the reader does,
        triggering its reconnect instead of waiting on a stuck stream.
        """
        await self._wait(lambda c: None, None)

    def forget(self, task_id: str) -> None:
        """Drop any retained state for a finished/abandoned task.

        Called by the executor when an operation leaves its books — on
        EVERY exit path (success, kill, channel death, retry teardown):
        a straggler's unconsumed exit event, an unclaimed RPC result, the
        telemetry seq high-water mark, and any stored rejection must not
        accumulate for the channel's lifetime.
        """
        self._started.pop(task_id, None)
        self._exits.pop(task_id, None)
        self._errors.pop(task_id, None)
        self._results.pop(task_id, None)
        if task_id not in self._serve_sinks:
            # Serving sessions outlive electron operations on the same
            # channel: an electron's forget() must never reset a live
            # session's seq high-water mark (token dedup depends on it).
            self._telemetry_seq.pop(task_id, None)

    async def kill(self, task_id: str, sig: int = 15) -> None:
        await self._send({"cmd": "kill", "id": task_id, "sig": sig})

    async def _send(self, command: dict) -> None:
        if self._dead is not None:
            raise AgentError(f"agent@{self.address} channel died: {self._dead}")
        _AGENT_RPCS.labels(cmd=str(command.get("cmd", "?"))).inc()
        try:
            await self._process.write_line(json.dumps(command))
        except TransportError as err:
            raise AgentError(f"agent@{self.address}: send failed: {err}") from err
