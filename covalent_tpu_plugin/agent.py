"""Client for the resident worker agent (``native/agent.cc``).

The reference's submit/status protocol costs one SSH round-trip per probe
(``covalent_ssh_plugin/ssh.py:383`` submit, ``ssh.py:402-406`` status,
``ssh.py:408-432`` poll loop).  The agent collapses all of that into one
persistent channel per worker: the executor writes a ``run`` command and the
agent *pushes* ``started``/``exit`` events the moment they happen — no poll
traffic, and task-completion latency bounded by the channel RTT instead of
the poll interval.

Deployment is self-contained: the single C++ source ships inside this
package, is uploaded to the worker's cache dir, and is compiled there by the
system compiler (cached by content hash, so compilation happens once per
worker per agent version).  Workers without a C++ toolchain simply raise
:class:`AgentError` and the executor falls back to the stateless
``nohup`` + poll protocol — the agent is an accelerator, never a
requirement.  Agent-launched tasks run in their own sessions, so even if the
agent or its channel dies mid-task, the fallback poller can resume
supervision using the PID from the ``started`` event.
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import json
import os
import shlex
import uuid
from functools import lru_cache
from pathlib import Path
from typing import Any

from .obs import events as obs_events
from .obs.metrics import REGISTRY
from .obs.trace import Span
from .transport import frames
from .transport.base import Transport, TransportError
from .utils.log import app_log

_AGENT_RPCS = REGISTRY.counter(
    "covalent_tpu_agent_rpcs_total",
    "Commands written to resident agent channels",
    ("cmd",),
)
_AGENT_EVENTS = REGISTRY.counter(
    "covalent_tpu_agent_events_total",
    "Events pushed by resident agent channels",
    ("event",),
)
AGENT_RESTARTS_TOTAL = REGISTRY.counter(
    "covalent_tpu_agent_restarts_total",
    "Cached agent channels discarded and restarted after a failed ping",
)
AGENT_FRAMES_TOTAL = REGISTRY.counter(
    "covalent_tpu_agent_frames_total",
    "Protocol messages on agent channels by verb and encoding "
    "(jsonl lines vs negotiated binary frames)",
    ("verb", "encoding"),
)
AGENT_WIRE_BYTES_TOTAL = REGISTRY.counter(
    "covalent_tpu_agent_wire_bytes_total",
    "Bytes on agent channels by direction (up/down) and encoding",
    ("direction", "encoding"),
)


def frames_env_enabled() -> bool:
    """Process-wide kill switch: COVALENT_TPU_AGENT_FRAMES=0 pins JSONL."""
    return os.environ.get(
        "COVALENT_TPU_AGENT_FRAMES", ""
    ).strip().lower() not in ("0", "off", "false", "no")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


#: Same-event-loop-turn invoke batching by default (window 0: zero added
#: latency — only invokes already queued in the current turn coalesce);
#: a positive window trades a bounded wait for bigger batches.
_BATCH_WINDOW_S = max(0.0, _env_float(
    "COVALENT_TPU_RPC_BATCH_WINDOW_MS", 0.0
) / 1000.0)
_BATCH_MAX_OPS = max(1, int(_env_float("COVALENT_TPU_RPC_BATCH_MAX", 16)))

AGENT_SOURCE = Path(__file__).parent / "native" / "agent.cc"

#: Remote filename of the staged harness module.  Shared by the per-task
#: stager (StagedTask.remote_harness_file) and the pool server so the
#: resident interpreter always serves the same file task specs point at.
HARNESS_BASENAME = "covalent_tpu_harness.py"


class AgentError(TransportError):
    """Agent unavailable or its channel failed; callers fall back to polling."""


@lru_cache(maxsize=1)
def agent_source_hash() -> str:
    """Content hash naming the remote binary, so stale agents never run."""
    return hashlib.sha256(AGENT_SOURCE.read_bytes()).hexdigest()[:12]


async def ensure_agent_binary(conn: Transport, remote_cache: str) -> str:
    """Upload + compile the agent on the worker (idempotent, hash-cached).

    One round-trip when the binary already exists; upload + one compile
    round-trip the first time.  Raises :class:`AgentError` when the worker
    has no C++ compiler.
    """
    binary = f"{remote_cache}/agent_{agent_source_hash()}"
    q_binary = shlex.quote(binary)
    # mkdir rides the probe: this may run concurrently with (or before) the
    # executor preflight that normally creates the cache dir.
    probe = await conn.run(
        f"mkdir -p {shlex.quote(remote_cache)}; "
        f"test -x {q_binary} && echo HAVE || echo MISSING"
    )
    if "HAVE" in probe.stdout:
        return binary

    source = f"{binary}.cc"
    await conn.put(str(AGENT_SOURCE), source)
    # Unique tmp name + atomic mv so concurrent electrons can race safely.
    tmp = shlex.quote(f"{binary}.tmp.{uuid.uuid4().hex[:8]}")
    build = await conn.run(
        "CXX=$(command -v g++ || command -v c++ || command -v clang++) "
        "&& [ -n \"$CXX\" ] "
        f"&& $CXX -O2 -std=c++17 -o {tmp} {shlex.quote(source)} "
        f"&& mv {tmp} {q_binary}",
        timeout=120.0,
    )
    if build.exit_status != 0:
        raise AgentError(
            f"no agent on {conn.address}: compile failed or no C++ compiler "
            f"({build.stderr.strip()[:200]})"
        )
    return binary


async def start_pool_server(
    conn: Transport,
    remote_cache: str,
    python_path: str,
    conda_env: str = "",
    preload: str = "cloudpickle",
    timeout: float = 90.0,
    frames_enabled: bool | None = None,
    frames_codec: str = "",
) -> "AgentClient":
    """Start the harness forkserver (``harness.py --serve``) on a worker.

    The resident interpreter preloads ``preload`` modules once; each task
    then costs a fork instead of interpreter startup + imports.  The
    generous timeout covers a cold jax import on the worker.  Speaks the
    same protocol as the native agent, so the returned client is a drop-in
    (``mode == "pool"``).
    """
    from . import harness as harness_module

    remote_harness = f"{remote_cache}/{HARNESS_BASENAME}"
    try:
        await conn.run(f"mkdir -p {shlex.quote(remote_cache)}")
        await conn.put(harness_module.__file__, remote_harness)
    except TransportError as err:
        raise AgentError(f"cannot stage pool server on {conn.address}: {err}") from err

    command = (
        f"env COVALENT_TPU_POOL_PRELOAD={shlex.quote(preload)} "
        f"{python_path} {shlex.quote(remote_harness)} --serve"
    )
    if conda_env:
        command = (
            f'eval "$(conda shell.bash hook)" && conda activate '
            f"{shlex.quote(conda_env)} && {command}"
        )
    try:
        process = await conn.start_process(command, describe=f"pool@{conn.address}")
    except TransportError as err:
        raise AgentError(f"cannot start pool server on {conn.address}: {err}") from err
    client = AgentClient(process, conn.address)
    client.mode = "pool"
    try:
        await client.ping(timeout)
        await client.negotiate_frames(
            enabled=frames_enabled, codec=frames_codec
        )
    except AgentError:
        await client.close()
        raise
    return client


def orphan_rendezvous_path(remote_cache: str) -> str:
    """Where an orphaned pool server publishes its adoption coordinates."""
    return f"{remote_cache}/pool_orphan.json"


async def read_orphan_rendezvous(
    conn: Transport, remote_cache: str
) -> dict | None:
    """The worker's ``pool_orphan.json``, or None when no orphan waits."""
    import tempfile

    path = orphan_rendezvous_path(remote_cache)
    with tempfile.TemporaryDirectory(prefix="covalent-orphan-") as tmp:
        local = f"{tmp}/pool_orphan.json"
        try:
            await conn.get(path, local)
            with open(local, "r", encoding="utf-8") as fh:
                meta = json.load(fh)
        except (TransportError, OSError, ValueError):
            return None
    if not isinstance(meta, dict) or not meta.get("sock"):
        return None
    return meta


async def attach_pool_server(
    conn: Transport,
    remote_cache: str,
    python_path: str,
    sock_path: str,
    epoch: int,
    conda_env: str = "",
    timeout: float = 30.0,
    frames_enabled: bool | None = None,
    frames_codec: str = "",
) -> "AgentClient":
    """Adopt an orphaned pool server instead of starting a fresh one.

    Spawns the ``--attach`` stdio relay through the normal transport (so
    adoption works identically over SSH and local), sends the epoch-fenced
    adopt line, and waits for the orphan's re-attach ready banner.  The
    orphan refuses a stale epoch with an error event — surfaced here as an
    AgentError so the caller falls back to a fresh server.
    """
    remote_harness = f"{remote_cache}/{HARNESS_BASENAME}"
    command = f"{python_path} {shlex.quote(remote_harness)} --attach " \
              f"{shlex.quote(sock_path)}"
    if conda_env:
        command = (
            f'eval "$(conda shell.bash hook)" && conda activate '
            f"{shlex.quote(conda_env)} && {command}"
        )
    try:
        process = await conn.start_process(
            command, describe=f"adopt@{conn.address}"
        )
    except TransportError as err:
        raise AgentError(
            f"cannot start attach relay on {conn.address}: {err}"
        ) from err
    client = AgentClient(process, conn.address)
    client.mode = "pool"
    try:
        await client._send({"cmd": "adopt", "epoch": int(epoch)})

        def adopted(c: "AgentClient"):
            if c._banner.get("reattach"):
                return c._banner
            if c._error_codes.get("") == "stale_epoch":
                message = c._errors.pop("", "stale epoch")
                c._error_codes.pop("", None)
                raise AgentError(f"agent@{c.address}: adopt refused: "
                                 f"{message}")
            if c._error_codes.get("") == "attach_failed":
                message = c._errors.pop("", "attach failed")
                c._error_codes.pop("", None)
                raise AgentError(f"agent@{c.address}: {message}")
            return None

        await client._wait(adopted, timeout)
        await client.ping(timeout)
        await client.negotiate_frames(
            enabled=frames_enabled, codec=frames_codec
        )
    except AgentError:
        await client.close()
        raise
    return client


class AgentClient:
    """One agent channel to one worker, demultiplexing pushed events.

    A background reader drains the channel and files events by task id;
    any number of concurrent tasks can await their own ``started``/``exit``
    notifications.
    """

    #: "native" (C++ agent, argv exec) or "pool" (harness forkserver, spec).
    mode: str = "native"

    def __init__(self, process, address: str):
        self._process = process
        self.address = address
        self._started: dict[str, int] = {}
        self._exits: dict[str, tuple[int, int]] = {}
        self._errors: dict[str, str] = {}
        #: function digests this channel's resident runtime has registered
        #: (RPC dispatch); dies with the client, exactly like the remote
        #: registry dies with the agent process.
        self._registered: set[str] = set()
        #: digest -> (code, message) for a failed registration.
        self._register_errors: dict[str, tuple[str, str]] = {}
        #: task id -> pushed ``result`` event (RPC invocations).
        self._results: dict[str, dict] = {}
        self._pongs = 0
        self._dead: BaseException | None = None
        self._cond = asyncio.Condition()
        #: sink for backhauled telemetry lines: called ``(task_id, data)``
        #: for every FRESH event the agent's watch side-band pushes.  Set
        #: by the executor; exceptions are contained (observer contract).
        self.on_telemetry = None
        #: task id -> highest worker-event ``seq`` seen; a re-watch after a
        #: reconnect re-tails from offset 0, so duplicates are expected and
        #: dropped here.
        self._telemetry_seq: dict[str, int] = {}
        #: serving sessions: sid -> pushed serve_opened / serve_error /
        #: serve_closed events, and sid -> per-session telemetry sink
        #: (serve.token / serve.reject / serve.stats data routed here
        #: instead of :attr:`on_telemetry`).
        self._serve_opened: dict[str, dict] = {}
        self._serve_errors: dict[str, dict] = {}
        self._serve_closed: dict[str, dict] = {}
        self._serve_sinks: dict[str, Any] = {}
        #: "sid/rid" -> pushed ``serve_kv`` event (disaggregated prefill
        #: answers: KV bundle bytes as a raw frame body, or an error).
        self._serve_kv: dict[str, dict] = {}
        #: "sid/rid" -> pushed ``serve_resumed`` ack (recovery path).
        self._serve_resumed: dict[str, dict] = {}
        #: "kind:sid/adapter" -> pushed ``serve_attached``/``serve_detached``
        #: ack (the multi-adapter registry path; kind keeps an attach and a
        #: detach of the same adapter from settling each other's waiter).
        self._serve_attached: dict[str, dict] = {}
        #: "serve"/"task" -> latest pushed inventory answer (recovery path;
        #: one outstanding request per kind — the slot is cleared on send).
        self._inventories: dict[str, dict] = {}
        #: last ``epoch_ok`` ack from declare_epoch (worker-side fence).
        self._epoch_ack: dict | None = None
        #: resident-mode profiling: profile id -> pushed profile_started /
        #: profile_stopped / profile_error events.
        self._profile_started: dict[str, dict] = {}
        self._profile_stopped: dict[str, dict] = {}
        self._profile_errors: dict[str, dict] = {}
        #: binary frame negotiation: the runtime's ready banner (capability
        #: advertisement), the pushed `frames` ack, and the active state.
        self._banner: dict = {}
        self._frames_ack: dict | None = None
        self.frames_active = False
        self._frame_codec = ""
        #: task id -> structured code from an `error` event (bad_frame is
        #: torn content — the rejection must classify PERMANENT, not burn
        #: gang retries re-sending identical corrupt bytes).
        self._error_codes: dict[str, str] = {}
        #: invoke micro-batching: digest -> [(command, args_bytes)] queued
        #: this window; flushed as ONE multi_invoke frame per digest.
        self._pending_invokes: dict[str, list] = {}
        self._flush_scheduled = False
        self._flush_now = False
        #: live flusher tasks: the loop keeps only weak refs to tasks, so
        #: an unreferenced flusher could be GC'd mid-flight, stranding its
        #: waiters on their started timeouts.
        self._flush_tasks: set = set()
        self._reader = asyncio.create_task(self._read_loop())

    # -- lifecycle -----------------------------------------------------------

    @classmethod
    async def start(
        cls,
        conn: Transport,
        binary: str,
        timeout: float = 15.0,
        frames_enabled: bool | None = None,
        frames_codec: str = "",
    ) -> "AgentClient":
        try:
            process = await conn.start_process(
                shlex.quote(binary), describe=f"agent@{conn.address}"
            )
        except TransportError as err:
            raise AgentError(f"cannot start agent on {conn.address}: {err}") from err
        client = cls(process, conn.address)
        try:
            # A ping round-trip both consumes the ready banner and proves the
            # channel is live before any task is entrusted to it.
            await client.ping(timeout)
            await client.negotiate_frames(
                enabled=frames_enabled, codec=frames_codec
            )
        except AgentError:
            await client.close()
            raise
        return client

    @property
    def alive(self) -> bool:
        return self._dead is None and not self._reader.done()

    @property
    def banner_sessions(self) -> list[str]:
        """Session ids a re-adopted pool server announced in its banner
        (empty for a fresh start — only ``reattach`` banners carry them)."""
        return [str(s) for s in (self._banner.get("sessions") or [])]

    async def close(self) -> None:
        try:
            if self._dead is None:
                await self._process.write_line('{"cmd":"shutdown"}')
        except TransportError:
            pass
        self._reader.cancel()
        try:
            await self._reader
        except (asyncio.CancelledError, Exception):
            pass
        await self._process.close()

    # -- event plumbing ------------------------------------------------------

    async def _read_loop(self) -> None:
        try:
            while True:
                message = await self._process.read_event()
                if message[0] == "frame":
                    _kind, verb, flags, header, body = message
                    AGENT_FRAMES_TOTAL.labels(
                        verb=frames.VERB_NAMES.get(verb, str(verb)),
                        encoding="binary",
                    ).inc()
                    AGENT_WIRE_BYTES_TOTAL.labels(
                        direction="down", encoding="binary"
                    ).inc(frames.HEADER_LEN + len(header) + len(body))
                    try:
                        event = frames.decode_payload(flags, header, body)
                    except frames.FrameIntegrityError as err:
                        # The frame arrived length-intact, so this is torn
                        # CONTENT, not a channel fault: deliver a marked
                        # event so the waiter fails PERMANENT instead of
                        # the whole channel dying transient.
                        try:
                            event = json.loads(header.decode("utf-8"))
                        except ValueError:
                            raise TransportError(
                                f"agent@{self.address}: undecodable torn "
                                f"frame: {err}"
                            ) from err
                        event.pop("_body", None)
                        event["torn"] = repr(err)
                    # FrameError (bad header JSON) falls through to the
                    # generic handler below: the stream itself cannot be
                    # trusted past it, so the reader dies and waiters see
                    # a channel death.
                else:
                    line = message[1]
                    try:
                        event = json.loads(line)
                    except ValueError:
                        continue  # stray non-protocol output; ignore
                    kind0 = str(event.get("event")) if isinstance(
                        event, dict
                    ) else "?"
                    AGENT_FRAMES_TOTAL.labels(
                        verb=kind0, encoding="jsonl"
                    ).inc()
                    AGENT_WIRE_BYTES_TOTAL.labels(
                        direction="down", encoding="jsonl"
                    ).inc(len(line) + 1)
                if not isinstance(event, dict):
                    continue
                async with self._cond:
                    kind = event.get("event")
                    task_id = event.get("id", "")
                    _AGENT_EVENTS.labels(event=str(kind)).inc()
                    if kind == "telemetry":
                        self._handle_telemetry(task_id, event.get("data"))
                        continue  # side-band: no waiter state to notify
                    if kind == "telemetry_batch":
                        if event.get("torn"):
                            # Torn batch body: the records (and their
                            # rids) are unrecoverable — say so loudly
                            # instead of silently dropping what may be a
                            # stream's done marker.
                            app_log.warning(
                                "agent@%s: dropped torn telemetry batch "
                                "for %s: %s",
                                self.address, task_id, event["torn"],
                            )
                            obs_events.emit(
                                "agent.torn_telemetry_batch",
                                address=self.address,
                                task_id=str(task_id),
                                error=str(event["torn"]),
                            )
                            continue
                        # Coalesced side-band frame: unpack and feed each
                        # record through the exact per-record path — seq
                        # dedup, serve sinks, and the exactly-once idx
                        # splice downstream are untouched by batching.
                        records = event.get("records") or b"[]"
                        try:
                            parsed = json.loads(
                                records.decode("utf-8")
                                if isinstance(records, (bytes, bytearray))
                                else records
                            )
                        except (ValueError, UnicodeDecodeError):
                            parsed = []
                        for record in parsed if isinstance(
                            parsed, list
                        ) else []:
                            self._handle_telemetry(task_id, record)
                        continue
                    if kind == "started":
                        self._started[task_id] = int(event["pid"])
                    elif kind == "multi_started":
                        pid = int(event.get("pid") or 0)
                        for tid in event.get("ids") or []:
                            self._started[str(tid)] = pid
                    elif kind == "ready":
                        self._banner = event
                    elif kind == "frames":
                        self._frames_ack = event
                    elif kind == "serve_opened":
                        self._serve_opened[task_id] = event
                    elif kind == "serve_error":
                        self._serve_errors[task_id] = event
                    elif kind == "serve_closed":
                        self._serve_closed[task_id] = event
                    elif kind == "serve_kv":
                        self._serve_kv[
                            f"{task_id}/{event.get('rid') or ''}"
                        ] = event
                        # Bound abandoned answers: a prefill whose waiter
                        # timed out leaves its (late) event unclaimed —
                        # drop oldest so a pathological session cannot
                        # grow this for the channel lifetime.
                        while len(self._serve_kv) > 256:
                            self._serve_kv.pop(
                                next(iter(self._serve_kv))
                            )
                    elif kind == "serve_resumed":
                        self._serve_resumed[
                            f"{task_id}/{event.get('rid') or ''}"
                        ] = event
                        while len(self._serve_resumed) > 1024:
                            self._serve_resumed.pop(
                                next(iter(self._serve_resumed))
                            )
                    elif kind in ("serve_attached", "serve_detached"):
                        self._serve_attached[
                            f"{kind}:{task_id}/"
                            f"{event.get('adapter') or ''}"
                        ] = event
                        while len(self._serve_attached) > 256:
                            self._serve_attached.pop(
                                next(iter(self._serve_attached))
                            )
                    elif kind == "serve_inventory":
                        self._inventories["serve"] = event
                    elif kind == "task_inventory":
                        self._inventories["task"] = event
                    elif kind == "epoch_ok":
                        self._epoch_ack = event
                    elif kind == "profile_started":
                        self._profile_started[task_id] = event
                    elif kind == "profile_stopped":
                        self._profile_stopped[task_id] = event
                    elif kind == "profile_error":
                        self._profile_errors[task_id] = event
                    elif kind == "exit":
                        self._exits[task_id] = (
                            int(event.get("code", -1)),
                            int(event.get("signal", 0)),
                        )
                    elif kind == "result":
                        self._results[task_id] = event
                    elif kind == "registered":
                        self._registered.add(str(event.get("digest") or ""))
                    elif kind == "register_error":
                        self._register_errors[
                            str(event.get("digest") or "")
                        ] = (
                            str(event.get("code") or "error"),
                            str(event.get("message") or "?"),
                        )
                    elif kind == "pong":
                        self._pongs += 1
                    elif kind == "error":
                        # id-less errors are log-only — EXCEPT the epoch
                        # fence refusal and a failed attach relay, which
                        # declare_epoch / attach_pool_server wait on.
                        if task_id or event.get("code") in (
                            "stale_epoch", "attach_failed"
                        ):
                            self._errors[task_id] = str(event.get("message", "?"))
                            if event.get("code"):
                                self._error_codes[task_id] = str(event["code"])
                        app_log.warning(
                            "agent@%s error: %s", self.address, event.get("message")
                        )
                    self._cond.notify_all()
        except asyncio.CancelledError:
            raise
        except BaseException as err:  # noqa: BLE001 - ANY reader death must
            # wake waiters: an unnotified exception here would leave
            # wait_exit() blocked forever (e.g. asyncssh.ConnectionLost is
            # neither TransportError nor OSError).
            obs_events.emit(
                "agent.channel_died", address=self.address, error=repr(err)
            )
            async with self._cond:
                self._dead = err
                self._cond.notify_all()

    def _handle_telemetry(self, task_id: str, data) -> None:
        """Dedup one backhauled event by ``seq`` and hand it to the sink.

        Worker events carry a per-process monotonically increasing ``seq``
        (harness ``_emit_worker_event``); a re-watch after channel loss
        replays the whole file, so everything at-or-below the high-water
        mark is a duplicate.  Events without a seq pass through — better a
        duplicate observation than a dropped one.
        """
        if not isinstance(data, dict):
            return
        seq = data.get("seq")
        if isinstance(seq, int):
            if seq <= self._telemetry_seq.get(task_id, 0):
                return
            self._telemetry_seq[task_id] = seq
        # Serving sessions own their side-band traffic: every record for a
        # watched sid (tokens, rejects, stats) routes to that session's
        # sink instead of the executor's generic backhaul handler.
        callback = self._serve_sinks.get(task_id) or self.on_telemetry
        if callback is None:
            return
        try:
            callback(task_id, data)
        except Exception as err:  # noqa: BLE001 - observers must not break
            app_log.debug("telemetry callback failed: %s", err)

    async def watch(self, task_id: str, path: str) -> None:
        """Start the telemetry side-band for one task's worker-local file.

        The agent tails ``path`` from offset 0 (flushing any backlog
        buffered while no channel was attached) and pushes each JSONL line
        as a ``telemetry`` event routed to :attr:`on_telemetry`.
        """
        await self._send({"cmd": "watch", "id": task_id, "path": path})

    async def unwatch(self, task_id: str) -> None:
        await self._send({"cmd": "unwatch", "id": task_id})

    async def _wait(self, predicate, timeout: float | None):
        """Await ``predicate(self)`` truthy, raising AgentError on channel death."""

        async def waiter():
            async with self._cond:
                while True:
                    if self._dead is not None:
                        raise AgentError(
                            f"agent@{self.address} channel died: {self._dead}"
                        )
                    value = predicate(self)
                    if value:
                        return value
                    await self._cond.wait()

        try:
            return await asyncio.wait_for(waiter(), timeout)
        except asyncio.TimeoutError:
            raise AgentError(f"agent@{self.address}: no event within {timeout}s")

    # -- commands ------------------------------------------------------------

    async def ping(self, timeout: float = 15.0) -> None:
        before = self._pongs
        await self._send({"cmd": "ping"})
        await self._wait(lambda c: c._pongs > before, timeout)

    async def negotiate_frames(
        self,
        timeout: float = 15.0,
        enabled: bool | None = None,
        codec: str = "",
    ) -> bool:
        """Switch the channel to binary frames when both ends are capable.

        Rides the ready-banner handshake (the same one-round-trip shape as
        the ``COVALENT_TPU_CODECS=`` pre-flight probe): a frame-capable
        runtime advertised ``frames`` in its banner — consumed before the
        ping ack, so this never races — and answers the ``frames`` command
        with an ack carrying the accepted body codec.  A silent banner (old
        or JSON-only runtime), a ``version: 0`` refusal (remote kill
        switch), or ``enabled=False`` (local kill switch /
        COVALENT_TPU_AGENT_FRAMES=0) all leave the channel on JSONL — the
        fallback is byte-equal, just slower.

        ``codec`` asks for per-frame BODY compression (zlib, the one codec
        every stdlib-only worker has).  Like the staging codec's download
        leg, it engages only when the operator pinned a codec: deflating a
        mid-size payload costs more CPU time than the base64+JSON parse it
        replaces, so it pays only where the wire (not the CPU) is the
        bottleneck — raw frames already drop the ~33% base64 inflation and
        both JSON legs for free.
        """
        if enabled is None:
            enabled = frames_env_enabled()
        if not enabled or not self._banner.get("frames"):
            return False
        codecs = self._banner.get("codecs") or []
        codec = "zlib" if codec == "zlib" and "zlib" in codecs else ""
        await self._send({
            "cmd": "frames", "version": frames.VERSION, "codec": codec,
        })
        ack = await self._wait(lambda c: c._frames_ack, timeout)
        if int(ack.get("version") or 0) >= 1:
            self.frames_active = True
            self._frame_codec = str(ack.get("codec") or "")
            obs_events.emit(
                "agent.frames_negotiated", address=self.address,
                codec=self._frame_codec,
            )
        return self.frames_active

    def _pop_rejection(self, task_id: str, what: str) -> AgentError | None:
        """Stored error event -> a rejection exception (or None).

        A definitive rejection means the task never started, so relaunch
        through the fallback path is safe.  A ``bad_frame`` code is torn
        content — identical bytes can never be re-sent successfully — so
        the rejection carries the duck-typed PERMANENT tag.
        """
        if task_id not in self._errors:
            return None
        message = self._errors.pop(task_id)
        code = self._error_codes.pop(task_id, "")
        rejection = AgentError(
            f"agent@{self.address} rejected {what} {task_id}: {message}"
        )
        rejection.rejected = True  # type: ignore[attr-defined]
        if code == "bad_frame":
            rejection.fault_label = "agent_bad_frame"  # type: ignore[attr-defined]
            rejection.fault_transient = False  # type: ignore[attr-defined]
        return rejection

    async def run_task(
        self,
        task_id: str,
        argv: list[str] | None = None,
        cwd: str = "",
        env: dict[str, str] | None = None,
        log: str = "",
        timeout: float = 30.0,
        spec: str = "",
    ) -> int:
        """Launch a task; returns the remote PID from the ``started`` event.

        ``argv`` targets the native C++ agent (it execs the command);
        ``spec`` targets the harness pool server (it forks and runs the spec
        in the pre-warmed interpreter).  Exactly one must be given.
        """
        command: dict = {"cmd": "run", "id": task_id}
        if spec:
            command["spec"] = spec
        else:
            command["argv"] = list(argv or [])
        if cwd:
            command["cwd"] = cwd
        if env:
            command["env"] = {str(k): str(v) for k, v in env.items()}
        if log:
            command["log"] = log
        sent = False
        # The span times command-write -> `started` push: the agent-path
        # analog of submit_task's round-trip, and the number that proves
        # (or disproves) the resident runtime's launch-latency win.
        submit_span = Span(
            "agent.submit", {"address": self.address, "task_id": task_id}
        )
        submit_span.__enter__()
        try:
            await self._send(command)
            sent = True

            def ready(c: "AgentClient"):
                rejection = c._pop_rejection(task_id, "run")
                if rejection is not None:
                    # A definitive rejection means the task never forked:
                    # relaunching through the fallback path is safe.
                    raise rejection
                return c._started.get(task_id)

            # Pop on success: a resident client serves many electrons;
            # per-task entries must not accumulate for the channel's lifetime.
            pid = await self._wait(ready, timeout)
            self._started.pop(task_id, None)
            return pid
        except AgentError as err:
            # Once the run command left for the worker, the harness may
            # already be alive there even though we never saw `started` —
            # the caller must NOT relaunch (double harness), only abort.
            # Exception: an explicit error event proves it never started.
            err.maybe_started = sent and not getattr(  # type: ignore[attr-defined]
                err, "rejected", False
            )
            submit_span.record_error(err)
            raise
        finally:
            submit_span.end()

    async def wait_exit(
        self, task_id: str, timeout: float | None = None
    ) -> tuple[int, int]:
        """Block until the pushed exit event: ``(exit_code, term_signal)``."""
        event = await self._wait(lambda c: c._exits.get(task_id), timeout)
        self._exits.pop(task_id, None)
        return event

    # -- RPC execute-by-digest ----------------------------------------------

    @property
    def registered_digests(self) -> frozenset:
        """Function digests this channel's resident runtime holds."""
        return frozenset(self._registered)

    async def register_fn(
        self,
        digest: str,
        path: str,
        runner: list[str] | None = None,
        timeout: float = 60.0,
    ) -> None:
        """Register a CAS-staged cloudpickled function by its digest.

        The remote side verifies ``path``'s sha256 against ``digest``
        BEFORE unpickling and keeps the loaded callable for invoke-by-
        digest.  Idempotent per client: a digest this channel already
        registered is a no-op.  A digest mismatch (torn or stale CAS
        artifact) raises an :class:`AgentError` tagged PERMANENT via the
        duck-typed ``fault_label`` hook — re-registering identical bytes
        can never succeed, so the resilience layer must not burn gang
        retries on it.  ``runner`` (native agent only) names the argv the
        agent forks per invocation (``[python, harness, --rpc-child]``).
        """
        if digest in self._registered:
            return
        command: dict = {"cmd": "register_fn", "digest": digest, "path": path}
        if runner:
            command["runner"] = [str(part) for part in runner]
        await self._send(command)

        def settled(c: "AgentClient"):
            if digest in c._register_errors:
                code, message = c._register_errors.pop(digest)
                failure = AgentError(
                    f"agent@{c.address}: register {digest[:12]} failed "
                    f"({code}): {message}"
                )
                if code == "digest_mismatch":
                    failure.fault_label = "rpc_digest_mismatch"  # type: ignore[attr-defined]
                    failure.fault_transient = False  # type: ignore[attr-defined]
                raise failure
            return digest in c._registered

        await self._wait(settled, timeout)

    async def invoke(
        self,
        task_id: str,
        digest: str,
        spec: dict | None = None,
        args_b64: str | None = None,
        args_bytes: bytes | None = None,
        args_path: str = "",
        args_digest: str = "",
        path: str = "",
        result_path: str = "",
        result_max_inline: int | None = None,
        timeout: float = 30.0,
    ) -> int:
        """Invoke a registered function by digest; returns the worker pid.

        Args travel inline below the executor's size threshold — as raw
        bytes in a binary frame when the channel negotiated frames
        (``args_bytes``), else base64-in-JSON (``args_b64``, derived from
        ``args_bytes`` automatically) — or by CAS path + digest when
        oversized.  On a frame-negotiated pool channel, inline invokes
        additionally micro-batch: every invoke enqueued in the same event-
        loop turn (window configurable via COVALENT_TPU_RPC_BATCH_WINDOW_MS)
        for the same digest ships as ONE ``multi_invoke`` frame, acked by
        one ``multi_started``, with results fanning back out by op id —
        the shape the fleet scheduler's digest-affinity placement produces.
        ``path`` (the function's CAS artifact) rides along so a restarted
        runtime can self-heal a lost registration, digest-verified.  The
        same size policy applies on the way back: given ``result_path`` +
        ``result_max_inline``, a result pickle over the threshold is
        staged to that remote path (announced by sha256 digest) instead of
        inlined onto the channel in one write.  The ``started`` ack bounds
        this call; the result streams back separately
        (:meth:`wait_result`).
        """
        command: dict = {"cmd": "invoke", "id": task_id, "digest": digest}
        if path:
            command["path"] = path
        if spec:
            command["spec"] = dict(spec)
        framed = (
            self.frames_active and args_bytes is not None and not args_path
        )
        if not framed:
            if args_b64 is None and args_bytes is not None:
                args_b64 = base64.b64encode(args_bytes).decode("ascii")
            if args_b64 is not None:
                command["args"] = args_b64
            elif args_path:
                command["args_path"] = args_path
                if args_digest:
                    command["args_digest"] = args_digest
        if result_path and result_max_inline is not None:
            command["result_path"] = result_path
            command["result_max_inline"] = int(result_max_inline)
        submit_span = Span(
            "agent.invoke", {"address": self.address, "task_id": task_id}
        )
        submit_span.__enter__()
        try:
            if framed and self.mode == "pool":
                self._enqueue_invoke(digest, command, args_bytes or b"")
            elif framed:
                # Native runtime: frames yes, batching no (it forks one
                # runner per invocation — there is nothing to fan back).
                header = dict(command)
                header["_body"] = "args_bytes"
                await self._send_frame(
                    frames.VERB_INVOKE, header, args_bytes or b""
                )
            else:
                await self._send(command)

            def ready(c: "AgentClient"):
                rejection = c._pop_rejection(task_id, "invoke")
                if rejection is not None:
                    raise rejection
                return c._started.get(task_id)

            pid = await self._wait(ready, timeout)
            self._started.pop(task_id, None)
            return pid
        except AgentError as err:
            submit_span.record_error(err)
            raise
        finally:
            submit_span.end()

    # -- invoke micro-batching -----------------------------------------------

    def _enqueue_invoke(
        self, digest: str, command: dict, body: bytes
    ) -> None:
        """Queue one framed invoke; the flusher coalesces per digest."""
        self._pending_invokes.setdefault(digest, []).append((command, body))
        total = sum(len(v) for v in self._pending_invokes.values())
        if not self._flush_scheduled:
            self._flush_scheduled = True
            self._spawn_flush(immediate=False)
        elif total >= _BATCH_MAX_OPS and not self._flush_now:
            # A full batch flushes NOW — skipping any configured window —
            # instead of waiting it out; the windowed flusher will find
            # an empty queue.  One immediate flusher at a time: further
            # over-max enqueues ride the one already scheduled.
            self._flush_now = True
            self._spawn_flush(immediate=True)

    def _spawn_flush(self, immediate: bool) -> None:
        task = asyncio.ensure_future(self._flush_invokes(immediate))
        self._flush_tasks.add(task)
        task.add_done_callback(self._flush_tasks.discard)

    async def _flush_invokes(self, immediate: bool = False) -> None:
        """Ship every queued invoke: one frame per digest group.

        With the default zero window only invokes enqueued in the same
        event-loop turn coalesce — a lone invoke pays no added latency.
        A send failure files a rejection for every op in the group so the
        waiters fail fast instead of sitting out their timeouts.
        """
        if not immediate and _BATCH_WINDOW_S > 0:
            await asyncio.sleep(_BATCH_WINDOW_S)
        else:
            await asyncio.sleep(0)
        pending, self._pending_invokes = self._pending_invokes, {}
        self._flush_scheduled = False
        self._flush_now = False
        for digest, entries in pending.items():
            try:
                await self._send_invoke_group(digest, entries)
            except (AgentError, TransportError, ValueError) as err:
                async with self._cond:
                    for command, _body in entries:
                        tid = str(command.get("id") or "")
                        self._errors[tid] = (
                            f"batched invoke send failed: {err}"
                        )
                    self._cond.notify_all()

    async def _send_invoke_group(self, digest: str, entries: list) -> None:
        if len(entries) == 1:
            command, body = entries[0]
            header = dict(command)
            header["_body"] = "args_bytes"
            await self._send_frame(frames.VERB_INVOKE, header, body)
            return
        ops, bodies = [], []
        fn_path = ""
        for command, body in entries:
            fn_path = fn_path or str(command.get("path") or "")
            ops.append({
                k: v for k, v in command.items()
                if k not in ("cmd", "digest", "path")
            })
            bodies.append(body)
        header: dict = {
            "cmd": "multi_invoke", "digest": digest, "ops": ops,
            "args_lens": [len(b) for b in bodies], "_body": "args_bytes",
        }
        if fn_path:
            header["path"] = fn_path
        await self._send_frame(
            frames.VERB_MULTI_INVOKE, header, b"".join(bodies)
        )

    async def wait_result(
        self, task_id: str, timeout: float | None = None
    ) -> dict:
        """Block until the invocation's pushed ``result`` event."""
        event = await self._wait(lambda c: c._results.get(task_id), timeout)
        self._results.pop(task_id, None)
        return event

    # -- serving sessions ----------------------------------------------------

    async def serve_open(
        self,
        sid: str,
        digest: str,
        path: str,
        options: dict | None = None,
        spec: dict | None = None,
        runner: "list[str] | None" = None,
        timeout: float = 120.0,
    ) -> dict:
        """Open a resident serving session; returns the ``serve_opened``
        event (``slots``, worker ``pid``).

        Ships a cloudpickled model-factory by CAS digest: the worker
        verifies ``path``'s sha256 against ``digest`` BEFORE unpickling,
        calls the factory ONCE (model load + compile — hence the generous
        timeout), and serves request commands for the session's lifetime.
        A refused open raises :class:`AgentError`; permanent refusals
        (digest mismatch, a factory rejecting its model shape) carry the
        duck-typed ``fault_label`` so the resilience layer never burns
        gang retries re-opening them.  ``runner`` (native agent only)
        names the argv forked to host the session
        (``[python, harness, --serve-child]``).
        """
        command: dict = {
            "cmd": "serve_open", "id": sid, "digest": digest, "path": path,
        }
        if options:
            command["options"] = dict(options)
        if spec:
            command["spec"] = dict(spec)
        if runner:
            command["runner"] = [str(part) for part in runner]
        await self._send(command)

        def settled(c: "AgentClient"):
            if sid in c._serve_errors:
                event = c._serve_errors.pop(sid)
                failure = AgentError(
                    f"agent@{c.address}: serve_open {sid} failed "
                    f"({event.get('code')}): {event.get('message')}"
                )
                if event.get("permanent"):
                    failure.fault_label = str(  # type: ignore[attr-defined]
                        event.get("label")
                        or f"serve_{event.get('code') or 'error'}"
                    )
                    failure.fault_transient = False  # type: ignore[attr-defined]
                raise failure
            return c._serve_opened.pop(sid, None)

        return await self._wait(settled, timeout)

    async def serve_request(
        self,
        sid: str,
        rid: str,
        prompt,
        params: dict | None = None,
        deadline_s: float = 0.0,
        tenant: str = "",
        kv_bytes: bytes | None = None,
        kv_digest: str = "",
        kv_path: str = "",
        trace: dict | None = None,
    ) -> None:
        """Submit one request to an open session (fire-and-stream).

        The response streams back over the telemetry side-band as
        ``serve.token`` records routed to the session's
        :meth:`watch_serve` sink; backpressure and unknown sessions
        arrive as ``serve.reject`` records the same way.

        A disaggregated request attaches its prefilled KV bundle:
        ``kv_bytes`` rides a raw binary frame body on a negotiated
        channel (the gang-local fast path), ``kv_path`` references a
        CAS-staged copy (the cross-pool road); either way ``kv_digest``
        is verified worker-side before the engine unpickles anything,
        and any mismatch silently degrades to a full prefill.

        ``trace`` (a :func:`~.obs.trace.context_of` carrier) rides the
        command header so the worker's per-request spans — queue wait,
        admission, decode — join the dispatcher's trace instead of
        starting orphan ones.
        """
        command: dict = {
            "cmd": "serve_request", "id": sid, "rid": rid, "prompt": prompt,
        }
        if params:
            command["params"] = dict(params)
        if deadline_s:
            command["deadline_s"] = float(deadline_s)
        if tenant:
            command["tenant"] = str(tenant)
        if trace:
            command["trace"] = dict(trace)
        if kv_digest:
            command["kv_digest"] = kv_digest
        if kv_path:
            command["kv_path"] = kv_path
        if self.frames_active:
            # Header-only frame (or body-carrying for an inline KV
            # bundle): at serving request rates even the line framing +
            # re-parse tax is worth skipping.
            if kv_bytes is not None and not kv_path:
                command["_body"] = "kv_bytes"
                await self._send_frame(
                    frames.VERB_SERVE, command, kv_bytes
                )
                return
            await self._send_frame(frames.VERB_SERVE, command)
            return
        if kv_bytes is not None and not kv_path:
            command["kv"] = base64.b64encode(kv_bytes).decode("ascii")
        await self._send(command)

    async def serve_prefill(
        self,
        sid: str,
        rid: str,
        prompt,
        params: dict | None = None,
        timeout: float = 60.0,
        trace: dict | None = None,
    ) -> dict:
        """Run a prefill-only pass on an open session; returns the
        ``serve_kv`` event with the bundle under ``data_bytes``.

        The worker's engine packages the prompt's prefilled cache lane
        (plus cursor/rng/sampling state) as a serializable KV bundle and
        streams it back as a raw frame body (base64 on a JSONL channel).
        A worker-side refusal (unknown session, shed, an engine without
        the surface) raises :class:`AgentError` — the disaggregated
        front degrades to a full prefill on the decode replica.

        ``trace`` propagates the requesting stream's trace context so
        the prefill tier's worker span lands in the SAME trace as the
        decode tier's — the cross-tier handoff is one waterfall.
        """
        command: dict = {
            "cmd": "serve_prefill", "id": sid, "rid": rid, "prompt": prompt,
        }
        if params:
            command["params"] = dict(params)
        if trace:
            command["trace"] = dict(trace)
        if self.frames_active:
            await self._send_frame(frames.VERB_SERVE, command)
        else:
            await self._send(command)
        key = f"{sid}/{rid}"

        def settled(c: "AgentClient"):
            return c._serve_kv.pop(key, None)

        event = await self._wait(settled, timeout)
        if event.get("code"):
            raise AgentError(
                f"agent@{self.address}: serve_prefill {rid} failed "
                f"({event.get('code')}): {event.get('message')}"
            )
        if "data_bytes" not in event and event.get("data"):
            try:
                event["data_bytes"] = base64.b64decode(event["data"])
            except (TypeError, ValueError) as err:
                raise AgentError(
                    f"agent@{self.address}: serve_prefill {rid} returned "
                    f"an undecodable bundle: {err}"
                ) from err
        return event

    async def serve_close(self, sid: str, timeout: float = 30.0) -> dict:
        """Close a session; returns the ``serve_closed`` event (``served``
        request count) after the worker drains admitted lanes."""
        await self._send({"cmd": "serve_close", "id": sid})

        def settled(c: "AgentClient"):
            if sid in c._serve_errors:
                event = c._serve_errors.pop(sid)
                failure = AgentError(
                    f"agent@{c.address}: serve_close {sid} failed "
                    f"({event.get('code')}): {event.get('message')}"
                )
                if event.get("permanent"):
                    # Same duck-tag propagation as serve_open: closing a
                    # session that does not exist is deterministic — the
                    # resilience layer must not burn retries on it.
                    failure.fault_label = str(  # type: ignore[attr-defined]
                        event.get("label")
                        or f"serve_{event.get('code') or 'error'}"
                    )
                    failure.fault_transient = False  # type: ignore[attr-defined]
                raise failure
            return c._serve_closed.pop(sid, None)

        return await self._wait(settled, timeout)

    # -- crash recovery (epoch fence, inventories, stream resume) ------------

    async def declare_epoch(self, epoch: int, timeout: float = 15.0) -> dict:
        """Declare this dispatcher's journal epoch on the channel.

        The worker records the highest epoch it has ever seen and refuses
        mutating commands from channels that declared a lower one — the
        split-brain fence.  Raises when THIS channel is the stale one.
        """
        self._epoch_ack = None
        self._errors.pop("", None)
        self._error_codes.pop("", None)
        await self._send({"cmd": "epoch", "epoch": int(epoch)})

        def settled(c: "AgentClient"):
            if c._epoch_ack is not None:
                return c._epoch_ack
            if c._error_codes.get("") == "stale_epoch":
                message = c._errors.pop("", "stale epoch")
                c._error_codes.pop("", None)
                raise AgentError(
                    f"agent@{c.address}: {message}"
                )
            return None

        return await self._wait(settled, timeout)

    async def serve_inventory(self, timeout: float = 30.0) -> dict:
        """Ask the worker which serving sessions survive in-process.

        Returns the ``serve_inventory`` event: per-session sid, factory
        digest, running rids with emitted-token counts, and the finished
        ring — everything the recovery path needs to re-adopt streams.
        """
        self._inventories.pop("serve", None)
        await self._send({"cmd": "serve_inventory"})
        return await self._wait(
            lambda c: c._inventories.pop("serve", None), timeout
        )

    async def task_inventory(self, timeout: float = 30.0) -> dict:
        """Ask the worker which forked task children are still running."""
        self._inventories.pop("task", None)
        await self._send({"cmd": "task_inventory"})
        return await self._wait(
            lambda c: c._inventories.pop("task", None), timeout
        )

    async def serve_resume(
        self, sid: str, rid: str, start: int, timeout: float = 30.0
    ) -> dict:
        """Resume one stream from token ``start`` after re-adoption.

        The worker re-emits ``history[start:]`` on the side-band (under
        the same lock as live chunks, so no gap is possible) and answers
        ``serve_resumed`` with what it knows about the rid: streaming,
        done, pending, or unknown.
        """
        key = f"{sid}/{rid}"
        self._serve_resumed.pop(key, None)
        await self._send({
            "cmd": "serve_resume", "id": sid, "rid": rid, "from": int(start),
        })
        return await self._wait(
            lambda c: c._serve_resumed.pop(key, None), timeout
        )

    async def serve_attach(
        self,
        sid: str,
        adapter: str,
        digest: str,
        path: str,
        timeout: float = 60.0,
    ) -> dict:
        """Splice a LoRA adapter bundle into a *running* session.

        ``path`` names a CAS-staged bundle on the worker host and
        ``digest`` its sha256 — the worker verifies bytes before the
        engine touches them, so a torn stage refuses instead of serving
        garbage.  Returns the ``serve_attached`` ack (content ``digest``
        plus ``attach_s``).  Refusals raise :class:`AgentError`, carrying
        the same permanence duck-tags as serve_open: an engine without an
        adapter bank or a digest mismatch is deterministic and must not
        burn gang retries.
        """
        return await self._serve_attach_rpc(
            {
                "cmd": "serve_attach", "id": sid, "adapter": str(adapter),
                "digest": str(digest), "path": str(path),
            },
            timeout,
        )

    async def serve_detach(
        self, sid: str, adapter: str, timeout: float = 30.0
    ) -> dict:
        """Remove a named adapter from a running session (its decode slot
        frees once in-flight requests pinned to it drain)."""
        return await self._serve_attach_rpc(
            {"cmd": "serve_detach", "id": sid, "adapter": str(adapter)},
            timeout,
        )

    async def _serve_attach_rpc(self, command: dict, timeout: float) -> dict:
        name = str(command["cmd"])
        sid, adapter = str(command["id"]), str(command["adapter"])
        key = f"{name}ed:{sid}/{adapter}"
        self._serve_attached.pop(key, None)
        await self._send(command)

        def settled(c: "AgentClient"):
            return c._serve_attached.pop(key, None)

        event = await self._wait(settled, timeout)
        if event.get("code"):
            failure = AgentError(
                f"agent@{self.address}: {name} {adapter!r} on {sid} failed "
                f"({event.get('code')}): {event.get('message')}"
            )
            if event.get("permanent"):
                failure.fault_label = str(  # type: ignore[attr-defined]
                    event.get("label")
                    or f"serve_{event.get('code') or 'error'}"
                )
                failure.fault_transient = False  # type: ignore[attr-defined]
            raise failure
        return event

    async def serve_cancel(self, sid: str, rid: str) -> None:
        """Cancel one in-flight request on a session (fire-and-forget).

        The hedging path calls this for the LOSING arm the moment the
        winner's first token lands: the worker frees the decode lane and
        finalizes the stream with ``error="cancelled"``.  No ack to wait
        on — the cancel races completion by design, and either terminal
        record settles the same waiter.
        """
        await self._send({"cmd": "serve_cancel", "id": sid, "rid": rid})

    # -- resident-mode profiling ---------------------------------------------

    async def profile_start(
        self,
        profile_id: str,
        trace_dir: str,
        sid: str = "",
        timeout: float = 60.0,
    ) -> dict:
        """Start a ``jax.profiler`` trace inside the resident runtime.

        The pool server runs the trace in its own process (where RPC
        invocations and pool-mode serving sessions execute); the native
        C++ agent forwards the command into a live ``--serve-child``
        session runner (``sid`` pins which one; otherwise the agent picks
        any).  Exactly one trace runs per runtime — a second start is
        refused ``busy``.  Returns the ``profile_started`` event.
        """
        command: dict = {
            "cmd": "profile_start", "id": profile_id, "dir": trace_dir,
        }
        if sid:
            command["sid"] = sid
        await self._send(command)
        return await self._wait(
            self._profile_settled(profile_id, self._profile_started), timeout
        )

    async def profile_stop(
        self,
        profile_id: str,
        artifact_dir: str = "",
        sid: str = "",
        timeout: float = 120.0,
        discard: bool = False,
    ) -> dict:
        """Stop the active trace; returns the ``profile_stopped`` event.

        The worker packages the trace directory into one content-addressed
        ``<sha256>.profile.tgz`` under ``artifact_dir`` (the dispatcher
        points this at the CAS dir) and announces ``path``/``digest``/
        ``bytes`` — the caller fetches and digest-verifies before trusting
        the artifact.  The generous timeout covers tarring a large trace.
        ``discard=True`` (a compensating stop for an abandoned capture)
        skips packaging entirely: the worker deletes the raw trace dir.
        """
        command: dict = {"cmd": "profile_stop", "id": profile_id}
        if artifact_dir:
            command["artifact_dir"] = artifact_dir
        if sid:
            command["sid"] = sid
        if discard:
            command["discard"] = True
        await self._send(command)
        return await self._wait(
            self._profile_settled(profile_id, self._profile_stopped), timeout
        )

    async def profile_wait_stopped(
        self, profile_id: str, timeout: float = 120.0
    ) -> dict:
        """Wait out an in-flight stop's ``profile_stopped`` WITHOUT
        re-sending the command — the worker packages the trace on a
        thread, and a resend during packaging is refused ("already
        stopping"), abandoning the artifact it is about to announce."""
        return await self._wait(
            self._profile_settled(profile_id, self._profile_stopped), timeout
        )

    def _profile_settled(self, profile_id: str, table: dict):
        def settled(c: "AgentClient"):
            if profile_id in c._profile_errors:
                event = c._profile_errors.pop(profile_id)
                raise AgentError(
                    f"agent@{c.address}: profile {profile_id} failed "
                    f"({event.get('code')}): {event.get('message')}"
                )
            return table.pop(profile_id, None)

        return settled

    def watch_serve(self, sid: str, sink) -> None:
        """Route session ``sid``'s side-band records to ``sink(sid, data)``
        (instead of :attr:`on_telemetry`).  Register BEFORE the first
        request so no token can slip past."""
        self._serve_sinks[sid] = sink

    def unwatch_serve(self, sid: str) -> None:
        """Drop a closed session's sink and retained per-sid state."""
        self._serve_sinks.pop(sid, None)
        self._telemetry_seq.pop(sid, None)
        self._serve_opened.pop(sid, None)
        self._serve_errors.pop(sid, None)
        self._serve_closed.pop(sid, None)
        for key in [
            k for k in self._serve_kv if k.startswith(f"{sid}/")
        ]:
            del self._serve_kv[key]
        for key in [
            k for k in self._serve_attached
            if k.partition(":")[2].startswith(f"{sid}/")
        ]:
            del self._serve_attached[key]

    async def wait_dead(self) -> None:
        """Block until this channel dies, then raise :class:`AgentError`.

        The serving tier's supervisor awaits this to notice a dropped
        channel (or dead resident worker) the moment the reader does,
        triggering its reconnect instead of waiting on a stuck stream.
        """
        await self._wait(lambda c: None, None)

    def forget(self, task_id: str) -> None:
        """Drop any retained state for a finished/abandoned task.

        Called by the executor when an operation leaves its books — on
        EVERY exit path (success, kill, channel death, retry teardown):
        a straggler's unconsumed exit event, an unclaimed RPC result, the
        telemetry seq high-water mark, and any stored rejection must not
        accumulate for the channel's lifetime.
        """
        self._started.pop(task_id, None)
        self._exits.pop(task_id, None)
        self._errors.pop(task_id, None)
        self._error_codes.pop(task_id, None)
        self._results.pop(task_id, None)
        if task_id not in self._serve_sinks:
            # Serving sessions outlive electron operations on the same
            # channel: an electron's forget() must never reset a live
            # session's seq high-water mark (token dedup depends on it).
            self._telemetry_seq.pop(task_id, None)

    async def kill(self, task_id: str, sig: int = 15) -> None:
        await self._send({"cmd": "kill", "id": task_id, "sig": sig})

    async def _send(self, command: dict) -> None:
        if self._dead is not None:
            raise AgentError(f"agent@{self.address} channel died: {self._dead}")
        _AGENT_RPCS.labels(cmd=str(command.get("cmd", "?"))).inc()
        line = json.dumps(command)
        AGENT_FRAMES_TOTAL.labels(
            verb=str(command.get("cmd", "?")), encoding="jsonl"
        ).inc()
        AGENT_WIRE_BYTES_TOTAL.labels(
            direction="up", encoding="jsonl"
        ).inc(len(line) + 1)
        try:
            await self._process.write_line(line)
        except TransportError as err:
            raise AgentError(f"agent@{self.address}: send failed: {err}") from err

    async def _send_frame(
        self, verb: int, header: dict, body: bytes = b""
    ) -> None:
        """One binary frame down the channel (negotiated path only)."""
        if self._dead is not None:
            raise AgentError(f"agent@{self.address} channel died: {self._dead}")
        _AGENT_RPCS.labels(cmd=str(header.get("cmd", "?"))).inc()
        payload = frames.encode_frame(
            verb, header, body, codec=self._frame_codec
        )
        AGENT_FRAMES_TOTAL.labels(
            verb=frames.VERB_NAMES.get(verb, str(verb)), encoding="binary"
        ).inc()
        AGENT_WIRE_BYTES_TOTAL.labels(
            direction="up", encoding="binary"
        ).inc(len(payload))
        try:
            await self._process.write_bytes(payload)
        except TransportError as err:
            raise AgentError(f"agent@{self.address}: send failed: {err}") from err
