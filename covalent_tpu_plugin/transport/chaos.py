"""Deterministic fault injection for any :class:`Transport`.

Production resilience claims are worthless untested, and real networks
produce faults neither deterministically nor on demand.  ``ChaosTransport``
wraps any transport backend and injects *scripted, seeded* faults — connect
errors, run errors, per-op delay, channel death after N ops (or on the
N-th command matching a substring), upload truncation — so the retry /
circuit-breaker / timeout machinery (resilience.py) is exercised by real
dispatches through the real lifecycle, reproducibly.

One :class:`ChaosPlan` is shared by every transport an executor creates, so
process-wide budgets like "exactly one channel death per fan-out"
(``max_faults=1``) are expressible.  Configuration is one environment
variable holding a comma-separated ``key=value`` spec::

    COVALENT_TPU_CHAOS="seed=7,drop_match=if test -f,max_faults=1"

Keys (all optional; unknown keys are rejected loudly — a typo'd chaos spec
silently injecting nothing would fake a green resilience test):

* ``seed``            — RNG seed for the probabilistic keys (default 0).
* ``delay``           — seconds of latency added to every op.
* ``connect_errors``  — fail the first N connect attempts.
* ``p_connect_error`` — probability a connect attempt fails.
* ``run_errors``      — fail the next N ``run`` calls (after any skip).
* ``p_run_error``     — probability any ``run`` call fails.
* ``drop_after``      — channel dies permanently after N successful ops.
* ``drop_match``      — channel dies on the next command containing this
  substring (pair with ``drop_match_skip=N`` to let N matches through).
* ``truncate_uploads``— corrupt the next N uploads (half the payload).
* ``preempt_after``   — models a TPU spot preemption: after N ops on one
  transport, SIGTERM is delivered to the worker processes the executor
  registered on it (``chaos_notify_pid``), then the channel drops after
  a ``preempt_grace``-second grace window — notice first, loss second,
  exactly the Cloud TPU preemption sequence.
* ``preempt_grace``   — seconds between the SIGTERM notice and channel
  death (default 1.0).
* ``jitter``          — seeded uniform extra latency in ``[0, jitter)``
  seconds added per op (gray mode: the link is alive but noisy).
* ``p_slow``          — probability an op hits the heavy tail: it sleeps
  ``slow_factor × max(delay, jitter, 0.01)`` seconds instead of its
  normal latency (gray mode: a browned-out worker, not a dead one).
* ``slow_factor``     — tail multiplier for ``p_slow`` (default 10).
* ``p_drop_op``       — probability a single op fails with a transport
  error WITHOUT killing the channel (gray mode: lossy-but-alive; the
  next op on the same transport works).
* ``max_faults``      — process-wide budget across ALL injected faults.

Every injected fault emits a ``chaos.fault`` event and increments
``covalent_tpu_chaos_faults_total{kind}`` so test assertions and bench
reports can attribute recovery behavior to the faults that caused it.
"""

from __future__ import annotations

import asyncio
import os
import random
import time
from typing import Any

from ..obs import events as obs_events
from ..obs.metrics import REGISTRY
from ..utils.log import app_log
from .base import CommandResult, Transport, TransportError

__all__ = ["ChaosPlan", "ChaosTransport", "plan_from_env", "plan_from_spec"]

ENV_VAR = "COVALENT_TPU_CHAOS"

CHAOS_FAULTS_TOTAL = REGISTRY.counter(
    "covalent_tpu_chaos_faults_total",
    "Faults injected by ChaosTransport, by kind",
    ("kind",),
)

_INT_KEYS = (
    "seed", "connect_errors", "run_errors", "drop_after",
    "drop_match_skip", "truncate_uploads", "max_faults", "preempt_after",
)
_FLOAT_KEYS = (
    "delay", "p_connect_error", "p_run_error", "preempt_grace",
    "jitter", "p_slow", "slow_factor", "p_drop_op",
)
_STR_KEYS = ("drop_match",)


class ChaosPlan:
    """Shared, mutable fault script consumed by :class:`ChaosTransport`.

    Counter-based faults (``connect_errors``, ``drop_after``, ...) are
    deterministic; probability-based ones draw from one seeded RNG, so a
    fixed seed reproduces the same fault sequence for the same op order.
    """

    def __init__(
        self,
        seed: int = 0,
        delay: float = 0.0,
        connect_errors: int = 0,
        p_connect_error: float = 0.0,
        run_errors: int = 0,
        p_run_error: float = 0.0,
        drop_after: int = 0,
        drop_match: str = "",
        drop_match_skip: int = 0,
        truncate_uploads: int = 0,
        max_faults: int = 0,
        preempt_after: int = 0,
        preempt_grace: float = 1.0,
        jitter: float = 0.0,
        p_slow: float = 0.0,
        slow_factor: float = 10.0,
        p_drop_op: float = 0.0,
    ) -> None:
        self.seed = int(seed)
        self.delay = float(delay)
        self.connect_errors = int(connect_errors)
        self.p_connect_error = float(p_connect_error)
        self.run_errors = int(run_errors)
        self.p_run_error = float(p_run_error)
        self.drop_after = int(drop_after)
        self.drop_match = str(drop_match)
        self.drop_match_skip = int(drop_match_skip)
        self.truncate_uploads = int(truncate_uploads)
        self.max_faults = int(max_faults)  # 0 = unbounded
        self.preempt_after = int(preempt_after)
        self.preempt_grace = float(preempt_grace)
        self.jitter = float(jitter)
        self.p_slow = float(p_slow)
        self.slow_factor = float(slow_factor)
        self.p_drop_op = float(p_drop_op)
        self.rng = random.Random(self.seed)
        self.faults_injected = 0
        self._match_seen = 0
        self._jitter_announced = False

    @property
    def active(self) -> bool:
        """Whether this plan can inject anything at all."""
        return any((
            self.delay > 0, self.connect_errors > 0, self.p_connect_error > 0,
            self.run_errors > 0, self.p_run_error > 0, self.drop_after > 0,
            self.drop_match, self.truncate_uploads > 0,
            self.preempt_after > 0, self.jitter > 0, self.p_slow > 0,
            self.p_drop_op > 0,
        ))

    def slow_tail_s(self) -> float:
        """Seconds the heavy tail sleeps when a ``p_slow`` fault fires."""
        return self.slow_factor * max(self.delay, self.jitter, 0.01)

    def take_fault(self, kind: str, **detail: Any) -> bool:
        """Consume one unit of fault budget; False when the budget is spent."""
        if self.max_faults and self.faults_injected >= self.max_faults:
            return False
        self.faults_injected += 1
        CHAOS_FAULTS_TOTAL.labels(kind=kind).inc()
        obs_events.emit("chaos.fault", kind=kind, **detail)
        app_log.warning("chaos: injecting %s fault (%s)", kind, detail)
        return True


def plan_from_spec(spec: str) -> ChaosPlan | None:
    """Parse a ``key=value,key=value`` spec; None when empty/blank."""
    spec = (spec or "").strip()
    if not spec:
        return None
    kwargs: dict[str, Any] = {}
    for token in spec.split(","):
        token = token.strip()
        if not token:
            continue
        key, sep, value = token.partition("=")
        key = key.strip()
        if not sep:
            raise ValueError(f"chaos spec token {token!r} is not key=value")
        if key in _INT_KEYS:
            kwargs[key] = int(value)
        elif key in _FLOAT_KEYS:
            kwargs[key] = float(value)
        elif key in _STR_KEYS:
            kwargs[key] = value
        else:
            raise ValueError(
                f"unknown chaos spec key {key!r} "
                f"(known: {', '.join(_INT_KEYS + _FLOAT_KEYS + _STR_KEYS)})"
            )
    return ChaosPlan(**kwargs)


def plan_from_env() -> ChaosPlan | None:
    """Plan from ``COVALENT_TPU_CHAOS``; None when unset."""
    return plan_from_spec(os.environ.get(ENV_VAR, ""))


class ChaosTransport(Transport):
    """A transport whose faults are scripted by a shared :class:`ChaosPlan`.

    Semantics mirror a real broken channel: once a drop fires, *every*
    subsequent op on this transport raises (without consuming further fault
    budget) until the executor discards it and dials a fresh one — exactly
    the recovery path the resilience layer must drive.
    """

    #: A chaos wrapper *simulates a network* even over a shared-fs inner
    #: transport, so the codec layer negotiates compression through it and
    #: ``put_bundle`` deliberately rides the base-class implementation:
    #: its tar travels through THIS class's ``put`` (truncation faults
    #: corrupt it) and its unpack through ``run`` (drop faults kill it),
    #: exactly like a real wire.
    zero_wire = False

    def __init__(self, inner: Transport, plan: ChaosPlan) -> None:
        self.inner = inner
        self.plan = plan
        self.ops = 0
        self.dead = False
        #: worker process-group leaders the executor registered on this
        #: channel (chaos_notify_pid) — the preempt fault's SIGTERM targets.
        self.worker_pids: list[int] = []
        self._preempted = False
        self._dead_at: float | None = None

    @property
    def address(self) -> str:  # type: ignore[override]
        return self.inner.address

    def chaos_notify_pid(self, pid: int) -> None:
        """Register one worker pid launched over this channel (the
        executor calls this after dispatch) so a ``preempt_after`` fault
        can deliver its SIGTERM notice to the right process group."""
        if pid and pid not in self.worker_pids:
            self.worker_pids.append(int(pid))

    async def _deliver_preempt_notice(self) -> None:
        """SIGTERM every registered worker's process group via the INNER
        channel (the notice arrives even though this wrapper is about to
        drop): group first so the harness's own children get it, direct
        pid as the fallback for the pre-setsid race."""
        for pid in list(self.worker_pids):
            try:
                await self.inner.run(
                    f"kill -s TERM -- -{pid} 2>/dev/null || "
                    f"kill -s TERM {pid} 2>/dev/null || true"
                )
            except Exception as err:  # noqa: BLE001 - notice is best-effort
                app_log.debug("chaos: preempt notice to %s failed: %s",
                              pid, err)

    async def _gate(self, op: str, command: str = "") -> None:
        """Count one op; raise if the channel is (or now becomes) dead."""
        if self._dead_at is not None and time.monotonic() >= self._dead_at:
            # The preemption grace window elapsed: the VM is gone.
            self.dead = True
        if self.dead:
            raise TransportError(
                f"chaos: channel to {self.address} is dead"
            )
        if self.plan.delay > 0:
            await asyncio.sleep(self.plan.delay)
        if self.plan.jitter > 0:
            # Gray noise: seeded uniform extra latency on every op.  One
            # announcing fault (the first) rather than one per op — the
            # budget is for discrete faults, not continuous noise.
            if not self.plan._jitter_announced:
                self.plan._jitter_announced = True
                self.plan.take_fault(
                    "jitter", address=self.address, jitter_s=self.plan.jitter
                )
            await asyncio.sleep(self.plan.rng.random() * self.plan.jitter)
        self.ops += 1
        plan = self.plan
        if plan.p_slow > 0 and plan.rng.random() < plan.p_slow:
            if plan.take_fault(
                "slow", address=self.address, op=op,
                slow_s=round(plan.slow_tail_s(), 3),
            ):
                # Heavy tail: the op completes, just brutally late — the
                # gray failure a binary breaker never sees.
                await asyncio.sleep(plan.slow_tail_s())
        if plan.p_drop_op > 0 and plan.rng.random() < plan.p_drop_op:
            if plan.take_fault("drop_op", address=self.address, op=op):
                # Lossy-but-alive: THIS op fails, the channel survives.
                raise TransportError(
                    f"chaos: op {op} dropped on {self.address} "
                    "(channel still alive)"
                )
        if (
            plan.preempt_after
            and not self._preempted
            and self.ops > plan.preempt_after
            and plan.take_fault(
                "preempt", address=self.address, op=op, ops=self.ops,
                pids=list(self.worker_pids), grace_s=plan.preempt_grace,
            )
        ):
            # Spot preemption sequence: TERM notice now, channel loss
            # after the grace window.  Ops inside the window still work —
            # that is what lets a cooperative final checkpoint (and a
            # serving warm handoff) land before the loss.
            self._preempted = True
            self._dead_at = time.monotonic() + max(
                0.0, plan.preempt_grace
            )
            await self._deliver_preempt_notice()
        if plan.drop_after and self.ops > plan.drop_after:
            if plan.take_fault("drop", address=self.address, op=op, ops=self.ops):
                self.dead = True
                raise TransportError(
                    f"chaos: channel to {self.address} dropped after "
                    f"{self.ops - 1} ops"
                )
        if plan.drop_match and command and plan.drop_match in command:
            plan._match_seen += 1
            if plan._match_seen > plan.drop_match_skip and plan.take_fault(
                "drop", address=self.address, op=op, match=plan.drop_match
            ):
                self.dead = True
                raise TransportError(
                    f"chaos: channel to {self.address} dropped on command "
                    f"matching {plan.drop_match!r}"
                )

    # -- connect (driven by connect_with_retries via _open) ------------------

    async def _open(self) -> None:
        plan = self.plan
        fail = False
        if plan.connect_errors > 0:
            fail = plan.take_fault("connect", address=self.address)
            if fail:
                plan.connect_errors -= 1
        elif plan.p_connect_error > 0 and plan.rng.random() < plan.p_connect_error:
            fail = plan.take_fault("connect", address=self.address)
        if fail:
            raise ConnectionRefusedError(
                f"chaos: connect to {self.address} refused"
            )
        opener = getattr(self.inner, "_open", None)
        if opener is not None:
            await opener()

    # -- Transport interface -------------------------------------------------

    async def run(self, command: str, timeout: float | None = None) -> CommandResult:
        await self._gate("run", command)
        plan = self.plan
        fail = False
        if plan.run_errors > 0:
            fail = plan.take_fault("run", address=self.address, command=command[:80])
            if fail:
                plan.run_errors -= 1
        elif plan.p_run_error > 0 and plan.rng.random() < plan.p_run_error:
            fail = plan.take_fault("run", address=self.address, command=command[:80])
        if fail:
            raise TransportError(f"chaos: run failed on {self.address}")
        return await self.inner.run(command, timeout)

    async def put(self, local_path: str, remote_path: str) -> None:
        await self._gate("put")
        plan = self.plan
        if plan.truncate_uploads > 0 and plan.take_fault(
            "truncate", address=self.address, remote=remote_path
        ):
            plan.truncate_uploads -= 1
            with open(local_path, "rb") as f:
                payload = f.read()
            import tempfile

            # Ship half the bytes under the same remote name: the CAS
            # digest verification on the worker is what must catch this.
            with tempfile.NamedTemporaryFile(delete=False) as tmp:
                tmp.write(payload[: max(0, len(payload) // 2)])
                truncated = tmp.name
            try:
                await self.inner.put(truncated, remote_path)
            finally:
                os.unlink(truncated)
            return
        await self.inner.put(local_path, remote_path)

    async def get(self, remote_path: str, local_path: str) -> None:
        await self._gate("get")
        await self.inner.get(remote_path, local_path)

    # exists_batch / rename / remove deliberately NOT forwarded to the
    # inner transport: the base-class implementations ride self.run (one
    # gated round trip each), so a chaos-wrapped LocalTransport behaves
    # op-for-op like a real SSH wire — a shell exec per probe/publish —
    # instead of silently borrowing the inner backend's direct-filesystem
    # fast paths.  Faults still apply exactly once, via the run gate.

    async def start_process(self, command: str, describe: str = ""):
        await self._gate("start_process", command)
        return await self.inner.start_process(command, describe)

    async def close(self) -> None:
        await self.inner.close()
