"""Wire codec layer: transport-level compression + bundled transfers.

The PR-2 CAS cache removed *repeat* uploads, but every byte that still
ships rides a whole-file, uncompressed ``put`` (ssh.py:243-255,
minissh.py:846) and every artifact costs its own round trips.  Both
Podracer (arXiv:2104.06272) and the Gemma-on-TPU cost study
(arXiv:2605.25645) locate a large share of dispatch cost in exactly this
payload movement, so this module attacks bytes-on-wire and round-trip
count directly:

* **Codecs** — ``zlib`` (stdlib, always available where python3 is) and
  ``zstd`` (via the optional ``zstandard`` package), negotiated per
  connection during the executor's pre-flight probe with a raw fallback,
  plus a skip-if-incompressible heuristic (small files and files that
  don't shrink ship raw — compression must never cost bytes or an extra
  round trip it can't pay for).
* **Single-file publish** (:func:`put_file`) — the CAS upload path:
  compressed payload to a temp name, then ONE remote exec decompresses,
  verifies the sha256 of the *decompressed* bytes against the CAS digest,
  and atomically publishes.  Same round-trip count as the raw
  put + rename path, fewer bytes on the wire.
* **Bundles** (:meth:`~.base.Transport.put_bundle`) — the many small
  per-worker spec/manifest files of a fan-out packed into one tar, shipped
  with a single ``put`` and unpacked (digest-verified, atomic per member)
  in a single remote exec: N round trips become 2.
* **Wire accounting** — every byte that crosses a transport is counted in
  ``covalent_tpu_wire_bytes_total{direction,codec}`` so the savings are a
  first-class observable, not an inference.

A corrupt or truncated payload (a torn upload, a chaos-injected
truncation) fails the remote digest/decompress verification and raises
:class:`CodecIntegrityError` — deliberately NOT a ``TransportError``, so
the resilience classifier treats it as PERMANENT: content corruption must
fail loud, never burn the retry budget re-shipping the same torn bytes.
"""

from __future__ import annotations

import asyncio
import hashlib
import io
import json
import os
import shlex
import tarfile
import tempfile
import uuid
import zlib
from typing import TYPE_CHECKING, Sequence

from ..obs.metrics import REGISTRY

if TYPE_CHECKING:  # pragma: no cover - typing only, avoids an import cycle
    from .base import Transport

__all__ = [
    "Codec",
    "CodecIntegrityError",
    "WIRE_BYTES_TOTAL",
    "MIN_COMPRESS_BYTES",
    "available_codecs",
    "get_codec",
    "pick_codec",
    "probe_clause",
    "parse_probe",
    "build_bundle",
    "unpack_command",
    "put_file",
    "get_file",
]

#: Files below this size ship raw: the compression header + remote exec
#: can't pay for themselves on tiny payloads (pid files, small specs).
MIN_COMPRESS_BYTES = 512

#: Compressed output must beat this fraction of the input or the file
#: ships raw — incompressible payloads (already-compressed checkpoints,
#: random tensors) must not pay a decompress exec for zero byte savings.
MAX_COMPRESS_RATIO = 0.9

#: Marker printed by the remote publish/unpack helpers on verification
#: failure, so the caller can classify corruption apart from exec errors.
_INTEGRITY_MARKER = "COVALENT_TPU_INTEGRITY"
_INTEGRITY_EXIT = 9

#: Prefix of the codec-capability line the pre-flight probe prints.
PROBE_PREFIX = "COVALENT_TPU_CODECS="

WIRE_BYTES_TOTAL = REGISTRY.counter(
    "covalent_tpu_wire_bytes_total",
    "Bytes shipped across transports by direction (up/down) and codec",
    ("direction", "codec"),
)


class CodecIntegrityError(RuntimeError):
    """Payload failed digest/decompress verification after transfer.

    A RuntimeError (not TransportError) on purpose: resilience.classify_error
    maps unknown non-transport types to PERMANENT, which is correct for
    content corruption — retrying re-ships the same torn bytes (the chaos
    suite's truncated-bundle case must not start a retry storm).
    """


class Codec:
    """One named compression algorithm with local compress/decompress."""

    def __init__(self, name: str) -> None:
        self.name = name

    def compress(self, data: bytes) -> bytes:
        if self.name == "zlib":
            return zlib.compress(data, 6)
        if self.name == "zstd":
            import zstandard

            return zstandard.ZstdCompressor().compress(data)
        raise ValueError(f"unknown codec {self.name!r}")

    def decompress(self, data: bytes) -> bytes:
        if self.name == "zlib":
            return zlib.decompress(data)
        if self.name == "zstd":
            import zstandard

            return zstandard.ZstdDecompressor().decompress(data)
        raise ValueError(f"unknown codec {self.name!r}")


def available_codecs() -> list[str]:
    """Codec names this (dispatcher) side can use, best first."""
    import importlib.util

    names = []
    if importlib.util.find_spec("zstandard") is not None:
        names.append("zstd")
    names.append("zlib")  # stdlib: always present alongside python3
    return names


def get_codec(name: str) -> Codec | None:
    """Codec instance for ``name``; None for "raw"/empty/unknown."""
    if name in ("zlib", "zstd"):
        return Codec(name)
    return None


def pick_codec(remote_names: Sequence[str]) -> Codec | None:
    """Best codec both ends support; None means raw."""
    remote = set(remote_names)
    for name in available_codecs():
        if name in remote:
            return Codec(name)
    return None


def probe_clause(python_path: str, compress: str = "auto") -> str | None:
    """Shell clause for the pre-flight compound probing remote codecs.

    Prints ``COVALENT_TPU_CODECS=zlib[,zstd]`` on its own line; always
    exits 0 so a probe failure degrades to the raw codec instead of
    failing pre-flight.  zlib is probed under ``-E -S`` (stdlib, no site
    processing — a site hook importing heavy ML runtimes must not slow
    the probe); zstd needs site-packages, so its plain-interpreter probe
    is only included when the *local* side could use the answer.
    """
    if compress == "off":
        return None
    py = python_path
    clauses = [
        f"{py} -E -S -c 'import zlib; print(\"{PROBE_PREFIX}zlib\")'"
    ]
    if compress in ("auto", "zstd") and "zstd" in available_codecs():
        clauses.append(
            f"{py} -c 'import zstandard; print(\"{PROBE_PREFIX}zstd\")'"
        )
    joined = "; ".join(f"({c}) 2>/dev/null" for c in clauses)
    return f"({joined}; true)"


def parse_probe(stdout: str) -> list[str]:
    """Remote codec names from pre-flight stdout ([] -> raw fallback)."""
    names: list[str] = []
    for line in stdout.splitlines():
        line = line.strip()
        if line.startswith(PROBE_PREFIX):
            names.extend(
                t for t in line[len(PROBE_PREFIX):].split(",") if t
            )
    return names


def record_wire(direction: str, codec_name: str, nbytes: int) -> None:
    WIRE_BYTES_TOTAL.labels(direction=direction, codec=codec_name).inc(nbytes)


# --------------------------------------------------------------------------
# Remote helper programs (run via `python -c` in ONE exec each).
# Failure protocol: verification problems print the integrity marker and
# exit _INTEGRITY_EXIT; anything else is an environment/exec error.
# --------------------------------------------------------------------------

# argv: src dst codec digest("-" = skip).  Decompress src, verify the
# sha256 of the DECOMPRESSED bytes, atomically publish to dst, unlink src.
_PUBLISH_PROGRAM = """
import hashlib, os, sys
src, dst, codec, digest = sys.argv[1:5]
try:
    data = open(src, 'rb').read()
    if codec == 'zlib':
        import zlib; data = zlib.decompress(data)
    elif codec == 'zstd':
        import zstandard; data = zstandard.ZstdDecompressor().decompress(data)
    if digest != '-' and hashlib.sha256(data).hexdigest() != digest:
        raise ValueError('digest mismatch for ' + dst)
    d = os.path.dirname(dst)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = dst + '.pub-' + str(os.getpid())
    with open(tmp, 'wb') as f:
        f.write(data)
    os.replace(tmp, dst)
except Exception as e:
    sys.stderr.write('{marker}: %r\\n' % (e,))
    sys.exit({exit})
finally:
    try: os.unlink(src)
    except OSError: pass
""".strip().format(marker=_INTEGRITY_MARKER, exit=_INTEGRITY_EXIT)

# argv: bundle codec.  Decompress, untar, verify each member's sha256
# against the embedded manifest, publish each atomically, unlink bundle.
_UNPACK_PROGRAM = """
import hashlib, io, json, os, sys, tarfile
path, codec = sys.argv[1:3]
try:
    data = open(path, 'rb').read()
    if codec == 'zlib':
        import zlib; data = zlib.decompress(data)
    elif codec == 'zstd':
        import zstandard; data = zstandard.ZstdDecompressor().decompress(data)
    tf = tarfile.open(fileobj=io.BytesIO(data))
    manifest = json.load(tf.extractfile('MANIFEST.json'))
    for m in manifest:
        buf = tf.extractfile(m['name']).read()
        if m.get('sha256') and hashlib.sha256(buf).hexdigest() != m['sha256']:
            raise ValueError('digest mismatch for ' + m['dest'])
        d = os.path.dirname(m['dest'])
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = m['dest'] + '.pub-' + str(os.getpid())
        with open(tmp, 'wb') as f:
            f.write(buf)
        os.replace(tmp, m['dest'])
except Exception as e:
    sys.stderr.write('{marker}: %r\\n' % (e,))
    sys.exit({exit})
finally:
    try: os.unlink(path)
    except OSError: pass
""".strip().format(marker=_INTEGRITY_MARKER, exit=_INTEGRITY_EXIT)

# argv: src tmp min_bytes codec.  Compress src to tmp when it's large
# enough to be worth it; print which path the download should take.
_PACK_PROGRAM = """
import os, sys
src, tmp, min_bytes, codec = sys.argv[1:5]
data = open(src, 'rb').read()
out = None
if len(data) >= int(min_bytes):
    if codec == 'zlib':
        import zlib; out = zlib.compress(data, 6)
    elif codec == 'zstd':
        import zstandard; out = zstandard.ZstdCompressor().compress(data)
if out is None or len(out) >= len(data):
    print('RAW %d' % len(data))
else:
    with open(tmp, 'wb') as f:
        f.write(out)
    print('Z %d' % len(out))
""".strip()


def _helper_python(python_path: str, codec_name: str) -> str:
    """Interpreter invocation for the remote helper programs.

    ``-E -S`` skips site/sitecustomize processing — the helpers are pure
    stdlib, and a site hook importing heavy ML runtimes (TPU-VM images do)
    would turn a ~30 ms exec into seconds.  zstd lives in site-packages,
    so only that codec pays the full interpreter start.
    """
    if codec_name == "zstd":
        return python_path
    return f"{python_path} -E -S"


def _check_exec(result, what: str):
    """Map a helper program's exit into the right exception type."""
    from .base import TransportError

    stderr = (result.stderr or "").strip()
    if result.exit_status == _INTEGRITY_EXIT or _INTEGRITY_MARKER in stderr:
        raise CodecIntegrityError(
            f"{what} failed digest/decompress verification "
            f"(torn or corrupt payload): {stderr}"
        )
    if result.exit_status != 0:
        raise TransportError(f"{what} failed: {stderr}")
    return result


def build_bundle(
    items: Sequence[tuple[str, str, str]], codec: Codec | None
) -> tuple[bytes, str]:
    """Pack ``(local, remote, digest)`` items into one (maybe compressed)
    tar payload; returns ``(payload, codec_name)``.

    The manifest (member name -> destination + expected sha256) travels
    inside the tar, so the single remote exec needs no other input.  The
    incompressible-skip heuristic applies to the whole bundle: if the
    compressed tar doesn't shrink, the raw tar ships.
    """
    buf = io.BytesIO()
    manifest = []
    with tarfile.open(fileobj=buf, mode="w") as tf:
        for i, (local, remote, digest) in enumerate(items):
            name = f"m{i}"
            manifest.append({"name": name, "dest": remote, "sha256": digest})
            tf.add(local, arcname=name)
        man_bytes = json.dumps(manifest).encode()
        info = tarfile.TarInfo("MANIFEST.json")
        info.size = len(man_bytes)
        tf.addfile(info, io.BytesIO(man_bytes))
    raw = buf.getvalue()
    if codec is not None and len(raw) >= MIN_COMPRESS_BYTES:
        packed = codec.compress(raw)
        if len(packed) < len(raw) * MAX_COMPRESS_RATIO:
            return packed, codec.name
    return raw, "raw"


def unpack_command(
    python_path: str, bundle_path: str, codec_name: str
) -> str:
    return (
        f"{_helper_python(python_path, codec_name)} "
        f"-c {shlex.quote(_UNPACK_PROGRAM)} "
        f"{shlex.quote(bundle_path)} {codec_name}"
    )


async def put_file(
    conn: "Transport",
    local_path: str,
    remote_path: str,
    *,
    codec: Codec | None = None,
    python_path: str = "python3",
    digest: str = "",
) -> dict:
    """Ship one file with atomic publish, compressed when profitable.

    Raw path: temp put + rename (the PR-2 CAS publish shape).  Compressed
    path: temp put + ONE exec that decompresses, verifies ``digest``
    against the *decompressed* bytes, and publishes — the same round-trip
    count, fewer bytes.  Returns ``{"ops", "wire_bytes", "codec"}``.
    """
    payload: bytes | None = None
    codec_name = "raw"
    if codec is not None:
        def _maybe_compress() -> bytes | None:
            data = open(local_path, "rb").read()
            if len(data) < MIN_COMPRESS_BYTES:
                return None
            packed = codec.compress(data)
            if len(packed) >= len(data) * MAX_COMPRESS_RATIO:
                return None
            return packed

        payload = await asyncio.to_thread(_maybe_compress)
    if payload is not None:
        codec_name = codec.name
        tmp_remote = f"{remote_path}.z.tmp-{uuid.uuid4().hex[:8]}"
        fd, tmp_local = tempfile.mkstemp(prefix="covalent-tpu-wire-")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(payload)
            await conn.put(tmp_local, tmp_remote)
        finally:
            try:
                os.unlink(tmp_local)
            except OSError:
                pass
        cmd = (
            f"{_helper_python(python_path, codec_name)} "
            f"-c {shlex.quote(_PUBLISH_PROGRAM)} "
            f"{shlex.quote(tmp_remote)} {shlex.quote(remote_path)} "
            f"{codec_name} {digest or '-'}"
        )
        _check_exec(await conn.run(cmd), f"publish of {remote_path}")
        record_wire("up", codec_name, len(payload))
        return {"ops": 2, "wire_bytes": len(payload), "codec": codec_name}
    # Raw: temp name + atomic rename (readers never see a torn artifact).
    tmp_remote = f"{remote_path}.tmp-{uuid.uuid4().hex[:8]}"
    await conn.put(local_path, tmp_remote)
    await conn.rename(tmp_remote, remote_path)
    size = os.path.getsize(local_path)
    record_wire("up", "raw", size)
    return {"ops": 2, "wire_bytes": size, "codec": "raw"}


async def get_file(
    conn: "Transport",
    remote_path: str,
    local_path: str,
    *,
    codec: Codec | None = None,
    python_path: str = "python3",
) -> dict:
    """Fetch one file, compressed on the wire when profitable.

    Costs one extra round trip (the remote pack exec), so callers engage
    it only when the operator pinned a codec — the remote side still
    ships raw (``RAW`` token) when the file is too small to win.
    """
    if codec is None:
        await conn.get(remote_path, local_path)
        try:
            size = os.path.getsize(local_path)
        except OSError:
            size = 0
        record_wire("down", "raw", size)
        return {"ops": 1, "wire_bytes": size, "codec": "raw"}
    tmp_remote = f"{remote_path}.z"
    cmd = (
        f"{_helper_python(python_path, codec.name)} "
        f"-c {shlex.quote(_PACK_PROGRAM)} "
        f"{shlex.quote(remote_path)} {shlex.quote(tmp_remote)} "
        f"{MIN_COMPRESS_BYTES} {codec.name}"
    )
    result = _check_exec(await conn.run(cmd), f"pack of {remote_path}")
    token = result.stdout.strip().splitlines()[-1] if result.stdout.strip() else ""
    if token.startswith("Z "):
        await conn.get(tmp_remote, local_path)
        packed = open(local_path, "rb").read()
        data = await asyncio.to_thread(codec.decompress, packed)
        with open(local_path, "wb") as f:
            f.write(data)
        record_wire("down", codec.name, len(packed))
        return {"ops": 2, "wire_bytes": len(packed), "codec": codec.name}
    await conn.get(remote_path, local_path)
    try:
        size = os.path.getsize(local_path)
    except OSError:
        size = 0
    record_wire("down", "raw", size)
    return {"ops": 2, "wire_bytes": size, "codec": "raw"}
