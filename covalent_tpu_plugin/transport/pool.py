"""Connection pooling across electrons.

The reference opens a fresh SSH connection per ``run()`` call
(``covalent_ssh_plugin/ssh.py:497``) and closes it at the end
(``ssh.py:585-587``) — with the handshake alone eating a large slice of the
<2 s overhead budget and the connection leaking on the exception path
(``ssh.py:581-583``).  The pool amortises the handshake across all electrons
of a lattice: transports are keyed by address, handed out shared, and closed
once at executor teardown (or via the async context manager).
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable

from ..obs import events as obs_events
from ..obs.metrics import REGISTRY
from ..obs.trace import Span
from .base import Transport

_POOL_ACQUIRES = REGISTRY.counter(
    "covalent_tpu_pool_acquires_total",
    "Transport pool lookups by result (hit = reused channel, "
    "miss = fresh dial)",
    ("result",),
)
_POOL_SIZE = REGISTRY.gauge(
    "covalent_tpu_pool_size",
    "Live transports currently held by pools in this process",
)


class TransportPool:
    """Keyed cache of live transports with single-flight connection setup."""

    def __init__(self) -> None:
        self._transports: dict[str, Transport] = {}
        self._locks: dict[str, asyncio.Lock] = {}
        self._guard = asyncio.Lock()

    async def acquire(
        self,
        key: str,
        factory: Callable[[], Awaitable[Transport]],
        gate=None,
    ) -> Transport:
        """Return the pooled transport for ``key``, creating it via
        ``factory`` exactly once even under concurrent electron fan-out.

        ``gate`` is an optional circuit breaker (duck-typed: ``check()`` /
        ``record_success()`` / ``record_failure()``, see resilience.py)
        consulted *before* a fresh dial: a quarantined host fails fast with
        ``CircuitOpenError`` instead of burning the full connect-retry
        envelope.  A pooled hit bypasses the gate — an already-live channel
        is itself evidence the host works (a broken one gets discarded, and
        its redial is gated).
        """
        async with self._guard:
            lock = self._locks.setdefault(key, asyncio.Lock())
        async with lock:
            transport = self._transports.get(key)
            if transport is not None:
                _POOL_ACQUIRES.labels(result="hit").inc()
                return transport
            if gate is not None:
                gate.check()
            _POOL_ACQUIRES.labels(result="miss").inc()
            # The span surfaces what pooling saves: its histogram is the
            # per-dial handshake cost that hits only on a miss.
            try:
                with Span("pool.connect", {"key": key}):
                    transport = await factory()
            except BaseException:
                if gate is not None:
                    gate.record_failure()
                raise
            if gate is not None:
                gate.record_success()
            self._transports[key] = transport
            _POOL_SIZE.inc()
            return transport

    async def discard(self, key: str, only=None) -> bool:
        """Drop (and close) a broken transport so the next acquire redials.

        ``only`` (an iterable of transports) scopes the discard to the
        channels the caller actually observed failing: under concurrent
        fan-out, electron A's teardown must not close the FRESH channel
        electron B just redialed under the same key — that cascade turns
        one injected fault into N spurious launch failures.  Returns
        whether a transport was discarded.
        """
        transport = self._transports.get(key)
        if transport is None:
            return False
        if only is not None and not any(transport is t for t in only):
            return False
        self._transports.pop(key, None)
        _POOL_SIZE.dec()
        obs_events.emit("pool.discard", key=key)
        await transport.close()
        return True

    async def close_all(self) -> None:
        transports = list(self._transports.values())
        self._transports.clear()
        _POOL_SIZE.dec(len(transports))
        await asyncio.gather(*(t.close() for t in transports), return_exceptions=True)

    def has(self, key: str) -> bool:
        """Whether a live transport is currently pooled under ``key``."""
        return key in self._transports

    def __len__(self) -> int:
        return len(self._transports)

    async def __aenter__(self) -> "TransportPool":
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close_all()
