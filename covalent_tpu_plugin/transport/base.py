"""Transport interface.

Distills the three operations the reference performs over its connection
object — ``conn.run(cmd)`` (``covalent_ssh_plugin/ssh.py:383``),
``asyncssh.scp(local, (conn, remote))`` upload (``ssh.py:360-361``), and
``asyncssh.scp((conn, remote), local)`` download (``ssh.py:451``) — into an
abstract base class every backend implements.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from dataclasses import dataclass


class TransportError(RuntimeError):
    """Raised for connection/copy/exec failures on the control plane."""


@dataclass
class CommandResult:
    """Shape-compatible stand-in for asyncssh's ``SSHCompletedProcess``.

    The executor reads ``exit_status``/``stdout``/``stderr`` exactly as the
    reference does (``ssh.py:383-386``, ``ssh.py:402-406``, ``ssh.py:553-555``).
    """

    exit_status: int
    stdout: str
    stderr: str

    @property
    def returncode(self) -> int:
        return self.exit_status


class Transport(ABC):
    """One control-plane channel to one worker host."""

    #: Human-readable address for logs ("user@host" or "localhost").
    address: str = "?"

    #: True when bytes never cross a wire (shared filesystem): the codec
    #: layer (transport/codec.py) skips compression for such backends —
    #: compressing a local copy burns CPU to save bytes that were free.
    zero_wire: bool = False

    @abstractmethod
    async def run(self, command: str, timeout: float | None = None) -> CommandResult:
        """Execute a shell command on the worker and capture its output."""

    @abstractmethod
    async def put(self, local_path: str, remote_path: str) -> None:
        """Copy a file from the dispatcher to the worker."""

    @abstractmethod
    async def get(self, remote_path: str, local_path: str) -> None:
        """Copy a file from the worker back to the dispatcher."""

    @abstractmethod
    async def close(self) -> None:
        """Release the channel (idempotent)."""

    async def exists_batch(self, paths: list[str]) -> list[bool]:
        """Existence flags for ``paths`` in ONE control-plane round-trip.

        Seeds the content-addressed staging cache (cache.py): probing N
        digest paths individually would cost N round-trips — the exact
        per-electron overhead the CAS exists to remove.  Default rides one
        compound ``test -e`` command; backends with direct filesystem
        access override it.  Unparseable probe output degrades to
        all-absent (a spurious re-upload, never a spurious skip).
        """
        import shlex

        if not paths:
            return []
        probe = "; ".join(
            f"test -e {shlex.quote(p)} && echo 1 || echo 0" for p in paths
        )
        result = await self.run(probe)
        tokens = [
            line.strip()
            for line in result.stdout.splitlines()
            if line.strip() in ("0", "1")
        ]
        if result.exit_status != 0 or len(tokens) != len(paths):
            return [False] * len(paths)
        return [token == "1" for token in tokens]

    async def rename(self, src: str, dst: str) -> None:
        """Atomically move a worker-side file (CAS publish step).

        Content-addressed uploads land under a temp name first, then rename
        into the digest path — readers (including other executors' batched
        existence probes) can never observe a half-written artifact.
        """
        import shlex

        result = await self.run(
            f"mv -f {shlex.quote(src)} {shlex.quote(dst)}"
        )
        if result.exit_status != 0:
            raise TransportError(
                f"rename {src} -> {dst} failed: {result.stderr.strip()}"
            )

    async def remove(self, paths: list[str]) -> CommandResult:
        """Best-effort delete of worker-side files (cleanup hot path).

        Default rides ``run("rm -f ...")`` — one round-trip on remote
        backends, matching the reference's cleanup (ssh.py:313-315).
        Backends with direct filesystem access override this to skip the
        shell entirely (a ``/bin/sh`` spawn costs ~3 ms per electron).
        """
        import shlex

        return await self.run("rm -f " + " ".join(shlex.quote(p) for p in paths))

    async def put_bundle(
        self,
        items: "list[tuple[str, str, str]]",
        bundle_path: str,
        python_path: str = "python3",
        codec=None,
    ) -> dict:
        """Ship many files in ONE upload + ONE remote exec.

        ``items`` is ``[(local_path, remote_path, sha256_digest)]`` (empty
        digest skips verification for that member).  The default packs a
        (codec-compressed when profitable) tar, ``put``s it to
        ``bundle_path``, and unpacks it remotely with a single
        ``python -c`` exec that verifies each member's digest against the
        *decompressed* bytes and publishes it atomically — so a fan-out's
        N per-worker spec round trips collapse to 2, and a torn bundle
        raises :class:`~.codec.CodecIntegrityError` (permanent) instead
        of launching against corrupt artifacts.  Backends with direct
        filesystem access override this to skip the tar entirely;
        fault-injection wrappers inherit it so their ``put``/``run``
        faults apply to the bundle exactly as to any other transfer.
        """
        import asyncio
        import os
        import tempfile

        from . import codec as codec_mod

        payload, codec_name = await asyncio.to_thread(
            codec_mod.build_bundle, items, codec
        )
        fd, tmp_local = tempfile.mkstemp(prefix="covalent-tpu-bundle-")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(payload)
            await self.put(tmp_local, bundle_path)
        finally:
            try:
                os.unlink(tmp_local)
            except OSError:
                pass
        codec_mod._check_exec(
            await self.run(
                codec_mod.unpack_command(python_path, bundle_path, codec_name)
            ),
            f"bundle unpack of {bundle_path}",
        )
        codec_mod.record_wire("up", codec_name, len(payload))
        return {
            "ops": 2,
            "wire_bytes": len(payload),
            "codec": codec_name,
            "members": len(items),
        }

    async def start_process(self, command: str, describe: str = ""):
        """Start a long-lived remote process with piped stdin/stdout.

        Returns a :class:`~.process.TransportProcess`.  Optional: backends
        that cannot hold a persistent channel raise, and callers fall back
        to the one-shot ``run()`` protocol.
        """
        raise TransportError(
            f"{type(self).__name__} does not support persistent processes"
        )

    async def __aenter__(self) -> "Transport":
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()
