"""Local (subprocess) transport.

The reference's only degraded mode is running the electron in-process on the
dispatcher (``covalent_ssh_plugin/ssh.py:202-204``).  This backend is
stronger: it drives the *full* stage/submit/poll/fetch lifecycle through a
local subprocess, so the entire executor path is exercised end-to-end with no
sshd — the localhost tier of the test strategy (SURVEY §4.2b) and BASELINE
config 1.
"""

from __future__ import annotations

import asyncio
import os
import shutil

from .base import CommandResult, Transport, TransportError


class LocalTransport(Transport):
    """Runs commands via ``asyncio.create_subprocess_shell`` and copies files
    with ``shutil`` on the dispatcher host itself."""

    #: Shared filesystem: nothing crosses a wire, so the codec layer
    #: ships raw (compressing a local copy is pure overhead).
    zero_wire = True

    def __init__(self) -> None:
        self.address = "localhost"
        self._closed = False

    async def run(self, command: str, timeout: float | None = None) -> CommandResult:
        if self._closed:
            raise TransportError("transport is closed")
        proc = await asyncio.create_subprocess_shell(
            command,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE,
        )
        try:
            stdout, stderr = await asyncio.wait_for(proc.communicate(), timeout)
        except asyncio.TimeoutError:
            proc.kill()
            await proc.wait()
            raise TransportError(f"command timed out after {timeout}s: {command!r}")
        return CommandResult(
            exit_status=proc.returncode if proc.returncode is not None else -1,
            stdout=stdout.decode(errors="replace"),
            stderr=stderr.decode(errors="replace"),
        )

    async def start_process(self, command: str, describe: str = ""):
        if self._closed:
            raise TransportError("transport is closed")
        from .process import start_local_process

        # `exec` so the handle we keep (and can kill) IS the target process,
        # not a lingering shell wrapper holding the pipes open.
        return await start_local_process(
            ["/bin/sh", "-c", f"exec {command}"],
            describe or f"local:{command.split()[0]}",
        )

    async def exists_batch(self, paths: list[str]) -> list[bool]:
        """Direct stat batch — no shell spawn on the CAS probe path."""
        return await asyncio.to_thread(
            lambda: [os.path.exists(p) for p in paths]
        )

    async def rename(self, src: str, dst: str) -> None:
        """Direct atomic replace — no shell spawn on the CAS publish path."""
        try:
            await asyncio.to_thread(os.replace, src, dst)
        except OSError as err:
            raise TransportError(f"rename {src} -> {dst} failed: {err}")

    async def remove(self, paths: list[str]) -> CommandResult:
        """Direct unlink — no shell spawn on the cleanup hot path.

        Mirrors ``rm -f``: missing files are fine, other per-path failures
        (permissions, a directory) don't stop the batch and surface as a
        nonzero exit + stderr so the caller's warning path fires.
        """

        def unlink_all() -> list[str]:
            errors = []
            for path in paths:
                try:
                    os.remove(path)
                except FileNotFoundError:
                    pass
                except OSError as err:
                    errors.append(f"{path}: {err}")
            return errors

        errors = await asyncio.to_thread(unlink_all)
        return CommandResult(
            exit_status=1 if errors else 0, stdout="", stderr="; ".join(errors)
        )

    async def put_bundle(
        self, items, bundle_path, python_path="python3", codec=None
    ) -> dict:
        """Direct atomic copies in one thread hop — no tar, no subprocess.

        The generic bundle exists to collapse *round trips*; on a shared
        filesystem a round trip is a function call, so the fast path is
        plain copy + replace per member (still atomic: a concurrent
        reader never sees a torn artifact).
        """
        from . import codec as codec_mod

        def copy_all() -> int:
            total = 0
            for local, remote, _digest in items:
                parent = os.path.dirname(remote)
                if parent:
                    os.makedirs(parent, exist_ok=True)
                # Unique per call, not per pid: two gang members staging
                # the same CAS digest concurrently from one dispatcher
                # process must not share a tmp name (the first replace
                # deletes it out from under the second copy).
                tmp = f"{remote}.tmp-{os.getpid()}-{os.urandom(4).hex()}"
                shutil.copyfile(local, tmp)
                os.replace(tmp, remote)
                total += os.path.getsize(remote)
            return total

        size = await asyncio.to_thread(copy_all)
        codec_mod.record_wire("up", "raw", size)
        return {
            "ops": 1, "wire_bytes": size, "codec": "raw",
            "members": len(items),
        }

    async def put(self, local_path: str, remote_path: str) -> None:
        if local_path != remote_path:
            await asyncio.to_thread(shutil.copyfile, local_path, remote_path)

    async def get(self, remote_path: str, local_path: str) -> None:
        if local_path != remote_path:
            await asyncio.to_thread(shutil.copyfile, remote_path, local_path)

    async def close(self) -> None:
        self._closed = True
